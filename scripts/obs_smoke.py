"""Fast CPU-only observability smoke (scripts/check.sh, both modes + CI).

Proves, on a 2-node in-process cluster in seconds, the self-observability
plane's end-to-end invariants (docs/observability.md):

1. a trace=true distributed measure query returns ONE merged span tree
   containing >= 2 per-node subtrees, each with nonzero device_ms /
   host_ms attribution and cache hit/miss tags;
2. tracing off returns byte-identical results (JSON form) to tracing on;
3. /metrics exposition carries bucketed (`_bucket`) latency histograms
   for at least the gather, device_execute and merge stages, and the
   scraped stage_breakdown (obs/prom.py) recovers nonzero quantiles;
4. the kernel audit's STATIC dispatch budget (lint/kernel/
   kernel_budgets.py, exported as `kernel_dispatch_budget` gauges)
   bounds the OBSERVED `device_execute` span count for the traced query
   — the measured plane and the predicted plane agree, which is the
   ratchet the fused whole-plan executor (ROADMAP item 2) tightens;
5. the fused whole-plan executor costs EXACTLY 1 device_execute
   dispatch per part-batch (reduce-span `path`/`dispatches` tags), and
   `BYDB_FUSED=0` restores the staged loop with byte-identical results.

Exit 0 on success; any assertion prints a diagnostic and exits 1.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# runnable as `python scripts/obs_smoke.py` from the repo root or CI
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = 1_700_000_000_000


def main() -> int:
    from pathlib import Path

    from banyandb_tpu.api import (
        Catalog,
        DataPointValue,
        Entity,
        FieldSpec,
        FieldType,
        Group,
        GroupBy,
        Measure,
        QueryRequest,
        ResourceOpts,
        SchemaRegistry,
        TagSpec,
        TagType,
        TimeRange,
        WriteRequest,
    )
    from banyandb_tpu.api.model import Aggregation
    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo
    from banyandb_tpu.cluster.rpc import LocalTransport
    from banyandb_tpu.obs import find_span, global_meter
    from banyandb_tpu.obs import prom as obs_prom
    from banyandb_tpu.obs.tracer import iter_spans
    from banyandb_tpu.server import result_to_json

    root = Path(tempfile.mkdtemp(prefix="bydb-obs-smoke-"))

    def schema(reg):
        reg.create_group(
            Group("g", Catalog.MEASURE, ResourceOpts(shard_num=4))
        )
        # INT field: sum aggregates ride the DEVICE kernel path (floats
        # take the exact-f64 host path, which has no device leg to time)
        reg.create_measure(
            Measure(
                group="g", name="m",
                tags=(TagSpec("svc", TagType.STRING),),
                fields=(FieldSpec("v", FieldType.INT),),
                entity=Entity(("svc",)),
            )
        )

    transport = LocalTransport()
    nodes, datanodes = [], []
    for i in range(2):
        reg = SchemaRegistry(root / f"node{i}")
        schema(reg)
        dn = DataNode(f"data-{i}", reg, root / f"node{i}" / "data")
        nodes.append(NodeInfo(dn.name, transport.register(dn.name, dn.bus)))
        datanodes.append(dn)
    liaison_reg = SchemaRegistry(root / "liaison")
    schema(liaison_reg)
    liaison = Liaison(liaison_reg, transport, nodes)

    points = tuple(
        DataPointValue(
            T0 + i, {"svc": f"svc-{i % 16}"}, {"v": (i * 7) % 100}, version=1
        )
        for i in range(4000)
    )
    liaison.write_measure(WriteRequest("g", "m", points))
    for dn in datanodes:
        dn.measure.flush()

    req = QueryRequest(
        ("g",), "m", TimeRange(T0, T0 + 10_000),
        group_by=GroupBy(("svc",)), agg=Aggregation("sum", "v"),
        trace=True, limit=100,
    )
    from banyandb_tpu.obs import metrics as obs_metrics

    h_device = obs_metrics.stage_histogram("device_execute")
    device_spans_before = h_device.snapshot()[0]
    res = liaison.query_measure(req)
    device_spans = h_device.snapshot()[0] - device_spans_before
    tree = (res.trace or {}).get("span_tree")
    assert tree, "trace=true must attach a merged span_tree"

    # -- 1: merged tree with per-node subtrees + attribution tags ---------
    subtrees = [
        s for s in iter_spans(tree) if str(s.get("name", "")).startswith("data:")
    ]
    assert len(subtrees) >= 2, (
        f"expected >= 2 node subtrees, got {[s['name'] for s in subtrees]}"
    )
    for st in subtrees:
        reduce_span = find_span(st, "reduce")
        assert reduce_span is not None, f"{st['name']}: no reduce span"
        tags = reduce_span["tags"]
        assert tags.get("device_ms", 0) > 0, f"{st['name']}: device_ms {tags}"
        assert tags.get("host_ms", 0) > 0, f"{st['name']}: host_ms {tags}"
        assert "partials_cache" in tags, f"{st['name']}: cache tag {tags}"
        gather_span = find_span(st, "gather")
        assert gather_span is not None and "serving_cache" in gather_span["tags"], (
            f"{st['name']}: gather cache tag missing"
        )
    assert find_span(tree, "merge") is not None, "liaison merge span missing"
    print(
        f"# merged tree: {len(subtrees)} node subtrees, "
        f"root {tree['duration_ms']}ms"
    )

    # -- 2: byte-identical results, tracing on vs off ----------------------
    import dataclasses
    import json

    res_off = liaison.query_measure(dataclasses.replace(req, trace=False))
    j_on = result_to_json(res)
    j_on.pop("trace", None)
    j_off = result_to_json(res_off)
    j_off.pop("trace", None)
    b_on, b_off = json.dumps(j_on, sort_keys=True), json.dumps(j_off, sort_keys=True)
    assert b_on == b_off, "results differ with tracing on vs off"
    print(f"# parity: {len(b_on)} result bytes identical with trace on/off")

    # -- 3: bucketed stage histograms on the exposition --------------------
    text = global_meter().prometheus_text()
    for stage in ("gather", "device_execute", "merge"):
        needle = f'banyandb_query_stage_ms_bucket{{stage="{stage}"'
        assert needle in text, f"no _bucket series for stage {stage}"
    breakdown = obs_prom.stage_breakdown(text)
    for stage in ("gather", "device_execute", "merge"):
        rec = breakdown.get(stage)
        assert rec and rec["count"] > 0, f"stage_breakdown missing {stage}"
        assert rec["p50_ms"] > 0, f"{stage} p50 is zero: {rec}"
    print(f"# stage_breakdown: {breakdown}")

    # -- 4: static dispatch budget >= observed device_execute spans --------
    # The kernel audit PREDICTS at most dispatch_budget("measure") device
    # legs per part-batch; each node's reduce is one part-batch, so the
    # observed span count for the traced query is bounded by
    # budget x part-batches.  A fused executor landing with a tighter
    # budget tightens this same assertion for free.
    from banyandb_tpu.lint.kernel import kernel_budgets

    published = kernel_budgets.publish_to_meter()
    assert published > 0, "no dispatch budgets published to the meter"
    text = global_meter().prometheus_text()
    assert 'kernel_dispatch_budget{signature="measure/' in text, (
        "kernel_dispatch_budget gauges missing from the exposition"
    )
    budget = kernel_budgets.dispatch_budget("measure")
    part_batches = len(subtrees)
    assert 0 < device_spans <= budget * part_batches, (
        f"observed device_execute spans ({device_spans}) exceed the static "
        f"dispatch budget ({budget}/part-batch x {part_batches} part-"
        "batches) — the kernel audit's prediction no longer bounds the "
        "measured plane"
    )
    print(
        f"# dispatch budget: {device_spans} observed device spans <= "
        f"{budget}/part-batch x {part_batches} part-batches (static)"
    )

    # -- 5: fused whole-plan executor: 1 dispatch per part-batch -----------
    # The default (fused) query must show EXACTLY one device_execute
    # dispatch per part-batch on every node's reduce span, and flipping
    # BYDB_FUSED=0 (the staged per-chunk loop) must return byte-identical
    # results — the A/B contract of docs/performance.md "Fused whole-plan
    # executor".
    for st in subtrees:
        tags = find_span(st, "reduce")["tags"]
        assert tags.get("path") == "fused", f"{st['name']}: path tag {tags}"
        assert tags.get("dispatches") == 1, (
            f"{st['name']}: fused part-batch cost {tags.get('dispatches')} "
            f"device_execute dispatches, want exactly 1 {tags}"
        )
    from banyandb_tpu.storage.cache import device_cache, global_cache

    os.environ["BYDB_FUSED"] = "0"
    try:
        # bust the serving/device caches so the staged run recomputes
        # instead of replaying the fused run's cached partials
        global_cache().clear()
        device_cache().clear()
        res_staged = liaison.query_measure(req)
    finally:
        os.environ.pop("BYDB_FUSED", None)
    j_staged = result_to_json(res_staged)
    j_staged.pop("trace", None)
    assert json.dumps(j_staged, sort_keys=True) == b_on, (
        "staged (BYDB_FUSED=0) results differ from the fused path"
    )
    staged_tree = (res_staged.trace or {}).get("span_tree")
    staged_reduce = [
        find_span(s, "reduce")["tags"]
        for s in iter_spans(staged_tree)
        if str(s.get("name", "")).startswith("data:")
    ]
    assert staged_reduce and all(
        t.get("path") == "staged" for t in staged_reduce
    ), f"BYDB_FUSED=0 did not restore the staged path: {staged_reduce}"
    print(
        f"# fused A/B: 1 dispatch/part-batch on {len(subtrees)} nodes, "
        "staged flip byte-identical"
    )

    # -- 6: multi-process data plane graft (docs/performance.md) ----------
    # a BYDB_WORKERS=2 standalone server produces ONE merged tree whose
    # scatter legs carry grafted worker subtrees, and the merged
    # /metrics exposition carries worker-labeled stage histograms that
    # the shared scraper aggregates across workers
    _worker_graft_smoke()
    print("obs_smoke: OK")
    return 0


def _worker_graft_smoke() -> None:
    import json as _json

    from banyandb_tpu.api import (
        Aggregation,
        Catalog,
        Entity,
        FieldSpec,
        FieldType,
        Group,
        GroupBy,
        Measure,
        QueryRequest,
        ResourceOpts,
        TagSpec,
        TagType,
        TimeRange,
    )
    from banyandb_tpu.cluster import serde
    from banyandb_tpu.cluster.bus import Topic
    from banyandb_tpu.obs import prom as obs_prom
    from banyandb_tpu.server import StandaloneServer

    tmp = tempfile.mkdtemp(prefix="bydb-obs-workers-")
    srv = StandaloneServer(tmp, port=0, workers=2)
    try:
        srv.start()
        srv.registry.create_group(
            Group("wg", Catalog.MEASURE, ResourceOpts(shard_num=4))
        )
        srv.registry.create_measure(
            Measure(
                group="wg", name="m",
                tags=(TagSpec("svc", TagType.STRING),),
                fields=(FieldSpec("v", FieldType.FLOAT),),
                entity=Entity(("svc",)),
            )
        )
        pts = [
            {"ts": T0 + i, "tags": {"svc": f"s{i % 6}"},
             "fields": {"v": float(i % 9)}, "version": 1}
            for i in range(300)
        ]
        srv.bus.handle(
            Topic.MEASURE_WRITE.value,
            {"request": {"group": "wg", "name": "m", "points": pts}},
        )
        req = QueryRequest(
            ("wg",), "m", TimeRange(T0, T0 + 10_000),
            group_by=GroupBy(("svc",)), agg=Aggregation("sum", "v"),
            trace=True, limit=100,
        )
        res = srv.bus.handle(
            Topic.MEASURE_QUERY_RAW.value,
            {"request": serde.query_request_to_json(req)},
        )["result"]
        tree = res["trace"]["span_tree"]

        def find_all(node, pred, out):
            if isinstance(node, dict):
                if pred(node):
                    out.append(node)
                for c in node.get("children", ()) or ():
                    find_all(c, pred, out)
            return out

        legs = find_all(
            tree, lambda n: str(n.get("name", "")).startswith("scatter:w"), []
        )
        assert len(legs) >= 2, (
            f"worker scatter legs missing: {_json.dumps(tree)[:300]}"
        )
        for leg in legs:
            sub = find_all(
                leg, lambda n: str(n.get("name", "")).startswith("data:w"), []
            )
            assert sub, f"scatter leg {leg.get('name')} has no grafted subtree"
            assert find_all(sub[0], lambda n: n.get("name") == "reduce", []), (
                f"{leg.get('name')}: grafted subtree carries no reduce span"
            )
        text = srv.bus.handle("metrics", {})["prometheus"]
        assert 'worker="w000"' in text and 'worker="w001"' in text
        assert "banyandb_worker" in text or "banyandb_workers_alive" in text
        stages = obs_prom.stage_breakdown(text)
        assert stages.get("gather", {}).get("count", 0) > 0, (
            f"scraper lost worker-labeled stage series: {sorted(stages)}"
        )
        print(
            f"# worker graft: {len(legs)} scatter legs with data:w* "
            "subtrees, worker-labeled stage histograms scraped"
        )
    finally:
        srv.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except AssertionError as e:
        print(f"obs_smoke: FAILED: {e}", file=sys.stderr)
        raise SystemExit(1) from e
