"""Fast CPU-only device-decode + zone-map smoke (scripts/check.sh, both
modes + CI).

Proves, in seconds on a REAL multi-block on-disk part, the device-side
decode contract (docs/performance.md "Device-side decode & zone maps"):

1. ``BYDB_DEVICE_DECODE=1`` (compressed ship: narrow codes + remap LUTs
   + narrow int fields, decoded on device inside the plan kernel) is
   byte-identical to ``=0`` on partials bytes AND result JSON, on a
   part-backed multi-block source — in BOTH fused and staged modes;
2. the compressed form ships strictly fewer bytes than the dense form
   (the decode span's shipped/dense counters, and the
   ``decode_ship_bytes_total`` meter counters);
3. zone-map block skipping: a selective eq predicate over the same part
   skips >= 1 block (``blocks_skipped_total{reason=zone}`` grows) with
   results identical to a ``BYDB_ZONE_SKIP=0`` full scan;
4. a ``decode`` span rides the reduce tree and the ``fused+decode/*``
   kernel-budget rows agree with the runtime (1 dispatch/part-batch).

Exit 0 on success; any assertion prints a diagnostic and exits 1.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BYDB_PRECOMPILE", "0")

# runnable as `python scripts/decode_smoke.py` from the repo root or CI
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = 1_700_000_000_000


def _partial_bytes(p) -> bytes:
    return p.content_bytes()  # the shared parity oracle (Partials)


def _span_named(tree: dict, name: str):
    if tree.get("name") == name:
        return tree
    for c in tree.get("children", ()):
        hit = _span_named(c, name)
        if hit is not None:
            return hit
    return None


def main() -> int:
    import numpy as np

    from banyandb_tpu.api.model import (
        Aggregation,
        Condition,
        GroupBy,
        QueryRequest,
        TimeRange,
    )
    from banyandb_tpu.api.schema import (
        Entity,
        FieldSpec,
        FieldType,
        Measure,
        TagSpec,
        TagType,
    )
    from banyandb_tpu.obs.metrics import global_meter
    from banyandb_tpu.obs.tracer import Tracer
    from banyandb_tpu.query.measure_exec import (
        compute_partials,
        finalize_partials,
    )
    from banyandb_tpu.server import result_to_json
    from banyandb_tpu.storage.part import Part, PartWriter

    n = 20_000  # 3 storage blocks (8192-row cap)
    rng = np.random.default_rng(23)
    m = Measure(
        group="g",
        name="m",
        tags=(TagSpec("svc", TagType.STRING),),
        fields=(FieldSpec("v", FieldType.INT),),
        entity=Entity(("svc",)),
    )
    # 'rare' appears ONLY in early rows -> only block 0's zone covers it
    codes = np.zeros(n, dtype=np.int32)
    codes[:64] = 1
    with tempfile.TemporaryDirectory() as root:
        part_dir = os.path.join(root, "part-1")
        PartWriter.write(
            part_dir,
            ts=T0 + np.arange(n, dtype=np.int64),
            series=np.zeros(n, dtype=np.int64),
            version=np.ones(n, dtype=np.int64),
            tag_codes={"svc": codes},
            tag_dicts={"svc": [b"common", b"rare"]},
            fields={"v": rng.integers(-100, 30_000, n).astype(np.float64)},
            extra_meta={"measure": "m"},
        )
        part = Part(part_dir)
        assert part.has_zone_maps(), "freshly written part must carry zones"
        assert len(part.blocks) == 3, len(part.blocks)

        req = QueryRequest(
            ("g",),
            "m",
            TimeRange(T0, T0 + n),
            group_by=GroupBy(("svc",)),
            agg=Aggregation("sum", "v"),
        )

        def run(decode: bool, fused: bool = True):
            os.environ["BYDB_DEVICE_DECODE"] = "1" if decode else "0"
            os.environ["BYDB_FUSED"] = "1" if fused else "0"
            blocks = part.select_blocks(T0, T0 + n)
            src = part.read(
                blocks, tags=["svc"], fields=["v"], narrow_codes=decode
            )
            tr = Tracer("decode-smoke")
            with tr.span("q") as sp:
                p = compute_partials(m, req, [src], span=sp)
            res = json.dumps(
                result_to_json(finalize_partials(m, req, [p])), sort_keys=True
            )
            return p, res, tr.finish()

        # 1. A/B parity, fused and staged
        p_dense, res_dense, _ = run(decode=False)
        for fused in (True, False):
            p_dec, res_dec, tree = run(decode=True, fused=fused)
            assert _partial_bytes(p_dec) == _partial_bytes(p_dense), (
                f"partials bytes diverged (fused={fused})"
            )
            assert res_dec == res_dense, f"result JSON diverged (fused={fused})"
        print("# parity: compressed == dense on partials bytes + result JSON")

        # 2. decode span + compression evidence
        dspan = _span_named(tree, "decode")
        assert dspan is not None, "no decode span in the reduce tree"
        tags = dspan["tags"]
        assert tags["mode"] == "device", tags
        shipped, dense = tags["shipped_bytes"], tags["dense_bytes"]
        assert 0 < shipped < dense, (shipped, dense)
        counters = global_meter().snapshot()["counters"]
        ship_c = counters.get(
            ("decode_ship_bytes", (("form", "shipped"),)), 0.0
        )
        dense_c = counters.get(("decode_ship_bytes", (("form", "dense"),)), 0.0)
        assert ship_c > 0 and dense_c > ship_c, (ship_c, dense_c)
        print(
            f"# decode span: shipped {shipped} vs dense {dense} bytes "
            f"(ratio {dense / shipped:.2f}x)"
        )

        # 3. zone-map skipping: selective eq -> >=1 block skipped, results
        # identical to the BYDB_ZONE_SKIP=0 full scan
        sel_req = QueryRequest(
            ("g",),
            "m",
            TimeRange(T0, T0 + n),
            criteria=Condition("svc", "eq", "rare"),
            agg=Aggregation("count", "v"),
        )
        lut = {v: i for i, v in enumerate(part.dict_for("svc"))}
        zone_preds = [("tag_svc", np.asarray([lut[b"rare"]], dtype=np.int64))]

        def count_result(blocks):
            src = part.read(blocks, tags=["svc"], fields=["v"])
            p = compute_partials(m, sel_req, [src])
            return json.dumps(
                result_to_json(finalize_partials(m, sel_req, [p])),
                sort_keys=True,
            )

        before = (
            global_meter()
            .snapshot()["counters"]
            .get(("blocks_skipped", (("reason", "zone"),)), 0.0)
        )
        pruned = part.select_blocks(T0, T0 + n, zone_preds=zone_preds)
        full = part.select_blocks(T0, T0 + n)
        after = (
            global_meter()
            .snapshot()["counters"]
            .get(("blocks_skipped", (("reason", "zone"),)), 0.0)
        )
        assert len(pruned) < len(full), (len(pruned), len(full))
        assert after > before, "blocks_skipped_total did not grow"
        assert count_result(pruned) == count_result(full), "zone skip changed results"
        print(
            f"# zone maps: {len(full) - len(pruned)} of {len(full)} blocks "
            f"skipped, results identical (blocks_skipped_total {after:.0f})"
        )

        # 4. budget agreement: the compressed ship form is ratcheted at
        # one dispatch per part-batch, and the runtime saw exactly that
        from banyandb_tpu.lint.kernel.kernel_budgets import BUDGETS

        rows = {k: v for k, v in BUDGETS.items() if k.startswith("fused+decode/")}
        assert len(rows) >= 5, sorted(rows)
        assert all(
            r.dispatches == 1 and r.gets == 1
            for r in rows.values()
            if r.dispatches is not None
        ), rows
        rspan = _span_named(tree, "reduce")
        assert rspan is not None and rspan["tags"]["dispatches"] == 1, rspan
        print(f"# budgets: {len(rows)} fused+decode rows, runtime dispatches=1")

    print("decode_smoke: OK")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"decode_smoke: FAIL: {e}", file=sys.stderr)
        sys.exit(1)
