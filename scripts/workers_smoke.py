"""Multi-process data plane smoke (docs/performance.md "Multi-process
data plane") — the check.sh gate for cluster/workers.py:

1. boots a standalone server with BYDB_WORKERS=2 beside a BYDB_WORKERS=0
   twin over identical writes (row + columnar envelopes) and asserts
   result JSON BYTE PARITY across aggregate / grouped / percentile /
   raw shapes;
2. asserts the scatter span graft: a traced worker-mode query carries
   one merged tree with per-worker ``scatter:<name>`` spans and worker
   ``data:<name>`` subtrees, and /metrics carries worker-labeled series;
3. SIGKILLs a worker mid-ingest and asserts restart + journal replay
   recovers every acked row with an explicit degraded window in between.

Exit 0 on success; any assertion prints a diagnostic and exits 1.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BYDB_WORKERS", "0")  # the harness passes workers=N

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

T0 = 1_700_000_000_000
HI = T0 + 1_000_000_000


def _schema(srv):
    from banyandb_tpu.api import (
        Catalog,
        Entity,
        FieldSpec,
        FieldType,
        Group,
        Measure,
        ResourceOpts,
        TagSpec,
        TagType,
    )

    srv.registry.create_group(
        Group("g", Catalog.MEASURE, ResourceOpts(shard_num=4))
    )
    srv.registry.create_measure(
        Measure(
            group="g",
            name="m",
            tags=(
                TagSpec("svc", TagType.STRING),
                TagSpec("region", TagType.STRING),
            ),
            fields=(FieldSpec("v", FieldType.FLOAT),),
            entity=Entity(("svc",)),
        )
    )


def _write(srv, base, n, rows=True):
    import base64

    import numpy as np

    from banyandb_tpu.cluster.bus import Topic

    if rows:
        pts = [
            {
                "ts": T0 + (base + i) * 10,
                "tags": {"svc": f"s{(base + i) % 5}", "region": f"r{i % 3}"},
                "fields": {"v": float((base + i) % 11)},
                "version": 1,
            }
            for i in range(n)
        ]
        r = srv.bus.handle(
            Topic.MEASURE_WRITE.value,
            {"request": {"group": "g", "name": "m", "points": pts}},
        )
    else:
        ts = (T0 + (base + np.arange(n)) * 10).astype("<i8")
        r = srv.bus.handle(
            Topic.MEASURE_WRITE_COLUMNS.value,
            {
                "group": "g",
                "name": "m",
                "ts": base64.b64encode(ts.tobytes()).decode(),
                "versions": base64.b64encode(
                    np.ones(n, dtype="<i8").tobytes()
                ).decode(),
                "tags": {
                    "svc": {
                        "dict": [f"s{i}" for i in range(5)],
                        "codes": base64.b64encode(
                            ((base + np.arange(n)) % 5)
                            .astype("<i4")
                            .tobytes()
                        ).decode(),
                    },
                    "region": [f"r{i % 3}" for i in range(n)],
                },
                "fields": {
                    "v": base64.b64encode(
                        ((base + np.arange(n)) % 11)
                        .astype("<f8")
                        .tobytes()
                    ).decode(),
                },
            },
        )
    assert r["written"] == n, r
    return n


QLS = [
    f"SELECT count(v) FROM MEASURE m IN g TIME BETWEEN {T0} AND {HI} GROUP BY svc",
    f"SELECT sum(v) FROM MEASURE m IN g TIME BETWEEN {T0} AND {HI} "
    f"WHERE region = 'r1' GROUP BY svc",
    f"SELECT percentile(v, 90) FROM MEASURE m IN g TIME BETWEEN {T0} AND {HI}",
    f"SELECT * FROM MEASURE m IN g TIME BETWEEN {T0} AND {HI} LIMIT 9 OFFSET 3",
]


def main() -> int:
    from banyandb_tpu.server import TOPIC_QL, TOPIC_SNAPSHOT, StandaloneServer

    tmp = tempfile.mkdtemp(prefix="bydb-workers-smoke-")

    def boot(workers, name):
        # 0 passes through verbatim: the parity baseline must pin the
        # single-process layout even when BYDB_WORKERS is exported
        srv = StandaloneServer(
            os.path.join(tmp, name), port=0, workers=workers
        )
        srv.start()
        _schema(srv)
        _write(srv, 0, 150, rows=True)
        _write(srv, 150, 150, rows=False)
        srv.bus.handle(TOPIC_SNAPSHOT, {})
        return srv

    srv0 = boot(0, "mode0")
    srv2 = boot(2, "mode2")
    try:
        # 1. scatter parity: byte-identical result JSON
        for ql in QLS:
            a = json.dumps(
                srv0.bus.handle(TOPIC_QL, {"ql": ql})["result"],
                sort_keys=True,
            )
            b = json.dumps(
                srv2.bus.handle(TOPIC_QL, {"ql": ql})["result"],
                sort_keys=True,
            )
            assert a == b, f"A/B divergence for {ql}:\n0: {a[:300]}\nN: {b[:300]}"
        print("parity: result JSON byte-identical across", len(QLS), "shapes")

        # 2. span graft: one merged tree, per-worker subtrees
        from banyandb_tpu.api import (
            Aggregation,
            GroupBy,
            QueryRequest,
            TimeRange,
        )
        from banyandb_tpu.cluster import serde
        from banyandb_tpu.cluster.bus import Topic

        req = QueryRequest(
            ("g",), "m", TimeRange(T0, HI),
            group_by=GroupBy(("svc",)), agg=Aggregation("sum", "v"),
            trace=True, limit=100,
        )
        traced = srv2.bus.handle(
            Topic.MEASURE_QUERY_RAW.value,
            {"request": serde.query_request_to_json(req)},
        )["result"]
        tree = traced["trace"]["span_tree"]

        def find_all(node, pred, out):
            if isinstance(node, dict):
                if pred(node):
                    out.append(node)
                for c in node.get("children", ()) or ():
                    find_all(c, pred, out)
            return out

        scatter = find_all(
            tree, lambda n: str(n.get("name", "")).startswith("scatter:w"), []
        )
        assert len(scatter) >= 2, f"expected >=2 worker scatter legs: {tree}"
        subtrees = find_all(
            tree, lambda n: str(n.get("name", "")).startswith("data:w"), []
        )
        assert len(subtrees) >= 2, "worker span subtrees not grafted"
        text = srv2.bus.handle("metrics", {})["prometheus"]
        assert 'worker="w000"' in text and 'worker="w001"' in text, (
            "per-worker metric labels missing from merged exposition"
        )
        print(
            f"graft: {len(scatter)} scatter legs, {len(subtrees)} worker "
            "subtrees, worker-labeled /metrics"
        )

        # 3. kill/restart: journal replay, explicit degraded window
        acked = 300
        srv2.pool.flush()
        acked += _write(srv2, 300, 80, rows=False)
        srv2.pool.kill_worker(0)
        acked += _write(srv2, 380, 40, rows=True)  # journal-spooled ack
        count_ql = (
            f"SELECT count(v) FROM MEASURE m IN g TIME BETWEEN {T0} AND {HI}"
        )
        saw_degraded = False
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            res = srv2.bus.handle(TOPIC_QL, {"ql": count_ql})["result"]
            total = int(sum(res["values"].get("count", [])))
            if res.get("degraded"):
                saw_degraded = True
                assert res["unavailable_nodes"] == ["w000"], res
            if not res.get("degraded") and total == acked:
                break
            time.sleep(0.2)
        res = srv2.bus.handle(TOPIC_QL, {"ql": count_ql})["result"]
        total = int(sum(res["values"].get("count", [])))
        assert total == acked and not res.get("degraded"), (
            f"acked-write loss across SIGKILL: {total} != {acked} "
            f"(degraded={res.get('degraded')})"
        )
        assert saw_degraded, "no explicit degraded marker during the outage"
        assert srv2.pool.restarts >= 1
        print(
            f"kill/restart: {acked} acked rows intact after SIGKILL+replay "
            f"(restarts={srv2.pool.restarts}, "
            f"window={round(time.monotonic() - t0, 1)}s)"
        )
        print("workers smoke: OK")
        return 0
    finally:
        srv2.stop()
        srv0.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    rc = main()
    # grpc's C++ teardown can abort on this kernel after success (the
    # chaos harness does the same); the asserts above already ran
    sys.stdout.flush()
    os._exit(rc)
