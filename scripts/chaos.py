"""Chaos harness: sustained write+query load under data-node kills and a
deterministic fault schedule (docs/robustness.md).

Modes:

  --smoke        ~5s, in-process: (A) liaison write-queue replay across
                 THREE data-node kill/restart cycles over the real
                 chunked-sync wire, (B) graceful query degradation with
                 explicit ``degraded`` / ``unavailable_nodes`` markers
                 and the per-query deadline bound, (C) a seeded
                 BYDB_FAULTS schedule (rpc/sync/disk boundaries) under
                 which ingest still converges with zero acked loss.
                 This is the tier-1 gate (tests/test_chaos.py,
                 scripts/check.sh both modes).

  --soak SECONDS real subprocess cluster (python -m banyandb_tpu.server
                 per role), SIGKILL kill/restart cycles under sustained
                 write+query load; one double-kill window forces
                 degraded responses.  The ``-m slow`` tier runs this.

Invariants asserted in both modes:

  1. zero acked-write loss — every acked row is queryable after
     recovery (acked = the write call returned success);
  2. no query runs past its deadline budget (+ scheduling slack);
  3. responses during partial outages carry explicit ``degraded`` +
     ``unavailable_nodes`` markers — partial must never look complete.

Usage:
    python scripts/chaos.py --smoke [--seed N]
    python scripts/chaos.py --soak 120 [--seed N] [--artifact out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

T0 = 1_700_000_000_000


# -- shared bits -------------------------------------------------------------


def _schema(reg, group="cg", shard_num=3):
    from banyandb_tpu.api import (
        Catalog,
        Entity,
        FieldSpec,
        FieldType,
        Group,
        Measure,
        ResourceOpts,
        TagSpec,
        TagType,
    )

    reg.create_group(Group(group, Catalog.MEASURE, ResourceOpts(shard_num=shard_num)))
    reg.create_measure(
        Measure(
            group=group, name="m",
            tags=(TagSpec("svc", TagType.STRING),),
            fields=(FieldSpec("v", FieldType.FLOAT),),
            entity=Entity(("svc",)),
        )
    )


def _points(base: int, n: int, mod: int = 8):
    from banyandb_tpu.api import DataPointValue

    return tuple(
        DataPointValue(
            ts_millis=T0 + base + i,
            tags={"svc": f"s{(base + i) % mod}"},
            fields={"v": 1.0},
            version=1,
        )
        for i in range(n)
    )


def _count_req(trace=False):
    from banyandb_tpu.api import (
        Aggregation,
        GroupBy,
        QueryRequest,
        TimeRange,
    )

    return QueryRequest(
        groups=("cg",), name="m",
        time_range=TimeRange(T0, T0 + 50_000_000),
        group_by=GroupBy(("svc",)),
        agg=Aggregation("count", "v"),
        trace=trace,
    )


def _total(res) -> int:
    return int(sum(res.values.get("count", [])))


def _bind_server(bus, port, sync_install=None, attempts=40):
    """GrpcBusServer on a FIXED port, retrying while the previous
    incarnation's socket drains (restart-on-same-port, the address every
    cached liaison channel and discovery entry still points at)."""
    from banyandb_tpu.cluster.rpc import GrpcBusServer

    for i in range(attempts):
        srv = GrpcBusServer(bus, port=port, sync_install=sync_install)
        if srv.port == port or port == 0:
            srv.start()
            return srv
        srv.stop(grace=0)
        time.sleep(0.1)
    raise RuntimeError(f"could not rebind port {port}")


# -- smoke scenario A: wqueue replay across kill/restart cycles --------------


def _smoke_wqueue_cycles(tmp, budget_s: float, stats: dict) -> None:
    from banyandb_tpu.api import SchemaRegistry, WriteRequest
    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo
    from banyandb_tpu.cluster.rpc import GrpcTransport

    nodes, servers, ports = [], {}, {}
    for i in range(2):
        reg = SchemaRegistry(tmp / f"a-n{i}" / "schema")
        _schema(reg, shard_num=2)
        dn = DataNode(f"n{i}", reg, tmp / f"a-n{i}" / "data")
        srv = _bind_server(dn.bus, 0, sync_install=dn.install_synced_parts)
        servers[f"n{i}"] = (dn, srv)
        ports[f"n{i}"] = srv.port
        nodes.append(NodeInfo(f"n{i}", srv.addr))

    lreg = SchemaRegistry(tmp / "a-liaison" / "schema")
    _schema(lreg, shard_num=2)
    transport = GrpcTransport()
    liaison = Liaison(
        lreg, transport, nodes, replicas=1, query_budget_s=budget_s
    )
    liaison.probe()
    wq = liaison.enable_write_queue(
        tmp / "a-liaison" / "wqueue", flush_interval_s=30.0,
        retry_base_s=0.01,
    )
    acked = 0

    def write(n=120):
        nonlocal acked
        acked += liaison.write_measure_queued(
            WriteRequest("cg", "m", _points(acked, n))
        )

    def query_total() -> int:
        t0 = time.perf_counter()
        res = liaison.query_measure(_count_req())
        wall = time.perf_counter() - t0
        stats["max_query_wall_s"] = max(stats["max_query_wall_s"], wall)
        assert wall <= budget_s + 1.0, f"query ran {wall:.2f}s past budget"
        assert not res.degraded, "replicated cluster must not degrade"
        return _total(res)

    def drain(deadline_s=20.0):
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            liaison.probe()  # the production probe loop runs periodically
            wq.flush(force=True)
            if wq.pending_parts() == 0:
                return
            time.sleep(0.05)
        raise AssertionError(
            f"wqueue never drained: {wq.pending_parts()} parts pending"
        )

    try:
        write()
        drain()
        assert query_total() == acked

        for cycle in range(3):
            victim = f"n{cycle % 2}"
            dn, srv = servers[victim]
            srv.stop(grace=0)  # the "kill": node unreachable, state kept
            write()            # acked into the spool-backed queue
            wq.flush(force=True)  # ships to the survivor, victim pends
            # acked rows stay queryable from the survivor mid-outage
            assert query_total() == acked, "acked rows lost mid-outage"
            # restart on the SAME port (discovery addresses are stable)
            srv2 = _bind_server(
                dn.bus, ports[victim], sync_install=dn.install_synced_parts
            )
            servers[victim] = (dn, srv2)
            liaison.probe()
            drain()  # re-ship: delivered.json + part uuid keep it single
            assert query_total() == acked, (
                f"cycle {cycle}: {query_total()} != acked {acked}"
            )
            stats["kill_cycles"] += 1
    finally:
        wq.stop(final_flush=False)
        transport.close()
        for dn, srv in servers.values():
            srv.stop(grace=0)
            dn.measure.close()
            dn.stream.close()
            dn.trace.close()
    stats["acked_a"] = acked


# -- smoke scenario B: graceful degradation + deadline -----------------------


def _smoke_degradation(tmp, budget_s: float, stats: dict) -> None:
    from banyandb_tpu.api import SchemaRegistry, WriteRequest
    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo
    from banyandb_tpu.cluster.rpc import LocalTransport
    from banyandb_tpu.obs.metrics import global_meter

    transport = LocalTransport()
    dns, infos = {}, []
    for i in range(3):
        reg = SchemaRegistry(tmp / f"b-n{i}" / "schema")
        _schema(reg)
        dn = DataNode(f"n{i}", reg, tmp / f"b-n{i}" / "data")
        dns[f"n{i}"] = dn
        infos.append(NodeInfo(f"n{i}", transport.register(f"n{i}", dn.bus)))
    lreg = SchemaRegistry(tmp / "b-liaison" / "schema")
    _schema(lreg)
    # replicas=0: every shard lives on exactly one node — losing a node
    # MUST degrade (not fail) queries, naming the unavailable node
    liaison = Liaison(lreg, transport, infos, replicas=0,
                      query_budget_s=budget_s)
    liaison.probe()

    total = 240
    liaison.write_measure(WriteRequest("cg", "m", _points(0, total)))
    for dn in dns.values():
        dn.measure.flush()

    res = liaison.query_measure(_count_req())
    assert _total(res) == total and not res.degraded

    before = global_meter().snapshot()["counters"].get(
        ("query_degraded", (("engine", "measure"),)), 0.0
    )
    transport.unregister("n1")  # mid-query node loss (probe not yet run)
    t0 = time.perf_counter()
    res = liaison.query_measure(_count_req(trace=True))
    wall = time.perf_counter() - t0
    stats["max_query_wall_s"] = max(stats["max_query_wall_s"], wall)
    assert wall <= budget_s + 1.0, f"degraded query ran {wall:.2f}s"
    assert res.degraded, "partial answer not marked degraded"
    assert res.unavailable_nodes == ["n1"], res.unavailable_nodes
    assert 0 < _total(res) < total, "degraded result should be partial"
    after = global_meter().snapshot()["counters"].get(
        ("query_degraded", (("engine", "measure"),)), 0.0
    )
    assert after > before, "query_degraded_total did not move"
    stats["degraded_seen"] += 1

    # recovery: node re-registers, probe restores, result completes
    transport.register("n1", dns["n1"].bus)
    liaison.probe()
    res = liaison.query_measure(_count_req())
    assert _total(res) == total and not res.degraded
    for dn in dns.values():
        dn.measure.close()
        dn.stream.close()
        dn.trace.close()


# -- smoke scenario C: seeded fault schedule under ingest --------------------


def _smoke_fault_schedule(tmp, seed: int, stats: dict) -> None:
    from banyandb_tpu.api import SchemaRegistry, WriteRequest
    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo, faults
    from banyandb_tpu.cluster.rpc import GrpcTransport, TransportError

    spec = (
        f"seed={seed};"
        "rpc=delay:p=0.2:ms=5;rpc=error:every=17:after=5;"
        "sync=corrupt:every=9:count=2;"
        "disk=enospc:every=7:after=1:count=2"
    )
    plane = faults.configure(spec)
    reg = SchemaRegistry(tmp / "c-n0" / "schema")
    _schema(reg, shard_num=2)
    dn = DataNode("n0", reg, tmp / "c-n0" / "data")
    srv = _bind_server(dn.bus, 0, sync_install=dn.install_synced_parts)
    lreg = SchemaRegistry(tmp / "c-liaison" / "schema")
    _schema(lreg, shard_num=2)
    transport = GrpcTransport()
    liaison = Liaison(lreg, transport, [NodeInfo("n0", srv.addr)])
    liaison.probe()
    wq = liaison.enable_write_queue(
        tmp / "c-liaison" / "wqueue", flush_interval_s=30.0,
        retry_base_s=0.01,
    )
    acked = 0
    try:
        for _ in range(6):
            # the rpc/disk boundaries may reject an append (shed) or a
            # seal (ENOSPC) — the caller retries; acked = returned count
            for _attempt in range(20):
                try:
                    acked += liaison.write_measure_queued(
                        WriteRequest("cg", "m", _points(acked, 40))
                    )
                    break
                except (TransportError, OSError):
                    time.sleep(0.01)
            try:
                wq.flush(force=True)
            except (TransportError, OSError):
                pass  # injected seal/ship fault; retried below
        faults.clear()  # drain cleanly: the schedule already fired
        end = time.monotonic() + 20
        while wq.pending_parts() and time.monotonic() < end:
            liaison.probe()  # a faulted ship may have marked n0 dead
            wq.flush(force=True)
            time.sleep(0.02)
        assert wq.pending_parts() == 0, "faulted spool never drained"
        liaison.probe()
        got = _total(liaison.query_measure(_count_req()))
        assert got == acked, f"fault schedule lost rows: {got} != {acked}"
    finally:
        faults.clear()
        wq.stop(final_flush=False)
        transport.close()
        srv.stop(grace=0)
        dn.measure.close()
        dn.stream.close()
        dn.trace.close()
    stats["faults_injected"] = len(plane.history)
    stats["fault_sites"] = plane.counters()
    stats["acked_c"] = acked
    assert plane.history, "schedule ran but injected nothing"
    # determinism: the same seed+schedule replays the same per-site
    # decision sequence (tests/test_faults.py pins exact sequences)
    p1, p2 = faults.FaultPlane(spec), faults.FaultPlane(spec)
    for site, n in sorted(plane.counters().items()):
        for _ in range(n):
            p1.decide(site)
            p2.decide(site)
    assert p1.history == p2.history, "fault plane is not deterministic"


def _smoke_worker_cycles(tmp, seed: int, stats: dict) -> None:
    """Multi-process data plane crash contract (docs/performance.md):
    SIGKILL shard-owning workers mid-ingest per a ``worker``-site kill
    schedule, assert zero acked-write loss after journal replay and a
    BOUNDED degraded window with explicit markers."""
    from banyandb_tpu.cluster import faults
    from banyandb_tpu.cluster.bus import Topic
    from banyandb_tpu.server import TOPIC_QL, StandaloneServer

    # the kill-schedule plane carries WHICH worker dies at WHICH cycle;
    # the harness performs the kill (site=worker, PR-7 contract)
    plane = faults.configure(f"seed={seed};worker=w000:at=1;worker=w001:at=2")
    srv = StandaloneServer(tmp / "workers", port=0, workers=2)
    srv.start()
    acked = 0
    degraded_windows = []
    try:
        _schema(srv.registry, group="cg", shard_num=4)

        def write(n=60):
            nonlocal acked
            from banyandb_tpu.cluster import serde as _serde
            from banyandb_tpu.api import WriteRequest

            r = srv.bus.handle(
                Topic.MEASURE_WRITE.value,
                {
                    "request": _serde.write_request_to_json(
                        WriteRequest("cg", "m", _points(acked, n))
                    )
                },
            )
            acked += r["written"]

        ql = (
            "SELECT count(v) FROM MEASURE m IN cg "
            f"TIME BETWEEN {T0} AND {T0 + 50_000_000}"
        )

        def probe() -> tuple[int, bool]:
            res = srv.bus.handle(TOPIC_QL, {"ql": ql})["result"]
            total = int(sum(res["values"].get("count", [])))
            if res.get("degraded"):
                assert res["unavailable_nodes"], "degraded without markers"
            return total, bool(res.get("degraded"))

        write(200)
        srv.pool.flush()  # journal trim: replay covers only the window
        write(100)
        for cycle in (1, 2):
            for victim in plane.kills_for_cycle(cycle, site="worker"):
                widx = srv.pool._names.index(victim)
                srv.pool.kill_worker(widx)
                t_kill = time.monotonic()
                write(80)  # acked DURING the dead window (journal spool)
                saw_degraded = False
                deadline = time.monotonic() + 45
                while time.monotonic() < deadline:
                    total, degraded = probe()
                    if degraded:
                        saw_degraded = True
                    if not degraded and total == acked:
                        break
                    time.sleep(0.2)
                window_s = time.monotonic() - t_kill
                degraded_windows.append(round(window_s, 2))
                assert saw_degraded, (
                    f"cycle {cycle}: no explicit degraded answer while "
                    f"{victim} was down"
                )
                total, degraded = probe()
                assert not degraded and total == acked, (
                    f"cycle {cycle}: acked-write loss or unbounded "
                    f"degradation ({total} != {acked}, degraded={degraded})"
                )
                stats["worker_kill_cycles"] = (
                    stats.get("worker_kill_cycles", 0) + 1
                )
        assert max(degraded_windows) < 45, degraded_windows
        stats["worker_degraded_windows_s"] = degraded_windows
        stats["worker_restarts"] = srv.pool.restarts
        stats["worker_acked"] = acked
    finally:
        faults.clear()
        srv.stop()


def _smoke_rebalance_under_kill(tmp, seed: int, stats: dict) -> None:
    """Elastic-cluster chaos (docs/robustness.md "Elastic cluster"):
    a join/kill schedule drives a LIVE rebalance whose preferred part
    source is SIGKILLed mid-move — the mover's holder failover pulls
    from the surviving replica, installs stay digest-deduped, the
    cutover bumps the epoch, and zero acked writes are lost."""
    from banyandb_tpu.api import SchemaRegistry, WriteRequest
    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo, faults
    from banyandb_tpu.cluster.placement import PlacementSelector
    from banyandb_tpu.cluster.rebalance import Rebalancer
    from banyandb_tpu.cluster.rpc import GrpcTransport

    # the schedule carries WHO joins and WHO dies mid-move; the harness
    # performs both (join/leave satellite: events_for_cycle)
    plane = faults.configure(f"seed={seed};join=r3:at=1;kill=r0:at=1")
    events = plane.events_for_cycle(1)
    assert events["join"] == ["r3"] and events["kill"] == ["r0"]

    nodes, servers, dns, ports = [], {}, {}, {}
    for i in range(3):
        reg = SchemaRegistry(tmp / f"e-r{i}" / "schema")
        _schema(reg, group="rg", shard_num=3)
        dn = DataNode(f"r{i}", reg, tmp / f"e-r{i}" / "data")
        srv = _bind_server(dn.bus, 0, sync_install=dn.install_synced_parts)
        servers[f"r{i}"], dns[f"r{i}"], ports[f"r{i}"] = srv, dn, srv.port
        nodes.append(NodeInfo(f"r{i}", srv.addr))
    lreg = SchemaRegistry(tmp / "e-liaison" / "schema")
    _schema(lreg, group="rg", shard_num=3)
    transport = GrpcTransport()
    # handoff: the kill window's writes spool the dead replica's copies
    # and replay them (epoch re-stamped) once it rejoins
    liaison = Liaison(
        lreg, transport, nodes, replicas=1,
        handoff_root=str(tmp / "e-liaison" / "handoff"),
    )
    liaison.probe()
    acked = [0]

    def write(n=90):
        from banyandb_tpu.api import DataPointValue

        pts = tuple(
            DataPointValue(
                ts_millis=T0 + acked[0] + i,
                tags={"svc": f"s{(acked[0] + i) % 8}"},
                fields={"v": 1.0}, version=1,
            )
            for i in range(n)
        )
        acked[0] += liaison.write_measure(WriteRequest("rg", "m", pts))

    def total() -> int:
        from banyandb_tpu.api import (
            Aggregation, GroupBy, QueryRequest, TimeRange,
        )

        res = liaison.query_measure(QueryRequest(
            groups=("rg",), name="m",
            time_range=TimeRange(T0, T0 + 50_000_000),
            group_by=GroupBy(("svc",)), agg=Aggregation("count", "v"),
        ))
        return int(sum(res.values.get("count", [])))

    try:
        write(240)
        # the scheduled JOIN: r3 appears in the addr book only
        for name in events["join"]:
            reg = SchemaRegistry(tmp / f"e-{name}" / "schema")
            _schema(reg, group="rg", shard_num=3)
            dn = DataNode(name, reg, tmp / f"e-{name}" / "data")
            srv = _bind_server(
                dn.bus, 0, sync_install=dn.install_synced_parts
            )
            servers[name], dns[name], ports[name] = srv, dn, srv.port
            with liaison._placement_lock:
                liaison.selector = PlacementSelector(
                    list(liaison.selector.nodes)
                    + [NodeInfo(name, srv.addr)],
                    liaison.placement,
                )
        liaison.probe()
        reb = Rebalancer(liaison)
        plan = reb.plan()
        assert plan.moves, "scheduled join produced no moves"

        def mid_move():
            # the scheduled KILL lands exactly mid-move: a part source
            # goes away between the bulk and delta ship rounds
            for victim in events["kill"]:
                servers[victim].stop(grace=0)
            write(90)  # acked during the kill window (replica covers)

        st = reb.apply(plan, mid_move=mid_move)
        assert st["ok"], st
        assert liaison.placement.epoch == 2
        stats["rebalance_parts_moved"] = st["parts_moved"]
        # restart the victim on its port; it learns the epoch from the
        # placement broadcast riding the next probe-visible traffic
        for victim in events["kill"]:
            servers[victim] = _bind_server(
                dns[victim].bus, ports[victim],
                sync_install=dns[victim].install_synced_parts,
            )
        liaison.probe()
        liaison.broadcast_placement()
        got = total()
        assert got == acked[0], (
            f"rebalance-under-kill lost acked writes: {got} != {acked[0]}"
        )
        for name, dn in dns.items():
            assert dn.epoch_record.epoch == 2, (name, dn.epoch_record.epoch)
        stats["rebalance_under_kill"] = 1
        stats["rebalance_acked"] = acked[0]
    finally:
        faults.clear()
        transport.close()
        for srv in servers.values():
            srv.stop(grace=0)
        for dn in dns.values():
            dn.measure.close()
            dn.stream.close()
            dn.trace.close()


def run_smoke(tmp_root, seed: int = 42, budget_s: float = 3.0) -> dict:
    from pathlib import Path

    tmp = Path(tmp_root)
    tmp.mkdir(parents=True, exist_ok=True)
    stats = {
        "mode": "smoke", "seed": seed, "kill_cycles": 0,
        "degraded_seen": 0, "max_query_wall_s": 0.0,
    }
    # QoS armed for every cycle (docs/robustness.md "Multi-tenant
    # QoS"): the admission plane runs live with a configured tenant
    # table; the chaos traffic is untenanted (default tenant, generous
    # limits), so the kill/degradation cycles must stay green — zero
    # acked loss, zero spurious sheds — THROUGH the armed plane.
    from banyandb_tpu.qos.plane import reset_qos

    saved_qos = {
        k: os.environ.get(k) for k in ("BYDB_QOS", "BYDB_QOS_TENANTS")
    }
    os.environ["BYDB_QOS"] = "1"
    os.environ["BYDB_QOS_TENANTS"] = json.dumps(
        {"chaos": {"write_rate": 1_000_000, "max_concurrent": 64}}
    )
    reset_qos()
    stats["qos_armed"] = 1
    t0 = time.perf_counter()
    try:
        _smoke_wqueue_cycles(tmp, budget_s, stats)
        _smoke_degradation(tmp, budget_s, stats)
        _smoke_fault_schedule(tmp, seed, stats)
        _smoke_worker_cycles(tmp, seed, stats)
        _smoke_rebalance_under_kill(tmp, seed, stats)
    finally:
        for k, v in saved_qos.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset_qos()
    stats["wall_s"] = round(time.perf_counter() - t0, 2)
    assert stats["kill_cycles"] >= 3
    assert stats["degraded_seen"] >= 1
    assert stats["worker_kill_cycles"] >= 2
    assert stats["rebalance_under_kill"] >= 1
    return stats


# -- soak: real subprocess cluster, SIGKILL cycles ---------------------------


def _child_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BYDB_QUERY_DEADLINE_S"] = "10"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO]
        + [
            p
            for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p and p != REPO
        ]
    )
    return env


def run_soak(
    tmp_root, seconds: float = 120.0, seed: int = 42, n_nodes: int = 3
) -> dict:
    import signal
    import socket
    import subprocess
    from pathlib import Path

    from banyandb_tpu.cluster.bus import Topic
    from banyandb_tpu.cluster.rpc import GrpcTransport
    from banyandb_tpu.server import TOPIC_QL, TOPIC_REGISTRY

    tmp = Path(tmp_root)
    tmp.mkdir(parents=True, exist_ok=True)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [free_port() for _ in range(n_nodes + 1)]
    nodes_file = tmp / "nodes.json"
    nodes_file.write_text(json.dumps([
        {"name": f"n{i}", "addr": f"127.0.0.1:{ports[i]}", "roles": ["data"]}
        for i in range(n_nodes)
    ]))
    logs = [(tmp / f"proc{i}.log").open("w") for i in range(n_nodes + 1)]
    procs: dict[str, subprocess.Popen] = {}
    transport = GrpcTransport()
    laddr = f"127.0.0.1:{ports[n_nodes]}"

    def spawn(args, logf):
        return subprocess.Popen(
            [sys.executable, "-m", "banyandb_tpu.server", *args],
            env=_child_env(), stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True,
        )

    def spawn_data(i):
        procs[f"n{i}"] = spawn(
            ["--role", "data", "--root", str(tmp / f"n{i}"),
             "--name", f"n{i}", "--port", str(ports[i])], logs[i],
        )

    def wait_banner(i, timeout_s=120.0):
        path = tmp / f"proc{i}.log"
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            try:
                if "banyandb-tpu" in path.read_text(errors="replace"):
                    return
            except OSError:
                pass
            time.sleep(0.25)
        raise TimeoutError(f"proc{i} never printed its banner")

    def wait_health(addr, timeout_s=60.0):
        end = time.monotonic() + timeout_s
        last = None
        while time.monotonic() < end:
            try:
                r = transport.call(addr, Topic.HEALTH.value, {}, timeout=5)
                if r.get("status") == "ok":
                    return r
            except Exception as exc:  # noqa: BLE001 - still booting
                last = exc
            time.sleep(0.5)
        raise TimeoutError(f"{addr} never became healthy: {last}")

    stats = {
        "mode": "soak", "seed": seed, "kill_cycles": 0,
        "degraded_seen": 0, "max_query_wall_s": 0.0,
        "write_retries": 0, "acked": 0,
    }
    acked = 0

    def write_batch(n=200):
        nonlocal acked
        pts = [{
            "ts": T0 + acked + j, "tags": {"svc": f"s{(acked + j) % 8}"},
            "fields": {"v": 1.0}, "version": 1,
        } for j in range(n)]
        transport.call(
            laddr, Topic.MEASURE_WRITE.value,
            {"request": {"group": "cg", "name": "m", "points": pts}},
            timeout=15,
        )
        acked += n

    def write_with_retry():
        for _ in range(30):
            try:
                write_batch()
                return True
            except Exception:  # noqa: BLE001 - outage window
                stats["write_retries"] += 1
                time.sleep(0.2)
        return False

    def query() -> dict:
        t0 = time.perf_counter()
        r = transport.call(laddr, TOPIC_QL, {
            "ql": ("SELECT count(v) FROM MEASURE m IN cg "
                   f"TIME BETWEEN {T0} AND {T0 + 50_000_000}")
        }, timeout=30.0)["result"]
        wall = time.perf_counter() - t0
        stats["max_query_wall_s"] = max(stats["max_query_wall_s"], wall)
        # liaison budget is 10s (BYDB_QUERY_DEADLINE_S): the bound plus
        # scheduling slack
        assert wall <= 15.0, f"query ran {wall:.1f}s past its deadline"
        if r.get("degraded"):
            stats["degraded_seen"] += 1
            assert r.get("unavailable_nodes"), "degraded without names"
        return r

    def count_of(r) -> int:
        return int(sum(r["values"].get("count", [0])))

    def flush_all(names):
        for name in names:
            i = int(name[1:])
            try:
                transport.call(
                    f"127.0.0.1:{ports[i]}", "flush", {}, timeout=15
                )
            except Exception:  # noqa: BLE001 - node may be the victim
                pass

    def kill(name):
        p = procs[name]
        os.killpg(p.pid, signal.SIGKILL)
        p.wait()

    try:
        for i in range(n_nodes):
            spawn_data(i)
        procs["liaison"] = spawn(
            ["--role", "liaison", "--root", str(tmp / "l"),
             "--discovery", str(nodes_file), "--replicas", "1",
             "--port", str(ports[n_nodes])], logs[n_nodes],
        )
        for i in range(n_nodes):
            wait_banner(i)
            wait_health(f"127.0.0.1:{ports[i]}")
        wait_banner(n_nodes)
        wait_health(laddr)
        transport.call(laddr, TOPIC_REGISTRY, {
            "op": "create", "kind": "group", "item": {
                "name": "cg", "catalog": "measure",
                "resource_opts": {
                    "shard_num": 4, "replicas": 1,
                    "segment_interval": {"num": 1, "unit": "day"},
                    "ttl": {"num": 7, "unit": "day"}, "stages": [],
                },
            }}, timeout=15)
        transport.call(laddr, TOPIC_REGISTRY, {
            "op": "create", "kind": "measure", "item": {
                "group": "cg", "name": "m",
                "tags": [{"name": "svc", "type": "string"}],
                "fields": [{"name": "v", "type": "float"}],
                "entity": {"tag_names": ["svc"]}, "interval": "",
                "index_mode": False,
            }}, timeout=15)

        cycles = max(3, n_nodes)
        slice_s = max(seconds / (cycles + 1), 5.0)
        write_with_retry()
        assert count_of(query()) == acked

        for cycle in range(cycles):
            victims = [f"n{cycle % n_nodes}"]
            if cycle == cycles - 1:
                # the double-kill window: adjacent replicas down means
                # some shard loses its whole chain -> degraded answers
                victims.append(f"n{(cycle + 1) % n_nodes}")
            # bound the direct-write plane's documented crash window:
            # flush memtables before the kill (chaos measures replication
            # + replay, not WAL-less crash durability)
            flush_all([f"n{i}" for i in range(n_nodes)])
            for v in victims:
                kill(v)
            end = time.monotonic() + slice_s
            while time.monotonic() < end:
                write_with_retry()
                query()
                time.sleep(0.1)
            for v in victims:
                spawn_data(int(v[1:]))
            for v in victims:
                wait_health(f"127.0.0.1:{ports[int(v[1:])]}")
            stats["kill_cycles"] += 1

        # convergence: every acked row queryable after recovery
        end = time.monotonic() + 90
        got = -1
        while time.monotonic() < end:
            write_with_retry()
            got = count_of(query())
            if got >= acked:
                break
            time.sleep(2)
        assert got >= acked, f"acked-write loss: {got} < {acked}"
        stats["acked"] = acked
        assert stats["degraded_seen"] >= 1, (
            "double-kill window produced no degraded response"
        )
    finally:
        transport.close()
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    p.kill()
                p.wait()
        for f in logs:
            f.close()
    return stats


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--soak", type=float, default=0.0, metavar="SECONDS")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--artifact", default="")
    args = ap.parse_args()
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bydb-chaos-")
    if args.smoke:
        stats = run_smoke(tmp, seed=args.seed)
    elif args.soak:
        stats = run_soak(tmp, seconds=args.soak, seed=args.seed)
    else:
        print(__doc__)
        return 2
    print(json.dumps(stats, indent=2, default=str))
    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump(stats, f, indent=2, default=str)
    print("chaos: all invariants held")
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    sys.stderr.flush()
    # grpc's C++ worker threads can abort ("terminate called without an
    # active exception") during ordinary interpreter teardown on this
    # gVisor-class kernel AFTER every invariant already passed — same
    # exit contract as server.py main(): skip C++ teardown entirely
    os._exit(rc)
