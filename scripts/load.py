"""Load/SLO harness (test/load analog, BASELINE-style workload shape).

Boots a REAL standalone server (gRPC bus transport on a socket), then
drives a sustained mixed workload from concurrent client threads:

  - writer threads: batched measure writes (svc/region/status tags,
    one numeric field) — the benchmark-single-model ingest shape,
  - query threads: randomized BydbQL aggregates over the trailing
    window — the 50-concurrent-query read side,

while the server's own lifecycle loops flush/merge/retain underneath.
Reports sustained write points/min, query throughput, and query latency
p50/p90/p99 — the same metrics the reference's published benchmark
tables carry (docs/operation/benchmark/benchmark-single-model.md) —
plus SLO pass/fail against optional floors.

    PYTHONPATH=. JAX_PLATFORMS=cpu python scripts/load.py \
        --seconds 60 --writers 2 --queriers 4 \
        --min-writes-per-min 1000000 --max-p99-ms 250

Importable: run_load() powers tests/test_load_smoke.py.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np

T0 = 1_700_000_000_000
GROUP, MEASURE = "load", "cpm"


def _percentile(xs: list, q: float) -> float:
    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


def run_load(
    *,
    seconds: float = 30.0,
    writers: int = 2,
    queriers: int = 4,
    batch: int = 500,
    seed: int = 0,
    write_rate: int = 0,
    query_interval_ms: int = 0,
    tmp_root: str | None = None,
    workers: int = 0,
    autoreg: bool = False,
) -> dict:
    """write_rate: total sustained ingest points/s across all writers
    (0 = closed loop, writers go as fast as the core allows).  The
    reference's published query latencies are measured at a FIXED ingest
    rate (~9.5k points/s, benchmark-single-model.md:96) — a closed loop
    on a shared core measures writer throughput, not query SLO.

    workers: shard-owning worker subprocesses (BYDB_WORKERS A/B,
    docs/performance.md "Multi-process data plane"); 0 = the
    single-process layout every pre-r08 artifact measured.

    autoreg: the self-driving scenario (ISSUE 12 acceptance) — NO
    manual streamagg signature is registered; the server's bydb-autoreg
    loop must discover the dashboard pattern from query evidence on its
    own.  The artifact then carries the materialized-hit RAMP
    (per-bucket fraction + time to 0.9)."""
    import os as _os
    import tempfile

    from banyandb_tpu.cluster.rpc import GrpcTransport
    from banyandb_tpu.server import TOPIC_REGISTRY, StandaloneServer

    own_root = tmp_root is None
    root = tmp_root or tempfile.mkdtemp(prefix="bydb-load-")
    # the loop reads the env at server start; the baseline leg pins it
    # OFF explicitly (the server defaults autoreg ON) so the manual-
    # registration runs measure exactly the manual configuration — a
    # background loop adding signatures would contaminate the A/B
    _os.environ["BYDB_AUTOREG"] = "1" if autoreg else "0"
    # pass 0 through verbatim: the baseline phase must pin the
    # single-process layout even when BYDB_WORKERS is exported (None
    # would fall through to the env and mislabel the artifact)
    srv = StandaloneServer(root, port=0, workers=workers)
    srv.start()
    addr = srv.addr

    def call(transport, topic, env, timeout=60.0):
        return transport.call(addr, topic, env, timeout=timeout)

    try:
        setup = GrpcTransport()
        try:
            call(setup, TOPIC_REGISTRY, {"op": "create", "kind": "group", "item": {
                "name": GROUP, "catalog": "measure",
                "resource_opts": {
                    "shard_num": 2, "replicas": 0,
                    "segment_interval": {"num": 1, "unit": "day"},
                    "ttl": {"num": 7, "unit": "day"}, "stages": [],
                },
            }})
            call(setup, TOPIC_REGISTRY, {"op": "create", "kind": "measure", "item": {
                "group": GROUP, "name": MEASURE,
                "tags": [{"name": "svc", "type": "string"},
                         {"name": "region", "type": "string"},
                         {"name": "status", "type": "int"}],
                "fields": [{"name": "value", "type": "float"}],
                "entity": {"tag_names": ["svc"]}, "interval": "", "index_mode": False,
            }})
        finally:
            setup.close()
        if not autoreg:
            # materialized dashboard signatures (query/streamagg.py):
            # the two shapes the query mix re-asks — per-service reads
            # filter on svc, dashboards group by svc and optionally
            # filter region — registered up front exactly like a real
            # console deployment.  (The --autoreg scenario registers
            # NOTHING: the bydb-autoreg loop must find them itself.)
            reg_probe = GrpcTransport()
            try:
                from banyandb_tpu.server import TOPIC_STREAMAGG

                # ONE covering signature: (region, svc) answers both the
                # per-service reads and the dashboards (coverage needs
                # key-tag SUPERSET), so ingest pays a single window
                # update per row.  15s windows bound the uncovered
                # head/tail rescan to <=15s of event time per side.
                call(reg_probe, TOPIC_STREAMAGG, {
                    "op": "register", "group": GROUP, "measure": MEASURE,
                    "key_tags": ["region", "svc"], "fields": ["value"],
                    "window_millis": 15_000,
                })
            finally:
                reg_probe.close()
        stats = _drive_load(
            call, seconds=seconds, writers=writers,
            queriers=queriers, batch=batch, seed=seed,
            write_rate=write_rate, query_interval_ms=query_interval_ms,
        )
        stats["workers"] = workers
        stats["autoreg"] = autoreg
        # serving-cache composition of the reported latencies (VERDICT
        # r5 Weak #4): without hit/miss counters a p50 could be 99%
        # cache replay — fetch them from the RUNNING server so the
        # artifact records what the percentiles actually measured
        probe = GrpcTransport()
        try:
            stats["serving_cache"] = _serving_cache_stats(probe, addr)
            # per-stage attribution (gather / device_execute / merge /
            # streamagg p50/p99) from the server's bucketed histograms,
            # same scraper the bench artifact uses (obs/prom.py)
            stats["stage_breakdown"] = _stage_breakdown(probe, addr)
            from banyandb_tpu.server import TOPIC_STREAMAGG

            stats["streamagg"] = probe.call(
                addr, TOPIC_STREAMAGG, {"op": "stats"}, timeout=30.0
            )["streamagg"]
            if autoreg:
                stats["autoreg_stats"] = srv.autoreg.stats()
        finally:
            probe.close()
        return stats
    finally:
        srv.stop()
        if own_root:
            import shutil

            shutil.rmtree(root, ignore_errors=True)


def _serving_cache_stats(transport, addr: str) -> dict:
    """Serving-cache counters scraped from the live server's metrics
    topic -> {hits, misses, evictions, entries, hit_rate}."""
    from banyandb_tpu.server import TOPIC_METRICS

    from banyandb_tpu.obs import prom as obs_prom

    text = transport.call(addr, TOPIC_METRICS, {}, timeout=30.0).get(
        "prometheus", ""
    )
    # sum across label sets: in worker mode each worker exposes its own
    # serving cache under a worker="wNNN" label
    out = {}
    for name, _labels, value in obs_prom.parse_exposition(text):
        for key in ("hits", "misses", "evictions", "entries"):
            if name == f"banyandb_serving_cache_{key}":
                out[key] = out.get(key, 0) + int(value)
    lookups = out.get("hits", 0) + out.get("misses", 0)
    out["hit_rate"] = (
        round(out.get("hits", 0) / lookups, 4) if lookups else 0.0
    )
    return out


def _stage_breakdown(transport, addr: str) -> dict:
    """Stage latency quantiles recovered from the live exposition's
    _bucket series (docs/observability.md instrument scheme)."""
    from banyandb_tpu.obs import prom as obs_prom
    from banyandb_tpu.server import TOPIC_METRICS

    text = transport.call(addr, TOPIC_METRICS, {}, timeout=30.0).get(
        "prometheus", ""
    )
    return obs_prom.stage_breakdown(text)


def _drive_load(
    call, *, seconds, writers, queriers, batch, seed, write_rate=0,
    query_interval_ms=0,
) -> dict:
    """query_interval_ms: per-querier poll cadence (0 = closed loop).
    Closed-loop clients sharing the server's interpreter measure GIL
    saturation, not query latency — real dashboards poll on a refresh
    interval, and an OPEN-loop stream at that cadence measures latency
    including queueing without the coordinated-saturation artifact."""
    from banyandb_tpu.cluster.bus import Topic
    from banyandb_tpu.cluster.rpc import GrpcTransport
    from banyandb_tpu.server import TOPIC_QL

    stop = threading.Event()
    written = [0] * writers
    write_errors = [0] * writers
    q_lat_ms: list[list[float]] = [[] for _ in range(queriers)]
    q_errors = [0] * queriers
    clock0 = time.time()

    import base64

    svc_dict = [f"s{i}" for i in range(50)]
    region_dict = [f"r{i}" for i in range(3)]
    status_dict = [200, 404, 500]

    def writer(wid: int):
        rng = np.random.default_rng(seed + wid)
        t = GrpcTransport()
        lane_rate = write_rate / writers if write_rate else 0
        t_start = time.monotonic()
        try:
            while not stop.is_set():
                if lane_rate:
                    # token-bucket pacing: sleep until this lane's next
                    # batch is due at the configured points/s
                    due = t_start + written[wid] / lane_rate
                    delay = due - time.monotonic()
                    if delay > 0:
                        if stop.wait(min(delay, 0.5)):
                            break
                        continue
                # disjoint per-writer timestamp lanes: stride by writer
                # count so no two writers ever collide on (series, ts)
                # and silently overwrite each other
                ts = (
                    T0
                    + ((written[wid] + np.arange(batch, dtype=np.int64))
                       * writers + wid) * 10
                )
                env = {
                    "group": GROUP, "name": MEASURE,
                    "ts": base64.b64encode(
                        ts.astype("<i8").tobytes()
                    ).decode(),
                    "versions": base64.b64encode(
                        np.ones(batch, dtype="<i8").tobytes()
                    ).decode(),
                    "tags": {
                        "svc": {
                            "dict": svc_dict,
                            "codes": base64.b64encode(
                                rng.integers(0, 50, batch, dtype=np.int32)
                                .astype("<i4").tobytes()
                            ).decode(),
                        },
                        "region": {
                            "dict": region_dict,
                            "codes": base64.b64encode(
                                rng.integers(0, 3, batch, dtype=np.int32)
                                .astype("<i4").tobytes()
                            ).decode(),
                        },
                        "status": {
                            "dict": status_dict,
                            "codes": base64.b64encode(
                                rng.integers(0, 3, batch, dtype=np.int32)
                                .astype("<i4").tobytes()
                            ).decode(),
                        },
                    },
                    "fields": {
                        "value": base64.b64encode(
                            rng.integers(0, 1000, batch)
                            .astype("<f8").tobytes()
                        ).decode(),
                    },
                }
                try:
                    call(t, Topic.MEASURE_WRITE_COLUMNS.value, env)
                    written[wid] += batch
                except Exception:  # noqa: BLE001 - keep load flowing
                    write_errors[wid] += 1
        finally:
            t.close()

    AGGS = ("count", "sum", "mean", "max")

    def querier(qid: int):
        rng = np.random.default_rng(1000 + seed + qid)
        t = GrpcTransport()
        issued = 0
        q_start = time.monotonic()
        try:
            while not stop.is_set():
                if query_interval_ms:
                    # open-loop dashboard poll: next query is DUE on the
                    # cadence regardless of the last one's latency
                    due = q_start + issued * query_interval_ms / 1000.0
                    delay = due - time.monotonic()
                    if delay > 0:
                        if stop.wait(min(delay, 0.5)):
                            break
                        continue
                issued += 1
                agg = AGGS[rng.integers(0, len(AGGS))]
                # Trailing event-time window (the reference benchmark's
                # query shape: trailing 15 minutes during sustained
                # ingest, benchmark-single-model.md:104): high-water
                # mark from the writers' lane clocks, quantized to 1s
                # ticks the way dashboard refresh cycles are.
                hw = T0 + (max(written) * writers * 10) // 1000 * 1000
                lo = max(T0, hw - 900_000)
                if rng.integers(0, 4) < 3:
                    # per-entity metric read (the OAP access pattern the
                    # reference benchmark measures: one service's metric
                    # over the window, series-index pruned)
                    where = f"WHERE svc = 's{rng.integers(0, 50)}' "
                    group_by = ""
                else:
                    # dashboard aggregation across all services
                    where = (
                        f"WHERE region = 'r{rng.integers(0, 3)}' "
                        if rng.integers(0, 2) else ""
                    )
                    group_by = "GROUP BY svc "
                ql = (
                    f"SELECT {agg}(value) FROM MEASURE {MEASURE} IN {GROUP} "
                    f"TIME BETWEEN {lo} AND {hw} "
                    f"{where}{group_by}LIMIT 100"
                )
                t0 = time.perf_counter()
                try:
                    reply = call(t, TOPIC_QL, {"ql": ql})
                    # per-query serve-path marker (server classifies
                    # from the span tree): replay = partials-cache hit,
                    # materialized = streamagg window fold, scan = real
                    # cache-miss reduction.  The wall offset feeds the
                    # --autoreg materialized-hit ramp.
                    q_lat_ms[qid].append((
                        (time.perf_counter() - t0) * 1000,
                        reply.get("served", "scan"),
                        time.time() - clock0,
                    ))
                except Exception:  # noqa: BLE001
                    q_errors[qid] += 1
        finally:
            t.close()

    threads = [
        threading.Thread(target=writer, args=(w,), daemon=True)
        for w in range(writers)
    ] + [
        threading.Thread(target=querier, args=(q,), daemon=True)
        for q in range(queriers)
    ]
    for th in threads:
        th.start()
    time.sleep(seconds)
    stop.set()
    for th in threads:
        th.join(timeout=30)
    elapsed = time.time() - clock0

    samples = [x for bucket in q_lat_ms for x in bucket]
    lats = sorted(ms for ms, _served, _t in samples)
    # Headline split (ISSUE 10 satellite): the aggregate p50 hid 71.4%
    # serving-cache replay in r06 — report replay and real (cache-miss)
    # scans as separate percentiles, with materialized-window reads
    # counted as scans (they ARE the cache-miss answer path) but also
    # surfaced as their own hit fraction.
    replay = sorted(ms for ms, served, _t in samples if served == "replay")
    scans = sorted(ms for ms, served, _t in samples if served != "replay")
    materialized = [
        ms for ms, served, _t in samples if served == "materialized"
    ]
    # materialized-hit RAMP (the --autoreg acceptance evidence): per
    # 10s bucket, what fraction of queries served from windows — and
    # the first bucket whose fraction crosses 0.9
    ramp: list[dict] = []
    time_to_materialized = None
    bucket_s = 10.0
    if samples:
        horizon = max(t for _ms, _s, t in samples)
        b = 0.0
        while b < horizon:
            in_b = [s for _ms, s, t in samples if b <= t < b + bucket_s]
            if in_b:
                frac = sum(
                    1 for s in in_b if s == "materialized"
                ) / len(in_b)
                ramp.append(
                    {"t_s": round(b, 1), "fraction": round(frac, 3)}
                )
                if frac >= 0.9 and time_to_materialized is None:
                    time_to_materialized = round(b + bucket_s, 1)
            b += bucket_s
    total_written = sum(written)
    n_q = len(samples)
    return {
        "seconds": round(elapsed, 1),
        "writers": writers,
        "queriers": queriers,
        "points_written": total_written,
        "write_points_per_min": round(total_written / elapsed * 60),
        "write_errors": sum(write_errors),
        "queries": n_q,
        "queries_per_s": round(n_q / elapsed, 1),
        "query_errors": sum(q_errors),
        "latency_ms": {
            "p50": round(_percentile(lats, 50), 1),
            "p90": round(_percentile(lats, 90), 1),
            "p99": round(_percentile(lats, 99), 1),
        },
        "replay_p50_ms": round(_percentile(replay, 50), 1),
        "scan_p50_ms": round(_percentile(scans, 50), 1),
        "scan_p99_ms": round(_percentile(scans, 99), 1),
        "replay_fraction": round(len(replay) / n_q, 4) if n_q else 0.0,
        "materialized_hit_fraction": (
            round(len(materialized) / n_q, 4) if n_q else 0.0
        ),
        "materialized_ramp": ramp,
        "time_to_materialized_0_9_s": time_to_materialized,
        "served": {
            kind: sum(1 for _ms, s, _t in samples if s == kind)
            for kind in ("scan", "materialized", "replay")
        },
    }


def _columns_env(group: str, measure: str, ts, rng, batch: int) -> dict:
    """One columnar write envelope in the benchmark-single-model ingest
    shape (svc/region/status tags + one float field)."""
    import base64

    def b64(a) -> str:
        return base64.b64encode(a.tobytes()).decode()

    return {
        "group": group, "name": measure,
        "ts": b64(ts.astype("<i8")),
        "versions": b64(np.ones(batch, dtype="<i8")),
        "tags": {
            "svc": {
                "dict": [f"s{i}" for i in range(50)],
                "codes": b64(
                    rng.integers(0, 50, batch, dtype=np.int32).astype("<i4")
                ),
            },
            "region": {
                "dict": [f"r{i}" for i in range(3)],
                "codes": b64(
                    rng.integers(0, 3, batch, dtype=np.int32).astype("<i4")
                ),
            },
            "status": {
                "dict": [200, 404, 500],
                "codes": b64(
                    rng.integers(0, 3, batch, dtype=np.int32).astype("<i4")
                ),
            },
        },
        "fields": {
            "value": b64(rng.integers(0, 1000, batch).astype("<f8")),
        },
    }


def _tenant_phase(
    *,
    tenants: dict,
    seconds: float,
    batch: int,
    seed: int,
    query_interval_ms: int,
    quota: int,
) -> dict:
    """One multi-tenant load phase on a fresh standalone server with the
    QoS plane armed: ``tenants`` maps tenant name -> target write rate
    (points/s; the ABUSER's target exceeds its quota on purpose).  Every
    tenant gets its own group (``<tenant>.load``), one paced writer and
    one open-loop querier; per-tenant latency percentiles, client-side
    shed counts (TransportError kind="shed" — the EXPECTED rejection,
    never counted as an error) and a zero-silent-drop witness (acked
    writes == served count) come back per tenant."""
    import os as _os
    import tempfile

    from banyandb_tpu.cluster.bus import Topic
    from banyandb_tpu.cluster.rpc import GrpcTransport, TransportError
    from banyandb_tpu.qos.plane import reset_qos
    from banyandb_tpu.server import (
        TOPIC_QL,
        TOPIC_QOS,
        TOPIC_REGISTRY,
        StandaloneServer,
    )

    root = tempfile.mkdtemp(prefix="bydb-tenants-")
    _os.environ["BYDB_AUTOREG"] = "0"
    _os.environ["BYDB_QOS"] = "1"
    _os.environ["BYDB_QOS_TENANTS"] = json.dumps(
        {t: {"write_rate": quota} for t in tenants}
    )
    reset_qos()
    srv = StandaloneServer(root, port=0, workers=0)
    srv.start()
    addr = srv.addr

    def call(transport, topic, env, timeout=60.0):
        return transport.call(addr, topic, env, timeout=timeout)

    try:
        setup = GrpcTransport()
        try:
            for tenant in tenants:
                call(setup, TOPIC_REGISTRY, {
                    "op": "create", "kind": "group", "item": {
                        "name": f"{tenant}.load", "catalog": "measure",
                        "resource_opts": {
                            "shard_num": 1, "replicas": 0,
                            "segment_interval": {"num": 1, "unit": "day"},
                            "ttl": {"num": 7, "unit": "day"}, "stages": [],
                        },
                    },
                })
                call(setup, TOPIC_REGISTRY, {
                    "op": "create", "kind": "measure", "item": {
                        "group": f"{tenant}.load", "name": MEASURE,
                        "tags": [
                            {"name": "svc", "type": "string"},
                            {"name": "region", "type": "string"},
                            {"name": "status", "type": "int"},
                        ],
                        "fields": [{"name": "value", "type": "float"}],
                        "entity": {"tag_names": ["svc"]},
                        "interval": "", "index_mode": False,
                    },
                })
                # the covering dashboard signature, per tenant (same
                # deployment shape as run_load): tenant-partitioned
                # materialized windows are part of what the scenario
                # verifies — one tenant's churn must not evict another's
                from banyandb_tpu.server import TOPIC_STREAMAGG

                call(setup, TOPIC_STREAMAGG, {
                    "op": "register", "group": f"{tenant}.load",
                    "measure": MEASURE,
                    "key_tags": ["region", "svc"], "fields": ["value"],
                    "window_millis": 15_000,
                })
        finally:
            setup.close()

        stop = threading.Event()
        acked = {t: 0 for t in tenants}
        sheds = {t: 0 for t in tenants}
        write_errors = {t: 0 for t in tenants}
        q_lat = {t: [] for t in tenants}  # (ms, served)
        q_sheds = {t: 0 for t in tenants}
        q_errors = {t: 0 for t in tenants}
        clock0 = time.time()

        def writer(tenant: str, rate: float):
            rng = np.random.default_rng(seed + sum(map(ord, tenant)))
            tr = GrpcTransport()
            t_start = time.monotonic()
            sent = 0  # attempted points (pacing covers sheds too)
            try:
                while not stop.is_set():
                    due = t_start + sent / rate
                    delay = due - time.monotonic()
                    if delay > 0:
                        if stop.wait(min(delay, 0.5)):
                            break
                        continue
                    ts = T0 + acked[tenant] + np.arange(batch, dtype=np.int64)
                    env = _columns_env(
                        f"{tenant}.load", MEASURE, ts, rng, batch
                    )
                    sent += batch
                    try:
                        call(tr, Topic.MEASURE_WRITE_COLUMNS.value, env)
                        acked[tenant] += batch
                    except TransportError as e:
                        if getattr(e, "kind", "") == "shed":
                            sheds[tenant] += 1  # EXPECTED, retryable
                        else:
                            write_errors[tenant] += 1
                    except Exception:  # noqa: BLE001 - keep load flowing
                        write_errors[tenant] += 1
            finally:
                tr.close()

        AGGS = ("count", "sum", "mean", "max")

        def querier(tenant: str):
            rng = np.random.default_rng(7000 + seed + sum(map(ord, tenant)))
            tr = GrpcTransport()
            issued = 0
            q_start = time.monotonic()
            try:
                while not stop.is_set():
                    due = q_start + issued * query_interval_ms / 1000.0
                    delay = due - time.monotonic()
                    if delay > 0:
                        if stop.wait(min(delay, 0.5)):
                            break
                        continue
                    issued += 1
                    agg = AGGS[rng.integers(0, len(AGGS))]
                    hw = T0 + max(acked[tenant], 1)
                    where = (
                        f"WHERE region = 'r{rng.integers(0, 3)}' "
                        if rng.integers(0, 2) else ""
                    )
                    ql = (
                        f"SELECT {agg}(value) FROM MEASURE {MEASURE} "
                        f"IN {tenant}.load "
                        f"TIME BETWEEN {T0} AND {hw} "
                        f"{where}GROUP BY svc LIMIT 100"
                    )
                    t0 = time.perf_counter()
                    try:
                        reply = call(tr, TOPIC_QL, {"ql": ql})
                        q_lat[tenant].append((
                            (time.perf_counter() - t0) * 1000,
                            reply.get("served", "scan"),
                        ))
                    except TransportError as e:
                        if getattr(e, "kind", "") == "shed":
                            q_sheds[tenant] += 1
                        else:
                            q_errors[tenant] += 1
                    except Exception:  # noqa: BLE001
                        q_errors[tenant] += 1
            finally:
                tr.close()

        threads = [
            threading.Thread(target=writer, args=(t, r), daemon=True)
            for t, r in tenants.items()
        ] + [
            threading.Thread(target=querier, args=(t,), daemon=True)
            for t in tenants
        ]
        for th in threads:
            th.start()
        time.sleep(seconds)
        stop.set()
        for th in threads:
            th.join(timeout=30)
        elapsed = time.time() - clock0

        # zero-silent-drop witness: every ACKED point must be served
        # back by a count over the full range (sheds were never acked)
        served_counts = {}
        probe = GrpcTransport()
        try:
            for tenant in tenants:
                r = call(probe, TOPIC_QL, {
                    "ql": f"SELECT count(value) FROM MEASURE {MEASURE} "
                          f"IN {tenant}.load "
                          f"TIME BETWEEN {T0} AND {T0 + (1 << 40)}",
                })
                served_counts[tenant] = int(
                    sum(r["result"]["values"].get("count", ()))
                )
            qos_stats = call(probe, TOPIC_QOS, {})["qos"]["tenants"]
        finally:
            probe.close()

        out: dict = {"seconds": round(elapsed, 1), "tenants": {}}
        for tenant, rate in tenants.items():
            lats = q_lat[tenant]
            scans = sorted(ms for ms, served in lats if served != "replay")
            all_ms = sorted(ms for ms, _s in lats)
            server_side = qos_stats.get(tenant, {})
            out["tenants"][tenant] = {
                "target_rate": rate,
                "quota": quota,
                "acked_points": acked[tenant],
                "acked_rate": round(acked[tenant] / elapsed, 1),
                "write_sheds_client": sheds[tenant],
                "write_shed_server": server_side.get("write_shed", 0),
                "write_errors": write_errors[tenant],
                "silent_drops": max(
                    0, acked[tenant] - served_counts[tenant]
                ),
                "queries": len(lats),
                "query_sheds": q_sheds[tenant],
                "query_errors": q_errors[tenant],
                "p50_ms": round(_percentile(all_ms, 50), 1),
                "p99_ms": round(_percentile(all_ms, 99), 1),
                "scan_p50_ms": round(_percentile(scans, 50), 1),
                "scan_samples": len(scans),
            }
        return out
    finally:
        srv.stop()
        import shutil

        shutil.rmtree(root, ignore_errors=True)
        for k in ("BYDB_QOS", "BYDB_QOS_TENANTS"):
            _os.environ.pop(k, None)
        from banyandb_tpu.qos.plane import reset_qos as _reset

        _reset()


TENANTS_MIN_CORES = 4


def run_tenants(
    *,
    seconds: float = 40.0,
    quota: int = 4000,
    abuse_x: int = 10,
    batch: int = 200,
    seed: int = 0,
    query_interval_ms: int = 250,
    allow_small_host: bool = False,
) -> dict:
    """The ROADMAP item 4 adversarial scenario: one ABUSER tenant
    driving ingest at ``abuse_x`` times its quota beside two compliant
    tenants, after a SOLO baseline phase measuring one compliant tenant
    alone (SAME duration and rate, so both phases scan comparable row
    counts).  The done-bar: compliant scan_p50 within 1.5x of its solo
    baseline, the abuser shed with explicit retryable rejections, zero
    silent drops anywhere.

    Small-host rule (same as --scaling/--expand): on a
    < TENANTS_MIN_CORES host the server, three tenants' clients and
    the abuser's shed attempts all convoy on the same cores, so the
    p50 ratio measures the BOX, not the admission plane — refuse
    unless --allow-small-host, and stamp the artifact with an explicit
    caveat when recorded anyway."""
    import os as _os

    cores = _os.cpu_count() or 1
    small = cores < TENANTS_MIN_CORES
    if small and not allow_small_host:
        raise SystemExit(
            f"load --tenants: host has {cores} cores < "
            f"{TENANTS_MIN_CORES}; the compliant-p50 ratio would "
            "measure core contention, not tenant isolation.  Re-run on "
            "a bigger host, or pass --allow-small-host to record an "
            "explicitly-caveated artifact."
        )
    compliant_rate = max(quota // 2, 1)  # well inside quota
    phase_s = max(seconds * 0.5, 10.0)  # EQUAL phases: comparable scans
    solo = _tenant_phase(
        tenants={"t1": compliant_rate},
        seconds=phase_s,
        batch=batch, seed=seed,
        query_interval_ms=query_interval_ms, quota=quota,
    )
    adversarial = _tenant_phase(
        tenants={
            "t1": compliant_rate,
            "t2": compliant_rate,
            "abuser": quota * abuse_x,
        },
        seconds=phase_s,
        batch=batch, seed=seed + 1,
        query_interval_ms=query_interval_ms, quota=quota,
    )
    solo_p50 = solo["tenants"]["t1"]["scan_p50_ms"]
    compliant = [adversarial["tenants"][t] for t in ("t1", "t2")]
    worst_p50 = max(c["scan_p50_ms"] for c in compliant)
    abuser = adversarial["tenants"]["abuser"]
    out = {
        "phase": "tenants",
        "cores": cores,
        "small_host": small,
        "quota_points_per_s": quota,
        "abuse_x": abuse_x,
        "solo": solo,
        "adversarial": adversarial,
        "solo_scan_p50_ms": solo_p50,
        "worst_compliant_scan_p50_ms": worst_p50,
        "compliant_p50_x": (
            round(worst_p50 / solo_p50, 2) if solo_p50 > 0 else None
        ),
        "abuser_sheds": abuser["write_sheds_client"],
        "abuser_acked_rate": abuser["acked_rate"],
        "silent_drops": sum(
            row["silent_drops"]
            for phase in (solo, adversarial)
            for row in phase["tenants"].values()
        ),
        "compliant_scan_samples": sum(
            c["scan_samples"] for c in compliant
        ),
        "write_errors": sum(
            row["write_errors"]
            for phase in (solo, adversarial)
            for row in phase["tenants"].values()
        ),
        "query_errors": sum(
            row["query_errors"]
            for phase in (solo, adversarial)
            for row in phase["tenants"].values()
        ),
    }
    if small:
        out["caveat"] = (
            f"measured on a {cores}-core host: server + three tenants' "
            "clients + the abuser's shed attempts share cores, so the "
            "compliant-p50 ratio OVERSTATES the abuser's impact; the "
            "ROADMAP <=1.5x bar is only valid on >= "
            f"{TENANTS_MIN_CORES} cores.  Shed/isolation/zero-drop "
            "witnesses are box-independent and binding."
        )
    return out


SCALING_MIN_CORES = 8


def run_expand(
    *,
    seconds: float = 45.0,
    writers: int = 2,
    queriers: int = 2,
    batch: int = 200,
    seed: int = 0,
    start_nodes: int = 3,
    end_nodes: int = 5,
    shard_num: int = 6,
    replicas: int = 1,
    allow_small_host: bool = False,
) -> dict:
    """Live cluster expansion under traffic (ROADMAP item 3 done-bar;
    docs/robustness.md "Elastic cluster"): a ``start_nodes``-node
    cluster of real gRPC data nodes takes sustained writes+queries
    while ``end_nodes - start_nodes`` nodes JOIN and one rebalance
    plan+apply moves their fair share of shards.  The artifact carries
    per-phase (steady / move-window / post-cutover) query p99, the
    move stats, and the zero-acked-loss witness.

    Small-host caveat rules mirror ``--scaling``: parent + nodes +
    clients convoy on a tiny host, so the move-window p99 ratio
    measures the BOX; refuse unless --allow-small-host, and stamp the
    artifact with an explicit caveat when recorded anyway."""
    import os as _os
    import tempfile
    from pathlib import Path

    from banyandb_tpu.api import (
        Catalog,
        DataPointValue,
        Entity,
        FieldSpec,
        FieldType,
        Group,
        Measure,
        ResourceOpts,
        SchemaRegistry,
        TagSpec,
        TagType,
        WriteRequest,
    )
    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo
    from banyandb_tpu.cluster.placement import PlacementSelector
    from banyandb_tpu.cluster.rebalance import Rebalancer
    from banyandb_tpu.cluster.rpc import (
        GrpcBusServer,
        GrpcTransport,
        TransportError,
    )

    cores = _os.cpu_count() or 1
    small = cores < SCALING_MIN_CORES
    if small and not allow_small_host:
        raise SystemExit(
            f"load --expand: host has {cores} cores < {SCALING_MIN_CORES}; "
            "the move-window p99 would measure core contention, not the "
            "mover.  Re-run on a bigger host, or pass --allow-small-host "
            "to record an explicitly-caveated artifact."
        )
    tmp = Path(tempfile.mkdtemp(prefix="bydb-expand-"))

    def schema(reg):
        reg.create_group(Group(
            GROUP, Catalog.MEASURE,
            ResourceOpts(shard_num=shard_num, replicas=replicas),
        ))
        reg.create_measure(Measure(
            group=GROUP, name=MEASURE,
            tags=(TagSpec("svc", TagType.STRING),),
            fields=(FieldSpec("value", FieldType.FLOAT),),
            entity=Entity(("svc",)),
        ))

    def spawn(name):
        reg = SchemaRegistry(tmp / name / "schema")
        schema(reg)
        dn = DataNode(name, reg, tmp / name / "data")
        srv = GrpcBusServer(dn.bus, sync_install=dn.install_synced_parts)
        srv.start()
        return dn, srv, NodeInfo(name, srv.addr)

    dns, servers, infos = {}, {}, []
    for i in range(start_nodes):
        dn, srv, info = spawn(f"x{i}")
        dns[info.name], servers[info.name] = dn, srv
        infos.append(info)
    transport = GrpcTransport()
    lreg = SchemaRegistry(tmp / "liaison" / "schema")
    schema(lreg)
    liaison = Liaison(
        lreg, transport, infos, replicas=replicas,
        placement_store=str(tmp / "liaison" / "placement.json"),
        handoff_root=str(tmp / "liaison" / "handoff"),
    )
    liaison.probe()

    stop = threading.Event()
    acked = [0] * writers
    write_errors = [0]
    samples: list[tuple[float, float]] = []  # (latency_ms, wall_s)
    q_errors = [0]
    clock0 = time.monotonic()
    write_lock = threading.Lock()

    def writer(wid):
        while not stop.is_set():
            with write_lock:
                base = sum(acked)
                pts = tuple(
                    DataPointValue(
                        ts_millis=T0 + (base + i) * writers + wid,
                        tags={"svc": f"s{(base + i) % 24}"},
                        fields={"value": 1.0}, version=1,
                    )
                    for i in range(batch)
                )
            try:
                liaison.write_measure(WriteRequest(GROUP, MEASURE, pts))
                acked[wid] += batch
            except TransportError:
                write_errors[0] += 1  # retryable window: retry next loop
            time.sleep(0.01)

    def querier(qid):
        from banyandb_tpu.api import (
            Aggregation, GroupBy, QueryRequest, TimeRange,
        )

        rng = np.random.default_rng(1000 + seed + qid)
        while not stop.is_set():
            req = QueryRequest(
                groups=(GROUP,), name=MEASURE,
                time_range=TimeRange(T0, T0 + 500_000_000),
                group_by=GroupBy(("svc",)),
                agg=Aggregation(
                    ("count", "sum", "max")[rng.integers(0, 3)], "value"
                ),
            )
            t0 = time.perf_counter()
            try:
                liaison.query_measure(req)
                samples.append((
                    (time.perf_counter() - t0) * 1000,
                    time.monotonic() - clock0,
                ))
            except Exception:  # noqa: BLE001 - counted, load continues
                q_errors[0] += 1
            time.sleep(0.05)

    threads = [
        threading.Thread(target=writer, args=(w,), daemon=True)
        for w in range(writers)
    ] + [
        threading.Thread(target=querier, args=(q,), daemon=True)
        for q in range(queriers)
    ]
    move_stats: dict = {}
    try:
        for th in threads:
            th.start()
        steady_s = max(seconds * 0.3, 5.0)
        time.sleep(steady_s)
        # the JOIN: new nodes appear in the addr book (no re-placement)
        for i in range(start_nodes, end_nodes):
            dn, srv, info = spawn(f"x{i}")
            dns[info.name], servers[info.name] = dn, srv
            with liaison._placement_lock:
                liaison.selector = PlacementSelector(
                    list(liaison.selector.nodes) + [info], liaison.placement
                )
        liaison.probe()
        move_t0 = time.monotonic() - clock0
        reb = Rebalancer(liaison)
        plan = reb.plan()
        move_stats = reb.apply(plan)
        move_t1 = time.monotonic() - clock0
        time.sleep(max(seconds - steady_s - (move_t1 - move_t0), 5.0))
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=30)

    # zero acked-write loss: poll until the full count is served
    total_acked = sum(acked)
    got = -1
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        from banyandb_tpu.api import Aggregation, QueryRequest, TimeRange

        try:
            res = liaison.query_measure(QueryRequest(
                groups=(GROUP,), name=MEASURE,
                time_range=TimeRange(T0, T0 + 500_000_000),
                agg=Aggregation("count", "value"),
            ))
            got = int(sum(res.values.get("count", [])))
            if got == total_acked and not res.degraded:
                break
        except TransportError:
            pass
        time.sleep(0.5)
    transport.close()
    for srv in servers.values():
        srv.stop(grace=0)
    for dn in dns.values():
        dn.measure.close()
        dn.stream.close()
        dn.trace.close()

    def phase_p(xs, q):
        return round(_percentile(xs, q), 1)

    steady = [ms for ms, t in samples if t < move_t0]
    window = [ms for ms, t in samples if move_t0 <= t <= move_t1]
    post = [ms for ms, t in samples if t > move_t1]
    out = {
        "phase": "expand",
        "cores": cores,
        "small_host": small,
        "nodes": {"start": start_nodes, "end": end_nodes},
        "shard_num": shard_num,
        "replicas": replicas,
        "seconds": round(time.monotonic() - clock0, 1),
        "acked": total_acked,
        "served_after_move": got,
        "acked_loss": max(0, total_acked - got),
        "write_errors": write_errors[0],
        "query_errors": q_errors[0],
        "queries": len(samples),
        "move_window_s": round(move_t1 - move_t0, 2),
        "rebalance": move_stats,
        "epoch": move_stats.get("new_epoch"),
        "p99_ms": {
            "steady": phase_p(steady, 99),
            "move_window": phase_p(window, 99),
            "post_cutover": phase_p(post, 99),
        },
        "p50_ms": {
            "steady": phase_p(steady, 50),
            "move_window": phase_p(window, 50),
            "post_cutover": phase_p(post, 50),
        },
        "move_p99_x": (
            round(phase_p(window, 99) / phase_p(steady, 99), 2)
            if steady and window and phase_p(steady, 99) > 0
            else None
        ),
    }
    if small:
        out["caveat"] = (
            f"measured on a {cores}-core host: liaison + {end_nodes} "
            "nodes + clients share cores, so the move-window p99 ratio "
            "OVERSTATES the mover's impact; the ROADMAP <2x bar is only "
            f"valid on >= {SCALING_MIN_CORES} cores"
        )
    return out


def run_scaling(
    *,
    seconds: float = 45.0,
    writers: int = 2,
    queriers: int = 4,
    batch: int = 500,
    seed: int = 0,
    write_rate: int = 0,
    query_interval_ms: int = 0,
    allow_small_host: bool = False,
    steps: tuple[int, ...] = (1, 4),
) -> dict:
    """The 1→4 worker scaling phase (ROADMAP item 2 done-bar): the SAME
    N-querier workload against BYDB_WORKERS=1 then =4, reporting the
    headline scaling ratio, per-phase scan/replay p50 and write errors.

    Guarded like the --max-scan-p50-ms vacuous-pass rule: measuring a
    4-worker fleet on a <8-core host convoys every process onto the
    same cores and reads as a scaling regression of the ENGINE when it
    is a property of the BOX — fail loudly instead of recording it,
    unless the caller explicitly marks the artifact as small-host."""
    import os as _os

    cores = _os.cpu_count() or 1
    small = cores < SCALING_MIN_CORES
    if small and not allow_small_host:
        raise SystemExit(
            f"load --scaling: host has {cores} cores < {SCALING_MIN_CORES}; "
            "the 1->4 worker headline would measure core contention, not "
            "scaling.  Re-run on a bigger host, or pass --allow-small-host "
            "to record an explicitly-caveated artifact."
        )
    phases = {}
    for n in steps:
        phases[f"workers_{n}"] = run_load(
            seconds=seconds, writers=writers, queriers=queriers,
            batch=batch, seed=seed, write_rate=write_rate,
            query_interval_ms=query_interval_ms, workers=n,
        )
    lo, hi = phases[f"workers_{steps[0]}"], phases[f"workers_{steps[-1]}"]
    out = {
        "phase": "worker-scaling",
        "cores": cores,
        "small_host": small,
        "steps": list(steps),
        "phases": phases,
        "qps_scaling": (
            round(hi["queries_per_s"] / lo["queries_per_s"], 2)
            if lo["queries_per_s"]
            else 0.0
        ),
        "scan_p50_scaling": (
            round(lo["scan_p50_ms"] / hi["scan_p50_ms"], 2)
            if hi["scan_p50_ms"]
            else 0.0
        ),
        "write_errors": sum(p["write_errors"] for p in phases.values()),
    }
    if small:
        out["caveat"] = (
            f"measured on a {cores}-core host: parent + workers + "
            "clients share cores, so the ratio UNDERSTATES the engine's "
            "scaling; the ROADMAP >=3x bar is only valid on >= "
            f"{SCALING_MIN_CORES} cores"
        )
    return out


def run_selftrace(
    *,
    seconds: float = 30.0,
    writers: int = 2,
    queriers: int = 4,
    batch: int = 500,
    seed: int = 0,
    write_rate: int = 0,
    query_interval_ms: int = 0,
) -> dict:
    """Self-trace overhead A/B (docs/observability.md "Self-trace"):
    the SAME workload with the dogfood sink OFF then ON at its
    worst-case setting (BYDB_SELF_TRACE_MS=0 — EVERY query's span tree
    queued and mirrored through the server's own TraceEngine), reporting
    ``selftrace_overhead_x`` = on_p50 / off_p50.  The sink's contract is
    shed-never-block, so the ratio is the whole claim: the gate reads
    <= 1.05.  The ON phase also witnesses the sink actually fired
    (``selftrace_spans`` delta) — a gate over a sink that never ran
    would pass vacuously."""
    import os as _os

    from banyandb_tpu.obs import metrics as obs_metrics

    def spans_total() -> float:
        snap = obs_metrics.global_meter().snapshot()
        return snap["counters"].get(("selftrace_spans", ()), 0.0)

    phases = {}
    deltas = {}
    for label in ("off", "on"):
        if label == "on":
            _os.environ["BYDB_SELF_TRACE"] = "1"
            _os.environ["BYDB_SELF_TRACE_MS"] = "0"
        s0 = spans_total()
        try:
            phases[label] = run_load(
                seconds=seconds, writers=writers, queriers=queriers,
                batch=batch, seed=seed, write_rate=write_rate,
                query_interval_ms=query_interval_ms,
            )
        finally:
            _os.environ.pop("BYDB_SELF_TRACE", None)
            _os.environ.pop("BYDB_SELF_TRACE_MS", None)
        deltas[label] = spans_total() - s0
    off_p50 = phases["off"]["latency_ms"]["p50"]
    on_p50 = phases["on"]["latency_ms"]["p50"]
    return {
        "phase": "selftrace",
        "phases": phases,
        "selftrace_spans_off": deltas["off"],
        "selftrace_spans_on": deltas["on"],
        "off_p50_ms": off_p50,
        "on_p50_ms": on_p50,
        "selftrace_overhead_x": (
            round(on_p50 / off_p50, 2) if off_p50 > 0 else None
        ),
        "write_errors": sum(p["write_errors"] for p in phases.values()),
        "query_errors": sum(p["query_errors"] for p in phases.values()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("bydb load (throughput/SLO harness)")
    ap.add_argument("--seconds", type=float, default=60.0)
    ap.add_argument("--writers", type=int, default=2)
    ap.add_argument("--queriers", type=int, default=4)
    ap.add_argument("--batch", type=int, default=500)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--write-rate", type=int, default=0,
        help="total ingest points/s across writers (0 = closed loop)",
    )
    ap.add_argument(
        "--write-rate-x", type=int, default=1,
        help="multiplier on --write-rate (e.g. --write-rate 10000 "
        "--write-rate-x 4 = the ROADMAP item 4 40k points/s run)",
    )
    ap.add_argument(
        "--query-interval-ms", type=int, default=0,
        help="per-querier dashboard poll cadence; 0 = closed loop "
        "(closed-loop clients in the server's interpreter measure GIL "
        "saturation, not latency)",
    )
    ap.add_argument("--min-writes-per-min", type=int, default=0)
    ap.add_argument("--max-p99-ms", type=float, default=0.0)
    ap.add_argument(
        "--max-scan-p50-ms", type=float, default=0.0,
        help="SLO floor on the real-scan (cache-miss) p50 — the "
        "ROADMAP item 4 done-bar reads this field directly",
    )
    ap.add_argument(
        "--workers", type=int, default=0,
        help="shard-owning worker subprocesses (BYDB_WORKERS A/B; "
        "0 = single-process layout)",
    )
    ap.add_argument(
        "--autoreg", action="store_true",
        help="self-driving scenario: register NO manual streamagg "
        "signature and let the bydb-autoreg loop discover the dashboard "
        "pattern (persists the materialized-hit ramp)",
    )
    ap.add_argument(
        "--max-materialize-s", type=float, default=0.0,
        help="SLO ceiling on time_to_materialized_0_9_s under --autoreg "
        "(the ISSUE 12 acceptance reads <= 120); never reaching 0.9 "
        "fails the gate",
    )
    ap.add_argument(
        "--expand", action="store_true",
        help="live cluster-expansion scenario (ROADMAP item 3): real "
        "gRPC data nodes under sustained traffic, N nodes join, one "
        "rebalance plan+apply moves their fair share — persists "
        "per-phase p99 (steady/move-window/post-cutover), the move "
        "stats and the zero-acked-loss witness",
    )
    ap.add_argument(
        "--expand-from", type=int, default=3,
        help="cluster size before the join (default 3)",
    )
    ap.add_argument(
        "--expand-to", type=int, default=5,
        help="cluster size after the join (default 5; the ROADMAP "
        "done-bar reads 3->5)",
    )
    ap.add_argument(
        "--max-move-p99-x", type=float, default=0.0,
        help="SLO ceiling on move-window p99 / steady p99 under "
        "--expand (the ROADMAP done-bar reads < 2.0); unmeasurable on "
        "a small host = failed SLO (vacuous-pass rule)",
    )
    ap.add_argument(
        "--tenants", action="store_true",
        help="multi-tenant adversarial scenario (ROADMAP item 4 "
        "done-bar): one abusive tenant at --abuse-x times its quota "
        "beside two compliant tenants, after a solo compliant "
        "baseline — persists per-tenant p50/p99, shed counts and the "
        "zero-silent-drop witness",
    )
    ap.add_argument(
        "--tenant-quota", type=int, default=4000,
        help="per-tenant ingest quota, points/s (the abuser targets "
        "--abuse-x times this)",
    )
    ap.add_argument(
        "--abuse-x", type=int, default=10,
        help="abuser ingest multiple over its quota (default 10)",
    )
    ap.add_argument(
        "--max-compliant-p50-x", type=float, default=0.0,
        help="SLO ceiling on worst compliant-tenant scan_p50 / solo "
        "baseline scan_p50 under --tenants (the ROADMAP done-bar reads "
        "<= 1.5); zero compliant scan samples = failed SLO "
        "(vacuous-pass rule)",
    )
    ap.add_argument(
        "--selftrace", action="store_true",
        help="self-trace overhead A/B: the same workload with the "
        "dogfood sink off then on at BYDB_SELF_TRACE_MS=0 (every query "
        "mirrored) — persists selftrace_overhead_x = on_p50/off_p50",
    )
    ap.add_argument(
        "--max-selftrace-x", type=float, default=1.05,
        help="SLO ceiling on selftrace_overhead_x under --selftrace "
        "(docs/observability.md reads <= 1.05); an unmeasurable ratio "
        "or a sink that never fired fails the gate (vacuous-pass rule)",
    )
    ap.add_argument(
        "--scaling", action="store_true",
        help="run the 1->4 worker scaling phase instead of one load run "
        "(persists per-phase stats + scaling ratios; requires a host "
        f"with >= {SCALING_MIN_CORES} cores)",
    )
    ap.add_argument(
        "--allow-small-host", action="store_true",
        help="record the scaling artifact on a small host anyway, with "
        "an explicit small_host caveat (the >=3x bar is NOT valid there)",
    )
    ap.add_argument(
        "--min-qps-scaling", type=float, default=0.0,
        help="SLO floor on the 1->4 worker queries/s ratio (the ROADMAP "
        "item 2 done-bar reads >=3.0 on a >=8-core host)",
    )
    ap.add_argument(
        "--out", default="",
        help="also persist the stats JSON to this path "
        "(e.g. docs/load_r06.json)",
    )
    args = ap.parse_args(argv)
    if args.expand:
        stats = run_expand(
            seconds=args.seconds, writers=args.writers,
            queriers=args.queriers, batch=args.batch, seed=args.seed,
            start_nodes=args.expand_from, end_nodes=args.expand_to,
            allow_small_host=args.allow_small_host,
        )
        slo_fail = []
        if stats["acked_loss"]:
            slo_fail.append("acked_loss")
        if stats["query_errors"]:
            slo_fail.append("errors")
        if args.max_move_p99_x:
            if stats["small_host"]:
                # vacuous-pass guard: a ratio measured under core
                # contention must never satisfy the bar
                slo_fail.append("move_p99_unmeasurable_small_host")
            elif (
                stats["move_p99_x"] is None
                or stats["move_p99_x"] > args.max_move_p99_x
            ):
                slo_fail.append("move_p99")
        stats["slo_fail"] = slo_fail
        print(json.dumps(stats))
        if args.out:
            from pathlib import Path

            Path(args.out).write_text(json.dumps(stats, indent=1) + "\n")
        return 1 if slo_fail else 0
    if args.tenants:
        stats = run_tenants(
            seconds=args.seconds, quota=args.tenant_quota,
            abuse_x=args.abuse_x, batch=args.batch, seed=args.seed,
            query_interval_ms=args.query_interval_ms or 250,
            allow_small_host=args.allow_small_host,
        )
        slo_fail = []
        if stats["abuser_sheds"] == 0:
            # the quota never bit: the scenario measured nothing
            slo_fail.append("abuser_not_shed")
        if stats["silent_drops"]:
            slo_fail.append("silent_drops")
        if stats["write_errors"] or stats["query_errors"]:
            slo_fail.append("errors")
        if args.max_compliant_p50_x:
            if stats["small_host"]:
                # vacuous-pass guard: a ratio measured under core
                # contention must never satisfy the bar
                slo_fail.append("compliant_p50_unmeasurable_small_host")
            elif stats["compliant_scan_samples"] == 0 or not stats[
                "compliant_p50_x"
            ]:
                # zero compliant samples (or an unmeasurable solo
                # baseline) must never satisfy the bar either
                slo_fail.append("compliant_p50_unmeasurable")
            elif stats["compliant_p50_x"] > args.max_compliant_p50_x:
                slo_fail.append("compliant_p50")
        stats["slo_fail"] = slo_fail
        print(json.dumps(stats))
        if args.out:
            from pathlib import Path

            Path(args.out).write_text(json.dumps(stats, indent=1) + "\n")
        return 1 if slo_fail else 0
    if args.selftrace:
        stats = run_selftrace(
            seconds=args.seconds, writers=args.writers,
            queriers=args.queriers, batch=args.batch, seed=args.seed,
            write_rate=args.write_rate * max(args.write_rate_x, 1),
            query_interval_ms=args.query_interval_ms,
        )
        slo_fail = []
        if stats["write_errors"] or stats["query_errors"]:
            slo_fail.append("errors")
        if args.max_selftrace_x:
            if stats["selftrace_spans_on"] <= 0:
                # the sink never mirrored a span: the ON phase measured
                # the OFF path twice and the ratio proves nothing
                slo_fail.append("selftrace_sink_never_fired")
            elif stats["selftrace_spans_off"] > 0:
                # the OFF baseline was contaminated by a live sink
                slo_fail.append("selftrace_baseline_contaminated")
            elif stats["selftrace_overhead_x"] is None:
                # off_p50 of 0.0 means no queries completed — an
                # unmeasured SLO is a failed SLO (vacuous-pass rule)
                slo_fail.append("selftrace_unmeasurable")
            elif stats["selftrace_overhead_x"] > args.max_selftrace_x:
                slo_fail.append("selftrace_overhead")
        stats["slo_fail"] = slo_fail
        print(json.dumps(stats))
        if args.out:
            from pathlib import Path

            Path(args.out).write_text(json.dumps(stats, indent=1) + "\n")
        return 1 if slo_fail else 0
    if args.scaling:
        if args.workers:
            # the sweep sets the worker count itself; a silently-ignored
            # flag would mislabel what was measured
            print(
                "load --scaling: --workers is ignored (the phase sweeps "
                "1->4 workers itself)",
                file=sys.stderr,
            )
        stats = run_scaling(
            seconds=args.seconds, writers=args.writers,
            queriers=args.queriers, batch=args.batch, seed=args.seed,
            write_rate=args.write_rate * max(args.write_rate_x, 1),
            query_interval_ms=args.query_interval_ms,
            allow_small_host=args.allow_small_host,
        )
        slo_fail = []
        if stats["write_errors"]:
            slo_fail.append("errors")
        # the single-run SLO gates apply PER PHASE — a gated pipeline
        # passing --max-scan-p50-ms must never sail through on the
        # scaling path unevaluated (vacuous-pass rule)
        for pname, p in stats["phases"].items():
            if (
                args.min_writes_per_min
                and p["write_points_per_min"] < args.min_writes_per_min
            ):
                slo_fail.append(f"write_points_per_min:{pname}")
            if args.max_p99_ms and p["latency_ms"]["p99"] > args.max_p99_ms:
                slo_fail.append(f"p99:{pname}")
            if args.max_scan_p50_ms:
                scan_samples = (
                    p["served"]["scan"] + p["served"]["materialized"]
                )
                if (
                    scan_samples == 0
                    or p["scan_p50_ms"] > args.max_scan_p50_ms
                ):
                    slo_fail.append(f"scan_p50:{pname}")
        if args.min_qps_scaling:
            if stats["small_host"]:
                # vacuous-pass guard, scaling edition: a ratio measured
                # under core contention must never satisfy the bar
                slo_fail.append("qps_scaling_unmeasurable_small_host")
            elif stats["qps_scaling"] < args.min_qps_scaling:
                slo_fail.append("qps_scaling")
        stats["slo_fail"] = slo_fail
        print(json.dumps(stats))
        if args.out:
            from pathlib import Path

            Path(args.out).write_text(json.dumps(stats, indent=1) + "\n")
        return 1 if slo_fail else 0
    stats = run_load(
        seconds=args.seconds, writers=args.writers,
        queriers=args.queriers, batch=args.batch, seed=args.seed,
        write_rate=args.write_rate * max(args.write_rate_x, 1),
        query_interval_ms=args.query_interval_ms,
        workers=args.workers,
        autoreg=args.autoreg,
    )
    slo_fail = []
    if args.max_materialize_s:
        t_m = stats.get("time_to_materialized_0_9_s")
        # vacuous-pass rule: never crossing 0.9 is a failure, not a None
        if t_m is None or t_m > args.max_materialize_s:
            slo_fail.append("time_to_materialized")
    if args.min_writes_per_min and stats["write_points_per_min"] < args.min_writes_per_min:
        slo_fail.append("write_points_per_min")
    if args.max_p99_ms and stats["latency_ms"]["p99"] > args.max_p99_ms:
        slo_fail.append("p99")
    if args.max_scan_p50_ms:
        scan_samples = stats["served"]["scan"] + stats["served"]["materialized"]
        # zero real-scan samples would make the gate pass vacuously
        # (_percentile([]) is 0.0) — an unmeasured SLO is a failed SLO
        if scan_samples == 0 or stats["scan_p50_ms"] > args.max_scan_p50_ms:
            slo_fail.append("scan_p50")
    if stats["write_errors"] or stats["query_errors"]:
        slo_fail.append("errors")
    stats["slo_fail"] = slo_fail
    print(json.dumps(stats))
    if args.out:
        from pathlib import Path

        Path(args.out).write_text(json.dumps(stats, indent=1) + "\n")
    return 1 if slo_fail else 0


if __name__ == "__main__":
    sys.exit(main())
