"""Fast CPU-only trace-surface smoke (scripts/check.sh, both modes + CI).

Proves, in seconds, the trace query surface + self-trace dogfood loop
end-to-end (docs/observability.md "Self-trace"):

1. criteria-only trace queries prune whole blocks BEFORE any read:
   a trace-id lookup skips parts via the bloom sidecar and an int-tag
   criteria scan skips parts via zone maps — both witnessed by
   `blocks_skipped_total{reason=bloom|zone}` deltas — and flipping
   `BYDB_ZONE_SKIP=0` returns byte-identical rows (pruning is an
   optimization, never a filter);
2. the same surface runs distributed: a trace=true 2-node trace query
   returns rows byte-identical to standalone plus ONE merged span tree
   with per-node scatter legs and the liaison merge span;
3. the dogfood loop closes: with `BYDB_SELF_TRACE=1` a traced query's
   span tree is mirrored through the server's own TraceEngine into
   `_monitoring.self_query`, and a bydbql ORDER BY duration_us DESC
   read-back recovers exactly the in-band tree's stages and durations.

Exit 0 on success; any assertion prints a diagnostic and exits 1.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# runnable as `python scripts/trace_smoke.py` from the repo root or CI
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = 1_700_000_000_000
DAY = 86_400_000

TRACE_SCHEMA = {
    "group": "sm",
    "name": "spans",
    "tags": [
        {"name": "trace_id", "type": "string"},
        {"name": "svc", "type": "string"},
        {"name": "duration", "type": "int"},
    ],
    "trace_id_tag": "trace_id",
}


def _schema_obj():
    from banyandb_tpu.api import TagSpec, TagType
    from banyandb_tpu.api.schema import Trace

    return Trace(
        group="sm",
        name="spans",
        tags=(
            TagSpec("trace_id", TagType.STRING),
            TagSpec("svc", TagType.STRING),
            TagSpec("duration", TagType.INT),
        ),
        trace_id_tag="trace_id",
    )


def _batches():
    """Three write batches -> three parts per shard: two day-0 batches
    (durations < 2000) and one two days later (durations >= 5000, so
    day-0 zone maps exclude the scan below entirely)."""
    def day0(lo, hi):
        return [
            (
                T0 + t * 10 + s,
                {"trace_id": f"t{t}", "svc": f"s{t % 3}", "duration": t * 100 + s},
                f"sp-t{t}-{s}".encode(),
            )
            for t in range(lo, hi)
            for s in range(2)
        ]

    seg2 = [
        (
            T0 + 2 * DAY + u * 10 + s,
            {"trace_id": f"u{u}", "svc": f"s{u % 3}", "duration": 5000 + u * 100 + s},
            f"sp-u{u}-{s}".encode(),
        )
        for u in range(4)
        for s in range(2)
    ]
    return day0(0, 10), day0(10, 20), seg2


def _skipped(reason: str) -> float:
    from banyandb_tpu.obs import metrics as obs_metrics

    snap = obs_metrics.global_meter().snapshot()
    return snap["counters"].get(("blocks_skipped", (("reason", reason),)), 0.0)


def _run_ql(engine, ql: str):
    from banyandb_tpu import bydbql
    from banyandb_tpu.query import ql_exec

    _, req = bydbql.parse_with_catalog(ql)
    return ql_exec.execute_trace_ql(engine, req)


def main() -> int:
    from pathlib import Path

    from banyandb_tpu import bydbql
    from banyandb_tpu.api import Catalog, Group, ResourceOpts, SchemaRegistry
    from banyandb_tpu.cli import trace_search_ql
    from banyandb_tpu.models.trace import SpanValue, TraceEngine

    root = Path(tempfile.mkdtemp(prefix="bydb-trace-smoke-"))

    # -- 1: block pruning witnessed by counters, A/B parity ----------------
    reg = SchemaRegistry(root / "sa")
    reg.create_group(Group("sm", Catalog.STREAM, ResourceOpts(shard_num=1)))
    eng = TraceEngine(reg, root / "sa" / "data")
    eng.create_trace(_schema_obj())
    for batch in _batches():
        eng.write(
            "sm",
            "spans",
            [SpanValue(ts, tags, p) for ts, tags, p in batch],
            ordered_tags=("duration",),
        )
        eng.flush()  # one part per batch (+ trace-id bloom sidecars)

    b0 = _skipped("bloom")
    res = _run_ql(eng, trace_search_ql("sm", "spans", where=["trace_id = 'u2'"]))
    bloom_delta = _skipped("bloom") - b0
    assert [r["trace_id"] for r in res.data_points] == ["u2", "u2"], res.data_points
    assert bloom_delta > 0, "trace-id lookup read parts the bloom should skip"

    zone_ql = trace_search_ql("sm", "spans", where=["duration >= 5000"], limit=100)
    z0 = _skipped("zone")
    res_zone = _run_ql(eng, zone_ql)
    zone_delta = _skipped("zone") - z0
    assert len(res_zone.data_points) == 8, len(res_zone.data_points)
    assert zone_delta > 0, "criteria scan read day-0 parts the zone maps exclude"

    os.environ["BYDB_ZONE_SKIP"] = "0"
    try:
        res_noskip = _run_ql(eng, zone_ql)
    finally:
        os.environ.pop("BYDB_ZONE_SKIP", None)
    assert res_noskip.data_points == res_zone.data_points, (
        "zone pruning changed results — it must only skip provably empty blocks"
    )
    print(
        f"# pruning: bloom Δ{bloom_delta:g}, zone Δ{zone_delta:g} blocks "
        "skipped; BYDB_ZONE_SKIP=0 byte-identical"
    )

    # -- 2: distributed trace=true query: parity + merged span tree --------
    import base64
    import dataclasses

    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo
    from banyandb_tpu.cluster.rpc import LocalTransport
    from banyandb_tpu.obs.tracer import iter_spans

    transport = LocalTransport()
    nodes = []
    for i in range(2):
        nreg = SchemaRegistry(root / f"n{i}")
        nreg.create_group(Group("sm", Catalog.STREAM, ResourceOpts(shard_num=4)))
        dn = DataNode(f"d{i}", nreg, root / f"n{i}" / "data")
        nodes.append(NodeInfo(dn.name, transport.register(dn.name, dn.bus)))
    lreg = SchemaRegistry(root / "l")
    lreg.create_group(Group("sm", Catalog.STREAM, ResourceOpts(shard_num=4)))
    lreg.create_trace(_schema_obj())
    liaison = Liaison(lreg, transport, nodes)
    for batch in _batches():
        liaison.write_trace(
            "sm",
            "spans",
            TRACE_SCHEMA,
            [
                {"ts": ts, "tags": tags, "span": base64.b64encode(p).decode()}
                for ts, tags, p in batch
            ],
            ordered_tags=("duration",),
        )

    ordered_ql = trace_search_ql(
        "sm", "spans", order_by="duration", desc=True, limit=6
    )
    _, req = bydbql.parse_with_catalog(ordered_ql)
    res_standalone = _run_ql(eng, ordered_ql)
    res_cluster = liaison.query_trace(dataclasses.replace(req, trace=True))
    assert res_cluster.data_points == res_standalone.data_points, (
        "distributed trace rows diverge from standalone"
    )
    tree = (res_cluster.trace or {}).get("span_tree")
    assert tree, "trace=true must attach a merged span_tree"
    names = [str(s.get("name", "")) for s in iter_spans(tree)]
    scatter_legs = [n for n in names if n.startswith("scatter:")]
    assert len(scatter_legs) >= 2, f"expected 2 scatter legs, got {names}"
    assert "merge" in names, f"liaison merge span missing: {names}"
    print(
        f"# distributed: {len(res_cluster.data_points)} rows byte-identical, "
        f"tree legs {scatter_legs} + merge"
    )

    # -- 3: the dogfood loop: self-trace -> bydbql read-back ---------------
    os.environ["BYDB_SELF_TRACE"] = "1"
    os.environ["BYDB_SELF_TRACE_MS"] = "0"
    try:
        _dogfood_smoke()
    finally:
        os.environ.pop("BYDB_SELF_TRACE", None)
        os.environ.pop("BYDB_SELF_TRACE_MS", None)
    print("trace_smoke: OK")
    return 0


def _dogfood_smoke() -> None:
    from banyandb_tpu.api import Catalog, Group, ResourceOpts
    from banyandb_tpu.cli import SELF_QUERY_QL, trace_search_ql
    from banyandb_tpu.models.trace import SpanValue
    from banyandb_tpu.obs.tracer import iter_spans
    from banyandb_tpu.server import StandaloneServer

    tmp = tempfile.mkdtemp(prefix="bydb-trace-dogfood-")
    srv = StandaloneServer(tmp, port=0, slow_query_ms=0.0)
    try:
        srv.registry.create_group(
            Group("sm", Catalog.TRACE, ResourceOpts(shard_num=1))
        )
        srv.registry.create_trace(_schema_obj())
        srv.trace.write(
            "sm",
            "spans",
            [
                SpanValue(T0 + i, {"trace_id": f"t{i}", "svc": "s0",
                                   "duration": i * 10}, b"x")
                for i in range(8)
            ],
            ordered_tags=("duration",),
        )
        srv.trace.flush()
        out = srv._ql(
            {"ql": trace_search_ql(
                "sm", "spans", order_by="duration", desc=True, limit=3
            )}
        )
        assert out["result"]["data_points"], "traced query returned no rows"
        entry = srv.slowlog.entries()[0]
        expect = {
            (sp.get("name", ""), int(float(sp.get("duration_ms", 0.0)) * 1000))
            for sp in iter_spans(entry["span_tree"])
        }
        wrote = srv.self_trace.flush()
        assert wrote == len(expect), f"mirrored {wrote} spans, tree has {len(expect)}"

        back = srv._ql({"ql": SELF_QUERY_QL.format(limit=50)})
        rows = back["result"]["data_points"]
        got = {(r["tags"]["stage"], r["tags"]["duration_us"]) for r in rows}
        assert got == expect, f"read-back {got} != in-band tree {expect}"
        assert {r["tags"]["engine"] for r in rows} == {"trace"}
        # the read-back itself must not re-enter the sink
        assert srv.self_trace.flush() == 0, "self-trace recursion guard broken"
        print(
            f"# dogfood: {wrote} spans mirrored, bydbql read-back matches "
            "the in-band tree exactly"
        )
    finally:
        srv.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except AssertionError as e:
        print(f"trace_smoke: FAILED: {e}", file=sys.stderr)
        raise SystemExit(1) from e
