"""Standalone-vs-cluster replay soak (docs/soak/g5d + scripts/
soak-vectorized.sh analog).

The reference ran its vectorized engine 48 h against the row engine with
byte-identical replay diffs (576 replays, 0 divergences).  This build's
two independent execution topologies play the same role: a standalone
engine and an N-node cluster hold identical data while randomized BydbQL
queries — interleaved with fresh writes, flushes, and merges so
snapshots move underneath — must return identical results from both.

    PYTHONPATH=. JAX_PLATFORMS=cpu python scripts/soak.py \
        --seconds 300 --seed 7 --report soak-report.jsonl

Every divergence is appended to the report as one JSON line with the
query, both normalized results, and the dataset epoch; exit code 1 if
any divergence occurred.  Importable: run_soak() powers the in-tree
smoke test (tests/test_soak_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

T0 = 1_700_000_000_000
GROUP, MEASURE = "sw", "cpm"
SVCS = 8
REGIONS = 3


def _schema(reg, shard_num):
    from banyandb_tpu.api import (
        Catalog, Entity, FieldSpec, FieldType, Group, Measure,
        ResourceOpts, TagSpec, TagType,
    )

    reg.create_group(
        Group(GROUP, Catalog.MEASURE, ResourceOpts(shard_num=shard_num))
    )
    reg.create_measure(
        Measure(
            group=GROUP, name=MEASURE,
            tags=(
                TagSpec("svc", TagType.STRING),
                TagSpec("region", TagType.STRING),
                TagSpec("status", TagType.INT),
            ),
            fields=(FieldSpec("value", FieldType.INT),),
            entity=Entity(("svc",)),
        )
    )


def _points(rng, epoch, n):
    from banyandb_tpu.api import DataPointValue

    return tuple(
        DataPointValue(
            T0 + epoch * 100_000 + i,
            {
                "svc": f"s{rng.integers(0, SVCS)}",
                "region": f"r{rng.integers(0, REGIONS)}",
                "status": int((200, 404, 500)[rng.integers(0, 3)]),
            },
            {"value": int(rng.integers(0, 1000))},
            version=1,
        )
        for i in range(n)
    )


def _random_ql(rng, epoch) -> str:
    """One random query over everything written so far."""
    t_end = T0 + (epoch + 1) * 100_000
    agg = rng.choice(["count", "sum", "min", "max", "mean"])
    parts = [f"SELECT {agg}(value) FROM MEASURE {MEASURE} IN {GROUP}"]
    parts.append(f"TIME BETWEEN {T0} AND {t_end}")
    r = rng.integers(0, 4)
    if r == 1:
        parts.append(f"WHERE region = 'r{rng.integers(0, REGIONS)}'")
    elif r == 2:
        parts.append(f"WHERE status >= {int(rng.choice([200, 404, 500]))}")
    elif r == 3:
        parts.append(
            f"WHERE svc IN ('s{rng.integers(0, SVCS)}', 's{rng.integers(0, SVCS)}') "
            f"OR region = 'r{rng.integers(0, REGIONS)}'"
        )
    if rng.integers(0, 2):
        parts.append("GROUP BY " + rng.choice(["svc", "region", "svc, region"]))
        if rng.integers(0, 3) == 0:
            parts.append(f"TOP {int(rng.integers(2, 5))} BY value")
    parts.append("LIMIT 200")
    return " ".join(parts)


def _norm(res) -> list:
    """Order-independent comparable form.

    Floats round to 4 SIGNIFICANT digits: the device kernels accumulate
    in f32 (Kahan-bounded per tile) and different topologies partition
    chunks differently, so float aggregates differ by accumulation
    order — measured up to ~4e-5 relative on 600k-row scans (2-shard
    standalone vs 4-shard cluster).  Counts compare exactly."""

    def r(v):
        if isinstance(v, (list, tuple)):
            return tuple(r(x) for x in v)
        if isinstance(v, float):
            return float(f"{v:.4g}") if v == v else v
        return v

    if res.data_points:
        return sorted(
            (dp["timestamp"], tuple(sorted(dp["tags"].items())))
            for dp in res.data_points
        )
    return sorted(
        (tuple(g), tuple(r(res.values[k][i]) for k in sorted(res.values)))
        for i, g in enumerate(res.groups)
    )


def run_soak(
    *,
    seconds: float = 0.0,
    iterations: int = 0,
    seed: int = 0,
    report_path: str | None = None,
    tmp_root: str | None = None,
    write_every: int = 5,
    batch: int = 400,
) -> dict:
    import tempfile

    from banyandb_tpu import bydbql
    from banyandb_tpu.api import SchemaRegistry, WriteRequest
    from banyandb_tpu.cluster import DataNode, Liaison, NodeInfo
    from banyandb_tpu.cluster.rpc import LocalTransport
    from banyandb_tpu.models.measure import MeasureEngine

    root = tmp_root or tempfile.mkdtemp(prefix="bydb-soak-")
    rng = np.random.default_rng(seed)

    sreg = SchemaRegistry(f"{root}/standalone")
    _schema(sreg, shard_num=2)
    standalone = MeasureEngine(sreg, f"{root}/standalone/data")

    transport = LocalTransport()
    nodes = []
    datanodes = []
    for i in range(2):
        reg = SchemaRegistry(f"{root}/n{i}")
        _schema(reg, shard_num=4)
        dn = DataNode(f"d{i}", reg, f"{root}/n{i}/data")
        datanodes.append(dn)
        nodes.append(NodeInfo(dn.name, transport.register(dn.name, dn.bus)))
    lreg = SchemaRegistry(f"{root}/l")
    _schema(lreg, shard_num=4)
    liaison = Liaison(lreg, transport, nodes)

    # Third topology: a mesh-fastpath liaison over the SAME data-node
    # engines (psum/pmin/pmax collectives, parallel/mesh_query.py) —
    # engaged when JAX exposes >=2 devices (force 8 CPU devices via
    # XLA_FLAGS=--xla_force_host_platform_device_count=8; a
    # single-device run soaks two topologies only).
    mesh_liaison = None
    try:
        import jax

        ndev = len(jax.devices())
        if ndev >= 2:
            from banyandb_tpu.parallel import make_mesh

            mreg = SchemaRegistry(f"{root}/lm")
            _schema(mreg, shard_num=4)
            mesh_liaison = Liaison(mreg, transport, nodes)
            mesh_liaison.enable_mesh_fastpath(
                make_mesh(ndev // 2, 2),
                {dn.name: dn.measure for dn in datanodes},
            )
    except Exception:  # noqa: BLE001 — mesh topology is best-effort extra
        mesh_liaison = None

    stats = {"queries": 0, "writes": 0, "divergences": 0, "errors": 0}
    report = open(report_path, "a") if report_path else None
    deadline = time.time() + seconds if seconds else None
    epoch = 0
    try:
        while True:
            if deadline and time.time() >= deadline:
                break
            if iterations and stats["queries"] >= iterations:
                break
            # mutate both topologies identically, keep snapshots moving
            if stats["queries"] % write_every == 0:
                pts = _points(rng, epoch, batch)
                standalone.write(WriteRequest(GROUP, MEASURE, pts))
                liaison.write_measure(WriteRequest(GROUP, MEASURE, pts))
                if epoch % 2 == 0:
                    standalone.flush()
                if epoch % 3 == 0:
                    for db in standalone._tsdbs.values():
                        for seg in db.segments:
                            for shard in seg.shards:
                                shard.merge()
                stats["writes"] += batch
                epoch += 1
            ql = _random_ql(rng, epoch)
            try:
                req = bydbql.parse(ql)
                results = {
                    "standalone": _norm(standalone.query(req)),
                    "cluster": _norm(liaison.query_measure(req)),
                }
                if mesh_liaison is not None:
                    results["mesh"] = _norm(mesh_liaison.query_measure(req))
            except Exception as e:  # noqa: BLE001 - soak must survive
                stats["errors"] += 1
                if report:
                    report.write(json.dumps({"ql": ql, "error": repr(e)}) + "\n")
                    report.flush()
                stats["queries"] += 1
                continue
            base_topo = results["standalone"]
            if any(v != base_topo for v in results.values()):
                stats["divergences"] += 1
                if report:
                    report.write(
                        json.dumps(
                            {"ql": ql, "epoch": epoch,
                             **{k: v[:50] for k, v in results.items()}},
                            default=str,
                        )
                        + "\n"
                    )
                    report.flush()
            stats["queries"] += 1
    finally:
        if report:
            report.close()
    return stats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("bydb soak (replay-diff harness)")
    ap.add_argument("--seconds", type=float, default=60.0)
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop after N queries instead of a time budget")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--report", default="soak-report.jsonl")
    args = ap.parse_args(argv)
    stats = run_soak(
        seconds=0 if args.iterations else args.seconds,
        iterations=args.iterations,
        seed=args.seed,
        report_path=args.report,
    )
    print(json.dumps(stats))
    return 1 if stats["divergences"] else 0


if __name__ == "__main__":
    sys.exit(main())
