"""Fast CPU-only wire-contract smoke (scripts/check.sh, both modes + CI).

Proves, in a few seconds with zero cluster processes, the bdwire
invariants (docs/linting.md "Wire-contract audit"):

1. the live role/topic matrix discovered from the tree equals the
   checked-in golden `EXPECTED_MATRIX` — the wire surface cannot grow
   or shrink without a reviewed diff (printed as the golden table);
2. seeded-violation self-test: every one of the seven analyzers FIRES
   on a tiny synthetic package carrying exactly its violation — the
   audit is not vacuous (a refactor that silently blinds an analyzer
   fails here, not in a post-incident review);
3. (unless --no-audit) the full bdwire family over the real tree is
   ZERO findings — every exemption in wire_config.py carries a reviewed
   reason and none is stale.

`scripts/check.sh` passes --no-audit because its `bdlint --check` gate
just ran the same family; steps 1-2 are this smoke's unique checks.

Exit 0 on success; any assertion prints a diagnostic and exits 1.
"""

from __future__ import annotations

import os
import sys
import tempfile
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# runnable as `python scripts/wire_smoke.py` from the repo root or CI
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# one synthetic package, one violation per analyzer (mirrors the
# fixtures in tests/test_wire_audit.py)
_SEED = {
    "__init__.py": "",
    "bus.py": "TOPIC_PING = 'ping'\nTOPIC_PONG = 'pong'\n",
    "server.py": (
        "from mypkg.bus import TOPIC_PING\n"
        "class Server:\n"
        "    def _register(self):\n"
        "        self.bus.subscribe(TOPIC_PING, self._on_ping)\n"
        "    def _on_ping(self, env):\n"
        "        return {}\n"
    ),
    "client.py": (
        "import os\n"
        "from mypkg.bus import TOPIC_PONG\n"
        "from mypkg.rpc import TransportError\n"
        "RAW = os.environ.get('BYDB_RAW')\n"
        "class Client:\n"
        "    def go(self):\n"
        "        try:\n"
        "            self.transport.call('a', TOPIC_PONG, {})\n"
        "        except TransportError:\n"
        "            pass\n"
    ),
    "rpc.py": (
        "class TransportError(Exception):\n"
        "    def __init__(self, msg, kind='error'):\n"
        "        self.kind = kind\n"
        "class Transport:\n"
        "    def call(self, addr, topic, env):\n"
        "        raise TransportError('busy', kind='sched')\n"
    ),
    "liaison.py": (
        "class Liaison:\n"
        "    def send(self):\n"
        "        return {'rows': 1, 'epoch': 2}\n"
    ),
    "node.py": (
        "class Node:\n"
        "    def on_write(self, env, meter):\n"
        "        meter.counter_add('rogue_metric', 1, {'a': 1})\n"
        "        return env['rows']\n"
    ),
}


def _self_test() -> None:
    from banyandb_tpu.lint.whole_program.callgraph import Program
    from banyandb_tpu.lint.whole_program.layers import parse_package
    from banyandb_tpu.lint.wire.envelopes import analyze_envelopes
    from banyandb_tpu.lint.wire.envregistry import analyze_envflags
    from banyandb_tpu.lint.wire.fault_sites import analyze_fault_sites
    from banyandb_tpu.lint.wire.kinds import analyze_kinds
    from banyandb_tpu.lint.wire.obs_contract import analyze_obs
    from banyandb_tpu.lint.wire.retryable import analyze_retryable
    from banyandb_tpu.lint.wire.topics import analyze_topics

    with tempfile.TemporaryDirectory() as td:
        root = Path(td) / "mypkg"
        root.mkdir()
        for rel, src in _SEED.items():
            (root / rel).write_text(src)
        trees = parse_package(root, "mypkg")
        program = Program.build(root, "mypkg", trees=trees)
        fired = set()
        for f in analyze_topics(
            program, trees,
            roles={"server": ("mypkg.server:Server._register",)},
            client_targets={"mypkg.client": ("server",)},
            exemptions={}, expected_matrix={"server": ("ping",)},
        ):
            fired.add(f.rule)
        for f in analyze_kinds(
            program, declared=("error", "shed"),
            retryable=frozenset({"shed"}),
            error_classes=("TransportError",),
            transport_kinds={}, classifier_switches={},
        ):
            fired.add(f.rule)
        for f in analyze_envelopes(program, groups={"write": {
            "producers": ("mypkg.liaison:Liaison.send",),
            "consumers": ("mypkg.node:Node.on_write",),
            "accepted_write_only": {}, "accepted_silent_default": {},
        }}):
            fired.add(f.rule)
        for f in analyze_fault_sites(
            program, transport_exempt={}, disk_prefixes=("mypkg.",),
            disk_exempt={}, sync_modules=(),
        ):
            fired.add(f.rule)
        for f in analyze_retryable(
            program, error_classes=("TransportError",),
            substrings=("spool",), exempt={},
        ):
            fired.add(f.rule)
        for f in analyze_envflags(
            trees, None, envflag_module="mypkg.envflag",
            envflag_funcs=("env_flag",), prefix="BYDB_",
            flags_doc="flags.md",
        ):
            fired.add(f.rule)
        for f in analyze_obs(trees, None, contract={}, obs_doc="obs.md"):
            fired.add(f.rule)
    want = {
        "wire-topic", "wire-kind", "wire-envelope", "wire-fault",
        "wire-retry", "wire-envflag", "wire-obs",
    }
    assert fired >= want, f"analyzers silent on seeded violations: {want - fired}"
    print(f"# self-test: all {len(want)} analyzers fire on seeded violations")


def main(run_audit: bool = True) -> int:
    import banyandb_tpu
    from banyandb_tpu.lint.whole_program.callgraph import Program
    from banyandb_tpu.lint.whole_program.layers import parse_package
    from banyandb_tpu.lint.wire import run_wire, wire_config
    from banyandb_tpu.lint.wire.topics import role_topic_matrix

    pkg = Path(banyandb_tpu.__file__).parent
    trees = parse_package(pkg, "banyandb_tpu")
    program = Program.build(pkg, "banyandb_tpu", trees=trees)

    # -- 1: live matrix == golden matrix -----------------------------------
    live = {
        role: tuple(sorted(t))
        for role, t in role_topic_matrix(program, trees).items()
    }
    golden = {
        r: tuple(sorted(t)) for r, t in wire_config.EXPECTED_MATRIX.items()
    }
    assert live == golden, (
        "role/topic matrix drifted from wire_config.EXPECTED_MATRIX:\n"
        f"  live:   {live}\n  golden: {golden}"
    )
    print("# role/topic matrix (golden, wire_config.EXPECTED_MATRIX):")
    for role in sorted(live):
        print(f"#   {role:<12} {len(live[role]):>2} topics: "
              + " ".join(live[role]))

    # -- 2: every analyzer fires on its seeded violation -------------------
    _self_test()

    # -- 3: the real tree audits to zero -----------------------------------
    # (--no-audit skips this half when the caller just ran the same
    # family through `python -m banyandb_tpu.lint --check`)
    if run_audit:
        findings, stats = run_wire(program, trees, pkg_root=pkg)
        assert findings == [], "wire findings:\n" + "\n".join(
            f.render() for f in findings
        )
        print(
            f"# bdwire: 0 findings over {stats['wire_topics']} topics / "
            f"{stats['wire_kind_sites']} kind sites"
        )
    print("wire_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(run_audit="--no-audit" not in sys.argv[1:]))
