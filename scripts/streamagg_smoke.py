"""Streaming-aggregation smoke (~3s): materialized rolling windows
answer registered dashboard signatures byte-identically to the full
rescan (docs/performance.md "Continuous streaming aggregation").

Asserts, against a real MeasureEngine (parts + memtable mix):

  1. registration backfills pre-existing rows; ingest across a window
     rotation keeps accumulating (window count grows);
  2. `BYDB_STREAMAGG` A/B: covered, partially-covered (unaligned
     head/tail) and filtered queries return byte-identical result JSON
     vs the full rescan — including after an eviction advances the
     covered horizon (head falls back to a bounded rescan);
  3. the traced query carries a `streamagg` span with coverage tags and
     the `streamagg_rows` / `streamagg_reads{kind}` counters move;
  4. the registry store round-trips: a fresh engine over the same root
     reloads the signature and re-answers with parity (the restart /
     recovery path).

Wired into scripts/check.sh (both modes) and .github/workflows/check.yml.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BYDB_PRECOMPILE", "0")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

T0 = 1_700_000_000_000


def _schema(reg):
    from banyandb_tpu.api.schema import (
        Catalog, Entity, FieldSpec, FieldType, Group, Measure,
        ResourceOpts, TagSpec, TagType,
    )

    reg.create_group(
        Group("sg", Catalog.MEASURE, ResourceOpts(shard_num=2))
    )
    reg.create_measure(Measure(
        group="sg", name="m",
        tags=(
            TagSpec("svc", TagType.STRING),
            TagSpec("region", TagType.STRING),
        ),
        fields=(FieldSpec("v", FieldType.FLOAT),),
        entity=Entity(("svc",)),
    ))


def _write(eng, base: int, n: int, seed: int) -> None:
    rng = np.random.default_rng(seed)
    ts = T0 + base + np.arange(n, dtype=np.int64)
    eng.write_columns(
        "sg", "m",
        ts_millis=ts,
        tags={
            "svc": [f"s{int(x)}" for x in rng.integers(0, 5, n)],
            "region": [f"r{int(x)}" for x in rng.integers(0, 3, n)],
        },
        fields={"v": rng.integers(0, 100, n).astype(np.float64)},
        versions=np.arange(n, dtype=np.int64) + base + 1,
    )


def _queries():
    from banyandb_tpu.api.model import (
        Aggregation, Condition, GroupBy, QueryRequest, TimeRange,
    )

    return [
        ("covered grouped count", QueryRequest(
            groups=("sg",), name="m", time_range=TimeRange(T0, T0 + 4000),
            group_by=GroupBy(("svc",)), agg=Aggregation("count", "v"),
        )),
        ("partial (unaligned head+tail) mean", QueryRequest(
            groups=("sg",), name="m",
            time_range=TimeRange(T0 + 137, T0 + 3791),
            group_by=GroupBy(("svc",)), agg=Aggregation("mean", "v"),
        )),
        ("filtered flat sum", QueryRequest(
            groups=("sg",), name="m", time_range=TimeRange(T0, T0 + 4000),
            agg=Aggregation("sum", "v"),
            criteria=Condition("region", "eq", "r1"),
        )),
        ("min with svc filter", QueryRequest(
            groups=("sg",), name="m", time_range=TimeRange(T0, T0 + 4000),
            group_by=GroupBy(("region",)), agg=Aggregation("min", "v"),
            criteria=Condition("svc", "in", ("s1", "s2")),
        )),
    ]


def _ab(eng, req) -> tuple[str, str]:
    from banyandb_tpu.server import result_to_json

    os.environ["BYDB_STREAMAGG"] = "1"
    on = json.dumps(result_to_json(eng.query(req)), sort_keys=True)
    os.environ["BYDB_STREAMAGG"] = "0"
    off = json.dumps(result_to_json(eng.query(req)), sort_keys=True)
    os.environ["BYDB_STREAMAGG"] = "1"
    return on, off


def main() -> int:
    t_start = time.perf_counter()
    from banyandb_tpu.api.schema import SchemaRegistry
    from banyandb_tpu.models.measure import MeasureEngine
    from banyandb_tpu.obs.metrics import global_meter
    from banyandb_tpu.obs.tracer import Tracer

    tmp = tempfile.mkdtemp(prefix="bydb-streamagg-smoke-")
    reg = SchemaRegistry(tmp + "/schema")
    _schema(reg)
    eng = MeasureEngine(reg, tmp + "/data")

    # 1: backfill of pre-registration rows, then ingest across rotations
    _write(eng, 0, 1100, seed=1)
    info = eng.streamagg.register(
        "sg", "m", key_tags=("region", "svc"), fields=("v",),
        window_millis=1000,
    )
    assert info["rows"] == 1100, f"backfill applied {info['rows']} rows"
    _write(eng, 1100, 1400, seed=2)
    eng.flush()  # parts + memtable mix feeds the A/B rescans below
    _write(eng, 2500, 1300, seed=3)
    st = eng.streamagg.stats()["signatures"][0]
    assert st["windows"] >= 3, f"no rotation: {st['windows']} windows"
    assert st["rows"] == 3800, st

    # 2: A/B byte parity across coverage shapes
    for name, req in _queries():
        on, off = _ab(eng, req)
        assert on == off, f"{name}: materialized != rescan\n{on}\n{off}"

    # eviction advances the covered horizon; head rescan keeps parity
    eng.streamagg.register(
        "sg", "m", key_tags=("svc",), fields=("v",),
        window_millis=1000, max_windows=2,
    )
    evicted = [
        s for s in eng.streamagg.stats()["signatures"]
        if s["key_tags"] == ["svc"]
    ][0]
    assert evicted["covered_from"] is not None, evicted
    from banyandb_tpu.api.model import Aggregation, GroupBy, QueryRequest, TimeRange

    req = QueryRequest(
        groups=("sg",), name="m", time_range=TimeRange(T0, T0 + 4000),
        group_by=GroupBy(("svc",)), agg=Aggregation("sum", "v"),
    )
    on, off = _ab(eng, req)
    assert on == off, "evicted-horizon head rescan broke parity"

    # 3: streamagg span + counters
    tracer = Tracer("smoke")
    eng.query(req, tracer=tracer)
    tree = tracer.finish()
    spans = []

    def walk(node):
        if isinstance(node, dict):
            spans.append(node)
            for c in node.get("children", ()) or ():
                walk(c)

    walk(tree)
    sa = [s for s in spans if s.get("name") == "streamagg"]
    assert sa, f"no streamagg span in {[s.get('name') for s in spans]}"
    assert sa[0]["tags"].get("coverage") in ("covered", "partial"), sa[0]
    counters = global_meter().snapshot()["counters"]
    assert counters.get(("streamagg_rows", ()), 0) >= 3800
    kinds = {
        dict(k[1]).get("kind")
        for k in counters
        if k[0] == "streamagg_reads"
    }
    assert "covered" in kinds or "partial" in kinds, kinds

    # 4: registry store round-trip (restart/recovery path)
    eng.flush()
    eng.close()
    eng2 = MeasureEngine(SchemaRegistry(tmp + "/schema"), tmp + "/data")
    st2 = eng2.streamagg.stats()
    assert len(st2["signatures"]) == 2, st2
    # memtable rows died with eng; windows must equal what a rescan of
    # the surviving parts sees — parity IS the gap-free/no-double oracle
    on, off = _ab(eng2, req)
    assert on == off, "reloaded registry broke parity"
    eng2.close()

    os.environ.pop("BYDB_STREAMAGG", None)
    print(
        "streamagg smoke OK: backfill 1100 rows, "
        f"{st['windows']} windows, A/B parity x{len(_queries()) + 2}, "
        f"span+counters, store round-trip "
        f"({time.perf_counter() - t_start:.1f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
