"""Adaptive-planner smoke (~3s): the self-driving loop end-to-end on a
real standalone server (docs/performance.md "Adaptive planner").

Asserts:

  1. a hot dashboard pattern (repeated streamagg-eligible QL queries,
     NO manual registration) is auto-registered by the bydb-autoreg
     loop and subsequent queries serve class `materialized`;
  2. `cli.py explain` output is sane: plan tree, chosen path, estimated
     vs actual rows (the golden-pinned renderer);
  3. `BYDB_PLANNER` A/B: result JSON byte-identical with the planner
     on/off across the mixed-selectivity probe set;
  4. the planner span + `planner_decisions_total{path}` /
     `autoreg_signatures{source}` instruments move.

Wired into scripts/check.sh (both modes) and
.github/workflows/check.yml.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BYDB_PRECOMPILE", "0")
# the loop is driven EXPLICITLY below (deterministic smoke): keep the
# background thread off, tick by hand
os.environ["BYDB_AUTOREG"] = "0"
os.environ.setdefault("BYDB_PLANNER", "1")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

T0 = 1_700_000_000_000
GROUP, MEASURE = "pg", "m"


def main() -> int:
    import base64

    from banyandb_tpu.cli import render_explain
    from banyandb_tpu.cluster.bus import Topic
    from banyandb_tpu.cluster.rpc import GrpcTransport
    from banyandb_tpu.server import (
        TOPIC_METRICS,
        TOPIC_QL,
        TOPIC_REGISTRY,
        StandaloneServer,
    )

    t_start = time.perf_counter()
    root = tempfile.mkdtemp(prefix="bydb-planner-smoke-")
    srv = StandaloneServer(root, port=0, workers=0)
    srv.start()
    tr = GrpcTransport()

    def call(topic, env, timeout=60.0):
        return tr.call(srv.addr, topic, env, timeout=timeout)

    try:
        call(TOPIC_REGISTRY, {"op": "create", "kind": "group", "item": {
            "name": GROUP, "catalog": "measure",
            "resource_opts": {
                "shard_num": 2, "replicas": 0,
                "segment_interval": {"num": 1, "unit": "day"},
                "ttl": {"num": 7, "unit": "day"}, "stages": [],
            },
        }})
        call(TOPIC_REGISTRY, {"op": "create", "kind": "measure", "item": {
            "group": GROUP, "name": MEASURE,
            "tags": [{"name": "svc", "type": "string"},
                     {"name": "region", "type": "string"}],
            "fields": [{"name": "v", "type": "int"}],
            "entity": {"tag_names": ["svc"]}, "interval": "",
            "index_mode": False,
        }})
        rng = np.random.default_rng(5)
        n = 6000
        ts = T0 + np.arange(n, dtype=np.int64) * 60  # ~6 min: several 60s windows
        call(Topic.MEASURE_WRITE_COLUMNS.value, {
            "group": GROUP, "name": MEASURE,
            "ts": base64.b64encode(ts.astype("<i8").tobytes()).decode(),
            "versions": base64.b64encode(
                np.ones(n, dtype="<i8").tobytes()
            ).decode(),
            "tags": {
                "svc": {
                    "dict": [f"s{i}" for i in range(8)],
                    "codes": base64.b64encode(
                        rng.integers(0, 8, n, dtype=np.int32)
                        .astype("<i4").tobytes()
                    ).decode(),
                },
                "region": {
                    "dict": ["east", "west"],
                    "codes": base64.b64encode(
                        rng.integers(0, 2, n, dtype=np.int32)
                        .astype("<i4").tobytes()
                    ).decode(),
                },
            },
            "fields": {
                "v": base64.b64encode(
                    rng.integers(0, 100, n).astype("<f8").tobytes()
                ).decode(),
            },
        })
        call(Topic.HEALTH.value, {})  # settle
        lo, hi = T0, T0 + n * 60

        dash = (
            f"SELECT sum(v) FROM MEASURE {MEASURE} IN {GROUP} TIME "
            f"BETWEEN {lo} AND {hi} WHERE region = 'east' GROUP BY svc"
        )
        probes = [
            dash,
            f"SELECT count(v) FROM MEASURE {MEASURE} IN {GROUP} TIME "
            f"BETWEEN {lo} AND {hi} GROUP BY region",
            f"SELECT mean(v) FROM MEASURE {MEASURE} IN {GROUP} TIME "
            f"BETWEEN {lo} AND {hi} WHERE svc IN ('s1','s2') "
            f"GROUP BY svc",
        ]

        # -- 1: hot pattern -> auto-registration -> materialized ------
        for _ in range(4):
            call(TOPIC_QL, {"ql": dash})
        made = 0
        for _ in range(5):
            made += srv.autoreg.tick()
            if made:
                break
        assert made >= 1, "autoreg never registered the hot signature"
        rows = srv._streamagg_signature_rows()
        assert rows and rows[0]["origin"] == "auto", rows
        served = call(TOPIC_QL, {"ql": dash}).get("served")
        assert served == "materialized", f"served={served!r}"
        print(f"# auto-registered: {rows[0]['signature']} -> materialized")

        # -- 2: explain output sane ----------------------------------
        reply = call(TOPIC_QL, {"ql": dash, "trace": True})
        text = render_explain(reply)
        assert "plan:" in text and "path: materialized" in text, text
        scan_ql = probes[1]
        os.environ["BYDB_STREAMAGG"] = "0"  # force the scan path
        reply = call(TOPIC_QL, {"ql": scan_ql, "trace": True})
        os.environ["BYDB_STREAMAGG"] = "1"
        text = render_explain(reply)
        assert "estimated rows:" in text and "actual rows:" in text, text
        assert "path: fused" in text or "path: staged" in text, text
        print("# explain renders plan + est-vs-actual rows")

        # -- 3: BYDB_PLANNER A/B byte parity --------------------------
        for ql in probes:
            os.environ["BYDB_PLANNER"] = "1"
            on = json.dumps(
                call(TOPIC_QL, {"ql": ql})["result"], sort_keys=True
            )
            os.environ["BYDB_PLANNER"] = "0"
            off = json.dumps(
                call(TOPIC_QL, {"ql": ql})["result"], sort_keys=True
            )
            os.environ["BYDB_PLANNER"] = "1"
            assert on == off, f"planner parity broke on: {ql}"
        print("# BYDB_PLANNER=0/1 result JSON byte-identical")

        # -- 4: instruments -------------------------------------------
        text = call(TOPIC_METRICS, {})["prometheus"]
        assert 'banyandb_planner_decisions_total{path="materialized"}' in text
        assert 'banyandb_autoreg_signatures{source="auto"}' in text, text
        assert "banyandb_autoreg_registered_total" in text
        print("# planner_decisions_total / autoreg_signatures exported")
    finally:
        tr.close()
        srv.stop()
        import shutil

        shutil.rmtree(root, ignore_errors=True)
    print(f"planner smoke OK in {time.perf_counter() - t_start:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
