"""Elastic-cluster smoke (~5s): live 3->4 node expansion under ingest
(docs/robustness.md "Elastic cluster").

Boots three in-process data nodes behind REAL gRPC bus servers, drives
sustained measure ingest from a writer thread, joins a fourth node, and
runs a full rebalance plan+apply while the writes keep flowing.
Asserts the cutover contract end to end:

  1. ZERO acked-write loss — every write acked before, during and
     after the move is served afterwards;
  2. result-JSON BYTE PARITY — the same fixed-window query returns
     byte-identical JSON before the move and after the cutover;
  3. the epoch bump is OBSERVED — every node's fence sits at the new
     epoch, and the liaison's placement_epoch gauge moved;
  4. a STALE-EPOCH write (a straggling liaison still routing on the
     old map) is observably rejected: retryable kind on the wire and
     stale_epoch_rejected counter > 0;
  5. one replica-REPAIR round runs to convergence (second round ships
     nothing).

Wired into scripts/check.sh (both modes) and the check workflow.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

T0 = 1_700_000_000_000


def _schema(reg, shard_num=4, replicas=1):
    from banyandb_tpu.api import (
        Catalog,
        Entity,
        FieldSpec,
        FieldType,
        Group,
        Measure,
        ResourceOpts,
        TagSpec,
        TagType,
    )

    reg.create_group(
        Group("eg", Catalog.MEASURE,
              ResourceOpts(shard_num=shard_num, replicas=replicas))
    )
    reg.create_measure(
        Measure(
            group="eg", name="m",
            tags=(TagSpec("svc", TagType.STRING),),
            fields=(FieldSpec("v", FieldType.FLOAT),),
            entity=Entity(("svc",)),
        )
    )


def _points(base, n, mod=16):
    from banyandb_tpu.api import DataPointValue

    return tuple(
        DataPointValue(
            ts_millis=T0 + base + i,
            tags={"svc": f"s{(base + i) % mod}"},
            fields={"v": 1.0},
            version=1,
        )
        for i in range(n)
    )


def _count_req(lo=T0, hi=T0 + 50_000_000):
    from banyandb_tpu.api import (
        Aggregation,
        GroupBy,
        QueryRequest,
        TimeRange,
    )

    return QueryRequest(
        groups=("eg",), name="m",
        time_range=TimeRange(lo, hi),
        group_by=GroupBy(("svc",)),
        agg=Aggregation("count", "v"),
    )


def _result_bytes(liaison, req) -> bytes:
    from banyandb_tpu.server import result_to_json

    res = liaison.query_measure(req)
    assert not res.degraded, f"unexpected degradation: {res.unavailable_nodes}"
    return json.dumps(result_to_json(res), sort_keys=True).encode()


def _spawn_node(tmp, name, transport):
    from banyandb_tpu.api import SchemaRegistry
    from banyandb_tpu.cluster import DataNode, NodeInfo
    from banyandb_tpu.cluster.rpc import GrpcBusServer

    reg = SchemaRegistry(tmp / name / "schema")
    _schema(reg)
    dn = DataNode(name, reg, tmp / name / "data")
    srv = GrpcBusServer(dn.bus, sync_install=dn.install_synced_parts)
    srv.start()
    return dn, srv, NodeInfo(name, srv.addr)


def run(tmp_root) -> dict:
    from pathlib import Path

    from banyandb_tpu.api import SchemaRegistry, WriteRequest
    from banyandb_tpu.cluster import Liaison
    from banyandb_tpu.cluster.placement import PlacementSelector
    from banyandb_tpu.cluster.rebalance import Rebalancer, ReplicaRepairer
    from banyandb_tpu.cluster.rpc import GrpcTransport, TransportError
    from banyandb_tpu.obs.metrics import global_meter

    tmp = Path(tmp_root)
    tmp.mkdir(parents=True, exist_ok=True)
    t_start = time.perf_counter()
    stats: dict = {}

    nodes, servers, dns = [], {}, {}
    for i in range(3):
        dn, srv, info = _spawn_node(tmp, f"n{i}", None)
        nodes.append(info)
        servers[info.name] = srv
        dns[info.name] = dn
    transport = GrpcTransport()
    lreg = SchemaRegistry(tmp / "liaison" / "schema")
    _schema(lreg)
    liaison = Liaison(
        lreg, transport, nodes, replicas=1,
        placement_store=str(tmp / "liaison" / "placement.json"),
    )
    liaison.probe()

    acked = [0]
    errors = [0]
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            try:
                liaison.write_measure(
                    WriteRequest("eg", "m", _points(acked[0], 40))
                )
                acked[0] += 40
            except TransportError:
                errors[0] += 1  # retryable window (shed/stale): retry
            time.sleep(0.005)

    try:
        # baseline ingest + the fixed pre-move window snapshot
        liaison.write_measure(WriteRequest("eg", "m", _points(0, 400)))
        acked[0] = 400
        fixed_req = _count_req(T0, T0 + 400)
        before = _result_bytes(liaison, fixed_req)

        th = threading.Thread(target=writer, daemon=True)
        th.start()

        # ---- the join: n3 appears in the addr book, owns nothing yet
        dn3, srv3, info3 = _spawn_node(tmp, "n3", None)
        servers["n3"], dns["n3"] = srv3, dn3
        with liaison._placement_lock:
            liaison.selector = PlacementSelector(
                list(liaison.selector.nodes) + [info3], liaison.placement
            )
        liaison.probe()

        reb = Rebalancer(liaison)
        plan = reb.plan()
        assert plan.moves, "join produced no moves"
        mid_window = []

        def mid_move():
            mid_window.append(acked[0])
            assert liaison.dual_route_shards(), "dual-route window closed"

        apply_stats = reb.apply(plan, mid_move=mid_move)
        stop.set()
        th.join(timeout=10)
        assert apply_stats["ok"] and apply_stats["parts_moved"] > 0
        stats["rebalance"] = apply_stats

        # ---- 3. epoch bump observed everywhere
        assert liaison.placement.epoch == 2, liaison.placement.epoch
        for name, dn in dns.items():
            assert dn.epoch_record.epoch == 2, (name, dn.epoch_record.epoch)
        stats["epoch"] = liaison.placement.epoch

        # ---- 1. zero acked-write loss (writer rows incl. the window)
        deadline = time.monotonic() + 10
        total = -1
        while time.monotonic() < deadline:
            from banyandb_tpu.query import measure_exec  # noqa: F401

            res = liaison.query_measure(_count_req())
            total = int(sum(res.values.get("count", [])))
            if total == acked[0] and not res.degraded:
                break
            time.sleep(0.1)
        assert total == acked[0], f"acked-write loss: {total} != {acked[0]}"
        stats["acked"] = acked[0]
        stats["writer_retries"] = errors[0]

        # ---- 2. byte parity across the cutover (fixed window)
        after = _result_bytes(liaison, fixed_req)
        assert after == before, "pre/post-cutover result JSON diverged"
        stats["parity_bytes"] = len(after)

        # ---- 4. stale-epoch write observably rejected (the straggling
        # liaison: stamps the OLD epoch on a write envelope)
        from banyandb_tpu.cluster import serde
        from banyandb_tpu.cluster.bus import Topic

        env = {
            "request": serde.write_request_to_json(
                WriteRequest("eg", "m", _points(acked[0], 4))
            ),
            "placement_epoch": 1,
        }
        rejected = False
        try:
            transport.call(
                nodes[0].addr, Topic.MEASURE_WRITE.value, env, timeout=5
            )
        except TransportError as e:
            rejected = e.kind == "stale_epoch"
        assert rejected, "stale-epoch write was not rejected"
        snap = global_meter().snapshot()["counters"]
        stale_total = sum(
            v for (name, _labels), v in snap.items()
            if name == "stale_epoch_rejected"
        )
        # the counter lives in the DATA NODE process == this process
        assert stale_total > 0, "stale_epoch_rejected counter did not move"
        stats["stale_rejected_total"] = stale_total

        # ---- 5. one repair round converges (second ships nothing)
        rep = ReplicaRepairer(liaison)
        r1 = rep.run_once()
        r2 = rep.run_once()
        assert r2["parts_shipped"] == 0, (
            f"repair did not converge: round2 shipped {r2['parts_shipped']}"
        )
        stats["repair_round1"] = r1["parts_shipped"]
        assert int(sum(
            liaison.query_measure(_count_req()).values.get("count", [])
        )) == acked[0]
    finally:
        stop.set()
        transport.close()
        for srv in servers.values():
            srv.stop(grace=0)
        for dn in dns.values():
            dn.measure.close()
            dn.stream.close()
            dn.trace.close()
    stats["wall_s"] = round(time.perf_counter() - t_start, 2)
    return stats


def main() -> int:
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bydb-rebalance-smoke-")
    stats = run(tmp)
    print(json.dumps(stats, indent=2, default=str))
    print("rebalance smoke: all invariants held")
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    sys.stderr.flush()
    # same exit contract as chaos.py/server.py: skip grpc C++ teardown
    # (pre-existing abort-at-exit on this gVisor-class kernel)
    os._exit(rc)
