#!/usr/bin/env python
"""bdsan smoke (~5s): prove the runtime sanitizers on a live engine.

Runs the one-shard concurrency stress slice from tests/test_sanitize.py
under BYDB_SANITIZE=1 and checks the full bdsan contract:

- sanitizers install (lock tracing + faulthandler),
- package locks map to their static declaration identities,
- the stress's lock-order witness log is consistent with the declared
  static graph (no undeclared edge between declared locks),
- zero leaked threads/fds after shutdown,
- a seeded leaked thread IS caught (the detector detects).

Exit 0 on success; prints a one-line JSON summary.  Wired into
scripts/check.sh (both modes) and .github/workflows/check.yml.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["BYDB_SANITIZE"] = "1"
os.environ.setdefault("BYDB_PRECOMPILE", "0")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tests"))


def main() -> int:
    from banyandb_tpu import sanitize
    from banyandb_tpu.sanitize import leaks, lockwatch

    assert sanitize.enabled() and sanitize.install() and sanitize.installed()

    # the detector detects: a seeded leak is caught, then cleaned up
    tracker = leaks.LeakTracker(track_fds=False).snapshot()
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="smoke-seeded-leak")
    t.start()
    seeded = tracker.check(grace_s=0.2)
    stop.set()
    t.join()
    if [x.name for x in seeded.threads] != ["smoke-seeded-leak"]:
        print("sanitize_smoke: seeded leak NOT caught", file=sys.stderr)
        return 1

    from test_sanitize import _run_stress

    with tempfile.TemporaryDirectory(prefix="bdsan-smoke-") as root:
        res = _run_stress(Path(root), seconds=2.0)

    undeclared = lockwatch.undeclared_edges(res["new_edges"])
    summary = {
        "written": res["written"],
        "queried": res["queried"],
        "worker_errors": len(res["errors"]),
        "lock_edges_observed": len(res["new_edges"]),
        "undeclared_lock_edges": [
            f"{w.held} -> {w.acquired}" for w in undeclared
        ],
        "leaks": res["leaks"].render() if not res["leaks"].clean() else "none",
    }
    print(json.dumps(summary))
    ok = (
        not res["errors"]
        and res["written"] > 0
        and res["queried"] > 0
        and not undeclared
        and res["leaks"].clean()
    )
    if not ok:
        for err in res["errors"][:5]:
            print(f"sanitize_smoke: worker error: {err}", file=sys.stderr)
        for w in undeclared:
            print(
                f"sanitize_smoke: undeclared lock edge {w.held} -> "
                f"{w.acquired} at {w.site}",
                file=sys.stderr,
            )
        if not res["leaks"].clean():
            print(res["leaks"].render(), file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
