"""Multi-tenant QoS smoke (~3s): the admission plane end-to-end on a
real standalone server (docs/robustness.md "Multi-tenant QoS").

Asserts, over the live gRPC bus wire:

  1. an ABUSER tenant writing at a multiple of its ingest quota is shed
     with the structured retryable wire kind (``kind="shed"``, the
     ServerBusy contract) — never a silent drop — and the per-tenant
     ``qos_write_shed`` counter moves;
  2. a COMPLIANT tenant's writes and queries keep being served while
     the abuser sheds (its counters show zero sheds);
  3. serving-cache partition isolation: a churn storm in one tenant's
     partition evicts nothing from another tenant or the default cache;
  4. single-tenant parity: with the DEFAULT config (QoS on, generous
     limits) an untenanted query's result JSON is byte-identical to the
     plane being off, and the ``qos`` topic + tenant-labeled
     ``qos_*`` metrics are live.

Wired into scripts/check.sh (both modes) and .github/workflows/check.yml.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BYDB_PRECOMPILE", "0")
# quotas for the smoke's tenants; untenanted traffic (tenant `default`)
# keeps the generous defaults — MUST be set before the plane is built
os.environ["BYDB_QOS"] = "1"
os.environ["BYDB_QOS_TENANTS"] = json.dumps({
    "abuser": {"write_rate": 500, "max_concurrent": 2},
    "good": {"weight": 4},
})

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

T0 = 1_700_000_000_000


def _mk_group(call, name: str) -> None:
    from banyandb_tpu.server import TOPIC_REGISTRY

    call(TOPIC_REGISTRY, {"op": "create", "kind": "group", "item": {
        "name": name, "catalog": "measure",
        "resource_opts": {
            "shard_num": 1, "replicas": 0,
            "segment_interval": {"num": 1, "unit": "day"},
            "ttl": {"num": 7, "unit": "day"}, "stages": [],
        },
    }})
    call(TOPIC_REGISTRY, {"op": "create", "kind": "measure", "item": {
        "group": name, "name": "m",
        "tags": [{"name": "svc", "type": "string"}],
        "fields": [{"name": "v", "type": "float"}],
        "entity": {"tag_names": ["svc"]}, "interval": "",
        "index_mode": False,
    }})


def _write(call, group: str, n: int, base: int = 0):
    from banyandb_tpu.cluster.bus import Topic

    return call(Topic.MEASURE_WRITE.value, {"request": {
        "group": group, "name": "m",
        "points": [
            {"ts": T0 + base + i, "tags": {"svc": f"s{i % 3}"},
             "fields": {"v": float(i % 7)}, "version": base + i + 1}
            for i in range(n)
        ],
    }})


def main() -> int:
    t_start = time.perf_counter()
    import numpy as np

    from banyandb_tpu.cluster.rpc import GrpcTransport, TransportError
    from banyandb_tpu.qos.plane import reset_qos
    from banyandb_tpu.server import TOPIC_QL, TOPIC_QOS, StandaloneServer

    reset_qos()  # pick up the env set above even if qos was imported
    tmp = tempfile.mkdtemp(prefix="bydb-qos-smoke-")
    srv = StandaloneServer(tmp, port=0)
    srv.start()
    t = GrpcTransport()

    def call(topic, env, timeout=30.0):
        return t.call(srv.addr, topic, env, timeout=timeout)

    try:
        for g in ("load", "abuser.load", "good.load"):
            _mk_group(call, g)

        # -- 1: abuser shed with the retryable wire kind ------------------
        sheds = 0
        base = 0
        for _ in range(12):
            try:
                _write(call, "abuser.load", 200, base)
                base += 200
            except TransportError as e:
                assert getattr(e, "kind", "") == "shed", (
                    f"abuser rejection must be kind='shed', got "
                    f"{getattr(e, 'kind', '?')}: {e}"
                )
                assert "quota" in str(e), e
                sheds += 1
        assert sheds >= 5, f"abuser at ~4x quota only shed {sheds}/12"
        qstats = call(TOPIC_QOS, {})
        ab = qstats["qos"]["tenants"]["abuser"]
        assert ab["write_shed"] >= sheds, ab

        # -- 2: compliant tenant unaffected -------------------------------
        _write(call, "good.load", 300)
        _write(call, "load", 300)  # untenanted -> default tenant
        r = call(TOPIC_QL, {
            "ql": f"SELECT count(v) FROM MEASURE m IN good.load "
                  f"TIME BETWEEN {T0} AND {T0 + 4000}",
        })
        assert sum(r["result"]["values"]["count"]) == 300, r["result"]
        good = call(TOPIC_QOS, {})["qos"]["tenants"]["good"]
        assert good["write_shed"] == 0 and good["query_shed"] == 0, good
        assert good["query_admitted"] >= 1, good

        # -- 3: cache partition isolation ---------------------------------
        from banyandb_tpu.qos import tenant_scope
        from banyandb_tpu.storage import cache as cache_mod

        with tenant_scope("good"):
            quiet = cache_mod.global_cache()
        quiet.get_or_load(("pin",), lambda: np.zeros(8, np.int8))
        with tenant_scope("abuser"):
            noisy = cache_mod.global_cache()
        noisy.set_cap(4)
        for i in range(200):
            noisy.get_or_load(("n", i), lambda: np.zeros(8, np.int8))
        assert noisy.stats()["evictions"] >= 190
        assert quiet.stats()["evictions"] == 0
        hits0 = quiet.stats()["hits"]
        quiet.get_or_load(
            ("pin",), lambda: (_ for _ in ()).throw(AssertionError)
        )
        assert quiet.stats()["hits"] == hits0 + 1, "pinned entry evicted"

        # -- 4: single-tenant parity + obs plane --------------------------
        ql = {
            "ql": f"SELECT sum(v) FROM MEASURE m IN load "
                  f"TIME BETWEEN {T0} AND {T0 + 4000} GROUP BY svc",
        }
        on = json.dumps(call(TOPIC_QL, dict(ql))["result"], sort_keys=True)
        srv.qos.enabled = False
        off = json.dumps(call(TOPIC_QL, dict(ql))["result"], sort_keys=True)
        srv.qos.enabled = True
        assert on == off, "untenanted QoS on/off results differ"
        from banyandb_tpu.server import TOPIC_METRICS

        text = call(TOPIC_METRICS, {})["prometheus"]
        assert 'banyandb_qos_write_shed_total{tenant="abuser"}' in text
        assert 'banyandb_serving_cache_hits{tenant="good"}' in text
        assert "banyandb_serving_cache_hits " in text  # default: unlabeled
    finally:
        t.close()
        srv.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        for k in ("BYDB_QOS", "BYDB_QOS_TENANTS"):
            os.environ.pop(k, None)
        reset_qos()

    print(
        f"qos smoke OK: abuser shed x{sheds} (kind=shed, counters move), "
        "compliant tenant served, cache partitions isolated, "
        f"single-tenant parity ({time.perf_counter() - t_start:.1f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
