"""Fast CPU-only fused-executor smoke (scripts/check.sh, both modes + CI).

Proves, in seconds on synthetic sources, the fused whole-plan
executor's contract (docs/performance.md "Fused whole-plan executor"):

1. a MULTI-chunk measure part-batch executes as ONE fused XLA program —
   exactly 1 device_execute dispatch + 1 batched device_get for the
   whole part-batch (reduce-span ``path``/``dispatches`` tags);
2. ``BYDB_FUSED=0`` restores the staged per-chunk loop with
   byte-identical partials (raw array bytes) AND result JSON;
3. the resolved fused signature is recorded in the precompile registry
   under kind="fused" and survives a JSON round-trip, so cold starts
   warm the fused kernel.

Exit 0 on success; any assertion prints a diagnostic and exits 1.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BYDB_PRECOMPILE", "1")

# runnable as `python scripts/fused_smoke.py` from the repo root or CI
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = 1_700_000_000_000


def main() -> int:
    import numpy as np

    from banyandb_tpu.api.model import (
        Aggregation,
        Condition,
        GroupBy,
        LogicalExpression,
        QueryRequest,
        TimeRange,
    )
    from banyandb_tpu.api.schema import (
        Entity,
        FieldSpec,
        FieldType,
        Measure,
        TagSpec,
        TagType,
    )
    from banyandb_tpu.obs.tracer import Tracer
    from banyandb_tpu.query import measure_exec, precompile
    from banyandb_tpu.query.measure_exec import (
        compute_partials,
        finalize_partials,
    )
    from banyandb_tpu.server import result_to_json
    from banyandb_tpu.storage.part import ColumnData

    rng = np.random.default_rng(23)
    n = 8192
    m = Measure(
        group="g",
        name="m",
        tags=(TagSpec("svc", TagType.STRING), TagSpec("region", TagType.INT)),
        fields=(FieldSpec("v", FieldType.INT),),
        entity=Entity(("svc",)),
    )
    src = ColumnData(
        ts=T0 + np.arange(n, dtype=np.int64),
        series=np.arange(n, dtype=np.int64) % 64,
        version=np.ones(n, dtype=np.int64),
        tags={
            "svc": rng.integers(0, 8, n).astype(np.int32),
            "region": rng.integers(0, 4, n).astype(np.int32),
        },
        fields={"v": rng.integers(0, 100, n).astype(np.float64)},
        dicts={
            "svc": [b"s%02d" % i for i in range(8)],
            "region": [
                i.to_bytes(8, "little", signed=True) for i in range(4)
            ],
        },
    )
    req = QueryRequest(
        ("g",),
        "m",
        TimeRange(T0, T0 + n),
        criteria=LogicalExpression(
            "and",
            Condition("svc", "eq", "s03"),
            Condition("region", "le", 2),
        ),
        group_by=GroupBy(("svc", "region")),
        field_projection=("v",),
        agg=Aggregation("sum", "v"),
    )

    def partial_bytes(p) -> bytes:
        parts = [p.count.tobytes()]
        for d in (p.sums, p.mins, p.maxs):
            for k in sorted(d):
                parts.append(d[k].tobytes())
        if p.hist is not None:
            parts.append(p.hist.tobytes())
        if p.rep_key is not None:
            parts.append(p.rep_key.tobytes())
        return b"".join(parts)

    def run(fused: bool):
        tr = Tracer("smoke")
        os.environ["BYDB_FUSED"] = "1" if fused else "0"
        try:
            with tr.span("q") as sp:
                partial = compute_partials(m, req, [src], span=sp)
                res = finalize_partials(m, req, [partial], span=sp)
        finally:
            os.environ.pop("BYDB_FUSED", None)
        tree = tr.finish()
        reduce_tags = _find(tree, "reduce")["tags"]
        return partial, res, reduce_tags

    # multi-chunk part-batch: SCAN_CHUNK pinned below the row count
    saved_chunk = measure_exec.SCAN_CHUNK
    measure_exec.SCAN_CHUNK = 2048  # 8192 rows -> a 4-chunk part-batch
    try:
        p_fused, r_fused, t_fused = run(fused=True)
        p_staged, r_staged, t_staged = run(fused=False)
    finally:
        measure_exec.SCAN_CHUNK = saved_chunk

    # -- 1: one dispatch for the whole multi-chunk part-batch --------------
    assert t_fused.get("path") == "fused", t_fused
    assert t_fused.get("chunks") == 4, t_fused
    assert t_fused.get("dispatches") == 1, (
        f"fused 4-chunk part-batch cost {t_fused.get('dispatches')} "
        f"dispatches, want exactly 1: {t_fused}"
    )
    assert t_staged.get("path") == "staged", t_staged
    assert t_staged.get("dispatches") == 4, t_staged
    print(
        f"# fused: {t_fused['chunks']} chunks -> 1 dispatch "
        f"(staged: {t_staged['dispatches']})"
    )

    # -- 2: byte parity staged vs fused ------------------------------------
    assert partial_bytes(p_fused) == partial_bytes(p_staged), (
        "fused partials bytes differ from staged"
    )
    j_fused = json.dumps(result_to_json(r_fused), sort_keys=True)
    j_staged = json.dumps(result_to_json(r_staged), sort_keys=True)
    assert j_fused == j_staged, "fused result JSON differs from staged"
    print(f"# parity: {len(j_fused)} result bytes identical fused/staged")

    # -- 3: fused signature recorded + JSON round-trip ---------------------
    fused_sigs = [
        s
        for kind, s in precompile.default_registry().signatures()
        if kind == "fused"
    ]
    assert fused_sigs, "no fused signature recorded in the registry"
    doc = precompile.spec_to_json("fused", fused_sigs[0])
    kind, back = precompile.spec_from_json(json.loads(json.dumps(doc)))
    assert kind == "fused" and back == fused_sigs[0], (
        "fused signature did not survive the registry JSON round-trip"
    )
    print(f"# registry: {len(fused_sigs)} fused signature(s), round-trip ok")
    print("fused_smoke: OK")
    return 0


def _find(tree: dict, name: str):
    if tree.get("name") == name:
        return tree
    for c in tree.get("children", ()):
        hit = _find(c, name)
        if hit is not None:
            return hit
    return None


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except AssertionError as e:
        print(f"fused_smoke: FAILED: {e}", file=sys.stderr)
        raise SystemExit(1) from e
