#!/usr/bin/env bash
# One-stop pre-PR gate: ruff (generic lint) + bdlint (project-native
# invariants, docs/linting.md) + the tier-1 test command from ROADMAP.md.
# Run from the repo root:  ./scripts/check.sh [--fast]
#   --fast  skip the tier-1 pytest run (lint-only, seconds not minutes)
set -u -o pipefail

cd "$(dirname "$0")/.."
fail=0

echo "== ruff =="
if command -v ruff >/dev/null 2>&1; then
    ruff check banyandb_tpu tests scripts || fail=1
else
    # the container this repo grows in does not ship ruff; the config
    # (ruff.toml) still pins the style for environments that do
    echo "ruff not installed; skipping (config: ruff.toml)"
fi

echo "== bdlint =="
# --fast skips the kernel lowering-audit (XLA compiles); the jaxpr,
# dispatch and budget halves of the kernel audit still run in both modes
if [ "${1:-}" = "--fast" ]; then
    python -m banyandb_tpu.lint --check --fast banyandb_tpu || fail=1
else
    python -m banyandb_tpu.lint --check banyandb_tpu || fail=1
fi

echo "== kernel smoke (bdjit) =="
# budget-table agreement with the plan-audit matrix + obs-plane export
# (docs/linting.md "Kernel audit").  --no-audit: the jaxpr/dispatch
# audit itself just ran inside bdlint --check above — no double work
env JAX_PLATFORMS=cpu python scripts/kernel_smoke.py --no-audit || fail=1

echo "== wire smoke (bdwire) =="
# role/topic matrix == golden, every wire analyzer fires on its seeded
# violation (docs/linting.md "Wire-contract audit").  --no-audit: the
# real-tree wire audit just ran inside bdlint --check above
env JAX_PLATFORMS=cpu python scripts/wire_smoke.py --no-audit || fail=1

echo "== cold-path smoke =="
# tiny store: pipelined == serial byte-identical, precompile registry
# populated + persisted, compile cache active (docs/performance.md)
env JAX_PLATFORMS=cpu python scripts/cold_smoke.py || fail=1

echo "== fused-executor smoke =="
# multi-chunk part-batch = ONE fused dispatch, BYDB_FUSED=0 staged flip
# byte-identical, fused signature recorded + round-tripped
# (docs/performance.md "Fused whole-plan executor")
env JAX_PLATFORMS=cpu python scripts/fused_smoke.py || fail=1

echo "== device-decode smoke =="
# compressed-ship A/B byte parity on a real multi-block part, zone-map
# block skipping with identical results, decode span + shipped-bytes
# counters, fused+decode budget agreement
# (docs/performance.md "Device-side decode & zone maps")
env JAX_PLATFORMS=cpu python scripts/decode_smoke.py || fail=1

echo "== streamagg smoke =="
# materialized rolling windows: registration backfill, ingest across a
# window rotation, BYDB_STREAMAGG=0 A/B byte parity (covered, partial,
# evicted-horizon), streamagg span + counters, registry store
# round-trip (docs/performance.md "Continuous streaming aggregation")
env JAX_PLATFORMS=cpu python scripts/streamagg_smoke.py || fail=1

echo "== planner smoke =="
# self-driving materialization: hot QL pattern -> bydb-autoreg
# registers a window -> served=materialized; explain renders est-vs-
# actual; BYDB_PLANNER=0/1 byte parity; planner/autoreg instruments
# (docs/performance.md "Adaptive planner")
env JAX_PLATFORMS=cpu python scripts/planner_smoke.py || fail=1

echo "== qos smoke =="
# multi-tenant QoS: abuser tenant shed with the retryable kind=shed
# wire rejection + per-tenant counters, compliant tenant served,
# serving-cache partition isolation, single-tenant parity
# (docs/robustness.md "Multi-tenant QoS")
env JAX_PLATFORMS=cpu python scripts/qos_smoke.py || fail=1

echo "== sanitize smoke (bdsan) =="
# live-engine stress slice under BYDB_SANITIZE=1: lock-order witnesses
# consistent with the declared graph, zero leaked threads/fds, seeded
# leak caught (docs/sanitizers.md)
env JAX_PLATFORMS=cpu BYDB_SANITIZE=1 python scripts/sanitize_smoke.py || fail=1

echo "== obs smoke =="
# 2-node traced distributed query: ONE merged span tree with per-node
# subtrees + device/host attribution, trace on/off result parity,
# bucketed stage histograms on /metrics (docs/observability.md)
env JAX_PLATFORMS=cpu python scripts/obs_smoke.py || fail=1

echo "== trace smoke =="
# trace query surface + dogfood loop: bloom/zone block pruning with
# BYDB_ZONE_SKIP=0 byte parity, distributed trace=true query parity +
# merged scatter/merge span tree, BYDB_SELF_TRACE round-trip — the
# in-band span tree read back from _monitoring.self_query via bydbql
# (docs/observability.md "Self-trace")
env JAX_PLATFORMS=cpu python scripts/trace_smoke.py || fail=1

echo "== workers smoke =="
# multi-process data plane: BYDB_WORKERS=2 vs 0 scatter BYTE parity,
# per-worker span graft + labeled /metrics, worker SIGKILL -> restart +
# journal replay with zero acked loss
# (docs/performance.md "Multi-process data plane")
env JAX_PLATFORMS=cpu python scripts/workers_smoke.py || fail=1

echo "== rebalance smoke =="
# elastic cluster: live 3->4 node expansion under sustained ingest —
# zero acked-write loss, pre/post-cutover result byte parity, epoch
# bump observed on every node, stale-epoch write rejected (counter),
# one replica-repair round to convergence
# (docs/robustness.md "Elastic cluster")
env JAX_PLATFORMS=cpu python scripts/rebalance_smoke.py || fail=1

echo "== chaos smoke =="
# 3 in-process data-node kill/restart cycles under the liaison write
# queue + a degradation scenario + a seeded fault schedule + a
# rebalance whose part source is killed mid-move (join/kill schedule,
# holder failover, zero loss): explicit degraded markers, queries
# inside their deadline budget (docs/robustness.md)
env JAX_PLATFORMS=cpu python scripts/chaos.py --smoke || fail=1

if [ "${1:-}" != "--fast" ]; then
    echo "== tier-1 tests (ROADMAP.md, BYDB_SANITIZE=1 via conftest) =="
    rm -f /tmp/_t1.log
    timeout -k 10 870 env JAX_PLATFORMS=cpu BYDB_SANITIZE=1 python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
        | tee /tmp/_t1.log
    rc=${PIPESTATUS[0]}
    echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
    [ "$rc" -ne 0 ] && fail=1
fi

if [ "$fail" -ne 0 ]; then
    echo "check.sh: FAILED"
else
    echo "check.sh: all gates green"
fi
exit "$fail"
