"""Fast CPU-only kernel-audit smoke (scripts/check.sh, both modes + CI).

Proves, in a few seconds with zero device kernel execution, the bdjit
invariants (docs/linting.md "Kernel audit"):

1. the jaxpr + dispatch analyzers run the full builtin + mesh matrix
   with ZERO findings — no host callbacks, no 64-bit dtypes, dispatch/
   transfer counts equal to the checked-in budgets, and every measure/
   stream scenario resolving EXACTLY its precompile-registry builtin
   signature;
2. the budget table agrees with the plan-audit matrix: every
   plan_audit.default_entries() signature has a budget row (ONE matrix
   feeds eval_shape contracts, precompile warming and the budgets);
3. the static dispatch budgets export to the obs plane as
   `kernel_dispatch_budget` gauges (the bound scripts/obs_smoke.py
   asserts against the measured `device_execute` spans).

The lowering-audit (XLA compiles) is exercised by the non-fast
`python -m banyandb_tpu.lint --check` gate, not here — this smoke stays
in the seconds class.

Exit 0 on success; any assertion prints a diagnostic and exits 1.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# runnable as `python scripts/kernel_smoke.py` from the repo root or CI
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(run_audit: bool = True) -> int:
    from banyandb_tpu.lint.kernel import kernel_budgets, run_kernel_audit
    from banyandb_tpu.lint.whole_program.plan_audit import default_entries

    # -- 1: jaxpr + dispatch analyzers clean over the full matrix ----------
    # (--no-audit skips this half when the caller just ran the same
    # analyzers through `python -m banyandb_tpu.lint --check`, the way
    # scripts/check.sh does — steps 2-3 are this smoke's unique checks)
    if run_audit:
        findings = run_kernel_audit(fast=True)
        assert findings == [], "kernel audit findings:\n" + "\n".join(
            f.render() for f in findings
        )
        print("# kernel audit (jaxpr + dispatch + budgets): 0 findings")

    # -- 2: budget table is in agreement with the plan-audit matrix --------
    audited = {e.name for e in default_entries()}
    rows = set(kernel_budgets.BUDGETS)
    assert audited <= rows, f"signatures without a budget row: {audited - rows}"
    extra = rows - audited
    print(
        f"# budget table: {len(rows)} rows cover {len(audited)} plan-audit "
        f"signatures + {len(extra)} executor/mesh rows {sorted(extra)}"
    )

    # -- 3: the static budgets export to the obs plane ---------------------
    from banyandb_tpu.obs.metrics import Meter

    meter = Meter()
    n = kernel_budgets.publish_to_meter(meter)
    text = meter.prometheus_text()
    assert n > 0 and "kernel_dispatch_budget{" in text, (
        "dispatch budgets missing from the exposition"
    )
    print(
        f"# obs export: {n} kernel_dispatch_budget gauges, measure budget = "
        f"{kernel_budgets.dispatch_budget('measure')}/part-batch"
    )
    print("kernel_smoke: OK")
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main(run_audit="--no-audit" not in sys.argv[1:]))
    except AssertionError as e:
        print(f"kernel_smoke: FAILED: {e}", file=sys.stderr)
        raise SystemExit(1) from e
