"""Shared environment construction for the repo-root driver scripts.

One place encodes the container gotcha: every python process loads the
axon sitecustomize via PYTHONPATH, which grabs the (flaky, single-chip)
TPU tunnel at interpreter start.  Child processes that must run on CPU
get a scrubbed environment from here; bench.py and __graft_entry__.py
both use it (tests/conftest.py covers the in-process pytest case with
setdefault semantics instead).

No jax imports allowed in this module — it runs before backend choice.
"""

from __future__ import annotations

import os

REPO_DIR = os.path.dirname(os.path.abspath(__file__))


def scrubbed_cpu_env(
    n_devices: int | None = None, base: dict | None = None
) -> dict:
    """Environment for a CPU child: no axon sitecustomize, repo importable,
    optionally an n-device forced host platform."""
    env = dict(base if base is not None else os.environ)
    parts = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p and p != REPO_DIR
    ]
    env["PYTHONPATH"] = os.pathsep.join([REPO_DIR] + parts)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env
