// bydb_native: host-side hot loops for the TPU-native BanyanDB build.
//
// The reference implements its column codecs in Go (pkg/encoding
// int_list.go, bytes.go); this module is the native equivalent for the
// paths that feed the device: fixed-width delta encode/decode with width
// downcast, zigzag varint (wire compat utility), dictionary code packing,
// and zstd block compression via the system libzstd.  Exposed as a C ABI
// consumed through ctypes (no pybind11 in the image).
//
// Build: make -C cpp   ->  cpp/libbydb_native.so

#include <cstdint>
#include <cstring>
#include <algorithm>

extern "C" {

// ---------------------------------------------------------------------------
// delta codec: values[n] (int64) -> deltas with smallest fitting width.
// Returns the width code (1/2/4/8) and writes n-1 packed deltas to out.
// out must hold (n-1)*8 bytes worst case.  Returns 0 on overflow-free
// success; fills *out_len with bytes written.
// ---------------------------------------------------------------------------

int bydb_delta_encode(const int64_t* values, int64_t n, uint8_t* out,
                      int64_t* out_len, int* width_code) {
  if (n <= 1) {
    *out_len = 0;
    *width_code = 1;
    return 0;
  }
  int64_t lo = INT64_MAX, hi = INT64_MIN;
  for (int64_t i = 1; i < n; ++i) {
    const int64_t d = values[i] - values[i - 1];
    lo = std::min(lo, d);
    hi = std::max(hi, d);
  }
  int width = 8;
  if (lo >= INT8_MIN && hi <= INT8_MAX) width = 1;
  else if (lo >= INT16_MIN && hi <= INT16_MAX) width = 2;
  else if (lo >= INT32_MIN && hi <= INT32_MAX) width = 4;
  *width_code = width;
  uint8_t* p = out;
  for (int64_t i = 1; i < n; ++i) {
    const int64_t d = values[i] - values[i - 1];
    switch (width) {
      case 1: { int8_t v = (int8_t)d; std::memcpy(p, &v, 1); p += 1; break; }
      case 2: { int16_t v = (int16_t)d; std::memcpy(p, &v, 2); p += 2; break; }
      case 4: { int32_t v = (int32_t)d; std::memcpy(p, &v, 4); p += 4; break; }
      default: { std::memcpy(p, &d, 8); p += 8; break; }
    }
  }
  *out_len = p - out;
  return 0;
}

// first + packed deltas -> values[n]
int bydb_delta_decode(int64_t first, const uint8_t* deltas, int64_t n,
                      int width_code, int64_t* out) {
  out[0] = first;
  const uint8_t* p = deltas;
  for (int64_t i = 1; i < n; ++i) {
    int64_t d;
    switch (width_code) {
      case 1: { int8_t v; std::memcpy(&v, p, 1); d = v; p += 1; break; }
      case 2: { int16_t v; std::memcpy(&v, p, 2); d = v; p += 2; break; }
      case 4: { int32_t v; std::memcpy(&v, p, 4); d = v; p += 4; break; }
      default: { std::memcpy(&d, p, 8); p += 8; break; }
    }
    out[i] = out[i - 1] + d;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// zigzag varint (pkg/encoding int_list.go wire shape): utility codec for
// tools that want byte-compatible-style streams.  Returns bytes written.
// ---------------------------------------------------------------------------

int64_t bydb_zigzag_varint_encode(const int64_t* values, int64_t n,
                                  uint8_t* out) {
  uint8_t* p = out;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t z = ((uint64_t)values[i] << 1) ^ (uint64_t)(values[i] >> 63);
    while (z >= 0x80) {
      *p++ = (uint8_t)(z | 0x80);
      z >>= 7;
    }
    *p++ = (uint8_t)z;
  }
  return p - out;
}

int64_t bydb_zigzag_varint_decode(const uint8_t* in, int64_t in_len,
                                  int64_t* out, int64_t max_out) {
  const uint8_t* p = in;
  const uint8_t* end = in + in_len;
  int64_t count = 0;
  while (p < end && count < max_out) {
    uint64_t z = 0;
    int shift = 0;
    while (p < end) {
      const uint8_t b = *p++;
      z |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    out[count++] = (int64_t)(z >> 1) ^ -(int64_t)(z & 1);
  }
  return count;
}

// zstd compression stays on the Python side: utils/compress.py binds the
// system libzstd directly via ctypes, so duplicating the wrapper here
// would only add a second copy of the same call.

// ---------------------------------------------------------------------------
// crc32 (chunked sync integrity; zlib polynomial, table-driven)
// ---------------------------------------------------------------------------

static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t bydb_crc32(const uint8_t* data, int64_t n, uint32_t seed) {
  if (!crc_init_done) crc_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (int64_t i = 0; i < n; ++i)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // extern "C"
