"""bdsan runtime sanitizers: lock-order witnesses + leak tracking +
crash diagnostics.  The dynamic half of the race/leak hunting layer
(docs/sanitizers.md); the static half is bdlint's ``wp-shared-state`` /
``lock-order`` whole-program analyses.

Gate: ``BYDB_SANITIZE=1`` (tests/conftest.py switches it on for the
whole pytest run).  ``install()`` is idempotent and does three things:

1. patches ``threading.Lock``/``RLock`` so package-created locks record
   acquisition-order witness edges mapped to their static declaration
   identities (lockwatch.py);
2. enables ``faulthandler`` so a wedged process dumps every thread's
   stack on SIGABRT/SIGSEGV and on the per-test watchdog
   (``arm_watchdog``/``disarm_watchdog``);
3. exposes the leak-tracking primitives (leaks.py) the conftest
   thread-parity fixture and the stress tests build on.

Everything here is import-light until install() runs: the static lock
model (an AST pass over the package) loads once, lazily.
"""

from __future__ import annotations

from banyandb_tpu.utils.envflag import env_flag


def enabled() -> bool:
    return env_flag("BYDB_SANITIZE", default=False)


_installed = False


def install() -> bool:
    """Install the runtime sanitizers (idempotent).  Returns True when
    active after the call."""
    global _installed
    if _installed:
        return True
    import faulthandler

    from banyandb_tpu.sanitize import lockwatch

    lockwatch.install()
    faulthandler.enable()
    _installed = True
    return True


def installed() -> bool:
    return _installed


def arm_watchdog(timeout_s: float) -> None:
    """Dump every thread's traceback if the process is still inside the
    current unit of work after ``timeout_s`` (non-fatal; the dump goes to
    stderr and work continues).  Re-arming replaces the previous timer."""
    import faulthandler

    faulthandler.dump_traceback_later(timeout_s, exit=False)


def disarm_watchdog() -> None:
    import faulthandler

    faulthandler.cancel_dump_traceback_later()
