"""Instrumented locks: runtime half of the bdsan lock-order contract.

``install()`` replaces ``threading.Lock``/``threading.RLock`` with
factories that wrap locks *created from package code* in a
:class:`TracedLock`.  Each traced lock carries the same
declaration-based identity the static analyzers use
(``module.Class.attr``, mapped through ``Program.lock_sites`` by the
constructor call's source location); locks created outside the package
(stdlib internals, grpc, tests) come back untouched.

Every acquisition records *lock-order witness edges*: acquiring B while
holding A appends the edge ``A -> B`` (first witness only, with thread
name and source site).  The set of runtime-observed edges is compared
against the **declared graph** — the static acquires-while-holding graph
(``lockorder.build_lock_graph``) plus the checked-in
``DECLARED_EXTRA_EDGES`` below for nestings the conservative resolver
cannot see.  A runtime edge between two declaration-mapped locks that is
absent from the declared graph is an ordering the tree never audited:
the stress tests fail on it, and a ``LockWatch`` constructed with an
explicit ``declared`` set reports it as a violation immediately.

Semantics notes:

- Reentrant re-acquisition of the *same declaration* never records an
  edge (two instances of one class share an identity, exactly like the
  static graph — cross-instance ordering is the static self-edge rule's
  business).
- ``Condition`` built on a traced RLock bypasses instrumentation inside
  ``wait()`` (``_release_save``/``_acquire_restore`` delegate to the
  real lock), symmetrically: the held-set stays consistent.
"""

from __future__ import annotations

import os.path
import sys
import threading
from dataclasses import dataclass, field
from typing import Optional

# Real constructors, captured at import time so the watch's own
# bookkeeping lock and the "not package code" fast path never recurse
# into the traced factories.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

# Runtime-observed lock nestings that are REAL and SAFE but invisible to
# the static resolver (calls through untyped variables, e.g.
# ``seg.shards[i].ingest(...)``).  Every entry is a reviewed declaration:
# adding one is an architecture decision, like a layering baseline edit,
# and tests/test_sanitize.py proves the union graph stays acyclic.
# Format: (held id, acquired id).
_NOTIFY = "banyandb_tpu.api.schema.SchemaRegistry._notify_lock"
DECLARED_EXTRA_EDGES: frozenset[tuple[str, str]] = frozenset(
    {
        # engine._tsdb getter reads group opts from the registry while
        # holding the engine map lock (one-way: the registry never calls
        # back into engines)
        (
            "banyandb_tpu.models.measure.MeasureEngine._tsdb_lock",
            "banyandb_tpu.api.schema.SchemaRegistry._lock",
        ),
        # the schema-event drainer holds _notify_lock while delivering
        # watcher callbacks, which read the registry (get_group under
        # _lock), mirror into the property plane (PropertyEngine +
        # InvertedIndex locks) and fan out to watch streams (WatchHub).
        # One-way: mutators queue events under _lock and drain OUTSIDE
        # it, and no watcher target ever re-enters the drainer.
        (_NOTIFY, "banyandb_tpu.api.schema.SchemaRegistry._lock"),
        (_NOTIFY, "banyandb_tpu.cluster.schema_plane.WatchHub._lock"),
        (_NOTIFY, "banyandb_tpu.index.inverted.InvertedIndex._lock"),
        (_NOTIFY, "banyandb_tpu.models.property.PropertyEngine._lock"),
        # shard.ingest serializes the memtable swap, then appends under
        # the memtable's own lock (flush takes them in the same order)
        (
            "banyandb_tpu.storage.tsdb.Shard._lock",
            "banyandb_tpu.storage.memtable.MemTable._lock",
        ),
    }
)


@dataclass
class EdgeWitness:
    held: str
    acquired: str
    thread: str
    site: str  # "file:line" of the acquiring frame


@dataclass
class LockWatch:
    """Edge recorder + (optional) immediate validator.

    declared=None records only; a set of (held, acquired) ids validates
    every new mapped edge on the spot (seeded tests use this)."""

    declared: Optional[frozenset] = None
    reentrant: frozenset = frozenset()
    _mu: object = field(default_factory=_REAL_LOCK)
    _tls: threading.local = field(default_factory=threading.local)

    def __post_init__(self):
        self.edges: dict[tuple[str, str], EdgeWitness] = {}
        self.violations: list[EdgeWitness] = []

    # -- per-thread held stack ------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquire(self, lock_id: str) -> None:
        st = self._stack()
        fresh = [
            (h, lock_id)
            for h in dict.fromkeys(st)
            if h != lock_id and (h, lock_id) not in self.edges
        ]
        st.append(lock_id)
        if not fresh:
            return
        site = _caller_site()
        tname = threading.current_thread().name
        with self._mu:
            for e in fresh:
                if e in self.edges:
                    continue
                w = EdgeWitness(e[0], e[1], tname, site)
                self.edges[e] = w
                if (
                    self.declared is not None
                    and is_declared_id(e[0])
                    and is_declared_id(e[1])
                    and e not in self.declared
                ):
                    self.violations.append(w)

    def note_release(self, lock_id: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == lock_id:
                del st[i]
                return

    # -- snapshots -------------------------------------------------------
    def snapshot_edges(self) -> dict[tuple[str, str], EdgeWitness]:
        with self._mu:
            return dict(self.edges)

    def snapshot_violations(self) -> list[EdgeWitness]:
        with self._mu:
            return list(self.violations)


class TracedLock:
    """Lock/RLock proxy feeding a LockWatch.  Unknown attributes
    delegate to the real lock (Condition integration)."""

    def __init__(self, real, lock_id: str, watch: LockWatch):
        self._real = real
        self.lock_id = lock_id
        self._watch = watch

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._watch.note_acquire(self.lock_id)
        return ok

    def release(self):
        self._real.release()
        self._watch.note_release(self.lock_id)

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._real, name)

    def __repr__(self):
        return f"<TracedLock {self.lock_id} of {self._real!r}>"


# -- static model + global installation ---------------------------------


@dataclass(frozen=True)
class StaticLockModel:
    decl_sites: dict  # (abs path, lineno) -> lock id
    declared: frozenset  # (held, acquired) edges, extras included
    reentrant: frozenset


_model: Optional[StaticLockModel] = None
_watch: Optional[LockWatch] = None
_installed = False
_pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_static() -> StaticLockModel:
    """Build (once) the static lock model from the package AST — the
    declaration-site map and the declared acquires-while-holding graph."""
    global _model
    if _model is None:
        from pathlib import Path

        import banyandb_tpu
        from banyandb_tpu.lint.whole_program.callgraph import Program
        from banyandb_tpu.lint.whole_program.lockorder import build_lock_graph

        pkg = Path(banyandb_tpu.__file__).parent
        program = Program.build(pkg, "banyandb_tpu")
        edges = frozenset(
            (e.held, e.acquired) for e in build_lock_graph(program)
        )
        _model = StaticLockModel(
            decl_sites={
                (os.path.abspath(p), ln): lid
                for (p, ln), lid in program.lock_sites.items()
            },
            declared=edges | DECLARED_EXTRA_EDGES,
            reentrant=frozenset(program.reentrant_locks),
        )
    return _model


def is_declared_id(lock_id: str) -> bool:
    """Ids mapped to a static declaration are dotted; fallback ids for
    unmapped package locks carry a ':'."""
    return ":" not in lock_id


def watch() -> LockWatch:
    global _watch
    if _watch is None:
        _watch = LockWatch()
    return _watch


def _caller_site() -> str:
    """First frame outside this module — where the acquisition happened."""
    f = sys._getframe(1)
    here = os.path.abspath(__file__)
    while f is not None and os.path.abspath(f.f_code.co_filename) == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _creation_site() -> Optional[tuple[str, int]]:
    """(abs path, lineno) of the Lock() construction when it happens in
    package code (sanitize/ itself excluded), else None."""
    f = sys._getframe(2)  # factory -> caller
    if f is None:
        return None
    path = os.path.abspath(f.f_code.co_filename)
    if not path.startswith(_pkg_dir + os.sep):
        return None
    if os.sep + "sanitize" + os.sep in path:
        return None
    return (path, f.f_lineno)


def _identify(site: tuple[str, int]) -> str:
    m = load_static()
    lid = m.decl_sites.get(site)
    if lid is not None:
        return lid
    rel = os.path.relpath(site[0], os.path.dirname(_pkg_dir))
    return f"{rel}:{site[1]}"


def _lock_factory():
    real = _REAL_LOCK()
    site = _creation_site()
    if site is None:
        return real
    return TracedLock(real, _identify(site), watch())


def _rlock_factory():
    real = _REAL_RLOCK()
    site = _creation_site()
    if site is None:
        return real
    return TracedLock(real, _identify(site), watch())


def install() -> None:
    """Patch threading.Lock/RLock with tracing factories (idempotent).
    Loads the static model eagerly so every subsequently created package
    lock maps to its declaration id."""
    global _installed
    if _installed:
        return
    load_static()
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def installed() -> bool:
    return _installed


def undeclared_edges(
    edges=None,
) -> list[EdgeWitness]:
    """Runtime-observed edges between declaration-mapped locks that the
    declared graph does not contain — the stress tests' consistency
    assertion.  Pass an explicit edge dict (e.g. the delta observed
    during a stress window) or default to everything seen so far."""
    m = load_static()
    src = edges if edges is not None else watch().snapshot_edges()
    out = []
    for (a, b), w in sorted(src.items()):
        if a == b or not (is_declared_id(a) and is_declared_id(b)):
            continue
        if (a, b) not in m.declared:
            out.append(w)
    return out
