"""gleak-style thread / file-descriptor leak tracking.

The reference's integration suites wrap every test in gleak's
goroutine-leak assertion; this is the Python analog for the two resource
kinds that actually leak here: threads and fds.

- Threads: snapshot the alive Thread objects, run, then require the set
  to return to baseline (minus allowlisted daemons) within a grace
  window — most stop() paths signal first and join with a timeout, so a
  freshly stopped thread needs a beat to exit.
- Fds: /proc/self/fd snapshots (Linux-only; degrade to empty sets
  elsewhere).  A gc.collect() runs before the final comparison so
  dropped-but-uncollected sockets/files don't read as leaks.

The allowlist names *process-wide singletons by design* — things a test
cannot and should not tear down.  Everything else that lingers is a bug:
fix the owner's stop()/close() instead of widening this list.
"""

from __future__ import annotations

import gc
import os
import re
import threading
import time
from dataclasses import dataclass

# Process-lifetime daemons, matched against Thread.name:
# - pytest-timeout/faulthandler helpers have no Python-visible threads;
# - grpc's default executor threads appear when channels use the global
#   pool (process-wide, reused, never joined by design);
# - the XLA compile cache / jax may keep worker pools alive.
DEFAULT_THREAD_ALLOWLIST: tuple[str, ...] = (
    r"^grpc-default-executor",
    r"^asyncio_\d+$",
    r"^pydevd\.",
)

# fd targets that belong to process-wide singletons created lazily on
# first use (grpc's global event engine allocates one epoll + eventfd
# pair per process and keeps them for the process lifetime) — a scoped
# tracker cannot account for them.  Real file/socket leaks have concrete
# paths and never match.
DEFAULT_FD_TARGET_ALLOWLIST: tuple[str, ...] = (
    r"^anon_inode:\[event",
)


def thread_snapshot() -> frozenset:
    """Baseline snapshot of live threads.  Snapshots the Thread OBJECTS
    (compared by identity), not bare idents — CPython recycles thread
    identifiers, so an ident-keyed baseline would silently miss a leaked
    thread that inherited a dead baseline thread's id."""
    return frozenset(threading.enumerate())


def leaked_threads(
    before: frozenset,
    allowlist: tuple = DEFAULT_THREAD_ALLOWLIST,
    grace_s: float = 2.0,
) -> list:
    """Alive threads that were not in ``before`` and match no allowlist
    pattern, after waiting up to ``grace_s`` for them to finish."""
    pats = [re.compile(p) for p in allowlist]
    deadline = time.monotonic() + grace_s
    while True:
        leaked = [
            t
            for t in threading.enumerate()
            if t.is_alive()
            and t not in before
            and not any(p.search(t.name or "") for p in pats)
        ]
        if not leaked or time.monotonic() >= deadline:
            return leaked
        time.sleep(0.05)


def process_snapshot() -> frozenset:
    """Baseline snapshot of registered child processes (the worker
    pool's subprocesses report spawn/reap through utils.procreg)."""
    from banyandb_tpu.utils import procreg

    return procreg.snapshot()


def leaked_processes(before: frozenset, grace_s: float = 2.0) -> list:
    """(pid, label) for child processes spawned during the scope that
    are still registered — a worker the owner neither stopped nor
    reaped.  The grace window covers a stop() racing the check."""
    from banyandb_tpu.utils import procreg

    deadline = time.monotonic() + grace_s
    while True:
        leaked = procreg.live(exclude=before)
        if not leaked or time.monotonic() >= deadline:
            return leaked
        time.sleep(0.05)


def open_fds() -> set:
    """Open descriptor numbers (Linux /proc; empty set elsewhere)."""
    try:
        return {int(n) for n in os.listdir("/proc/self/fd")}
    except (OSError, ValueError):
        return set()


def fd_target(fd: int) -> str:
    try:
        return os.readlink(f"/proc/self/fd/{fd}")
    except OSError:
        return "<gone>"


def leaked_fds(
    before: set,
    grace_s: float = 1.0,
    target_allowlist: tuple = DEFAULT_FD_TARGET_ALLOWLIST,
) -> list:
    """(fd, target) pairs open now but not at snapshot time.  Collects
    garbage first so unreferenced handles don't count; retries inside the
    grace window because close() on another thread may still be racing."""
    pats = [re.compile(p) for p in target_allowlist]
    deadline = time.monotonic() + grace_s
    while True:
        gc.collect()
        extra = sorted(open_fds() - before)
        # /proc/self/fd listing includes the listing's own dirfd: a lone
        # phantom entry whose target is the fd directory itself is noise
        pairs = [
            (fd, fd_target(fd))
            for fd in extra
        ]
        pairs = [
            p
            for p in pairs
            if p[1] != "<gone>" and not any(r.search(p[1]) for r in pats)
        ]
        if not pairs or time.monotonic() >= deadline:
            return pairs
        time.sleep(0.05)


@dataclass
class LeakReport:
    threads: list
    fds: list
    procs: list = None  # type: ignore[assignment]

    def clean(self) -> bool:
        return not self.threads and not self.fds and not self.procs

    def render(self) -> str:
        lines = []
        for t in self.threads:
            lines.append(f"leaked thread: {t.name} (ident={t.ident})")
        for fd, target in self.fds:
            lines.append(f"leaked fd: {fd} -> {target}")
        for pid, label in self.procs or ():
            lines.append(f"leaked process: {label} (pid={pid})")
        return "\n".join(lines) or "clean"


class LeakTracker:
    """Scoped tracker: snapshot() ... check() -> LeakReport."""

    def __init__(
        self,
        *,
        thread_allowlist: tuple = DEFAULT_THREAD_ALLOWLIST,
        track_fds: bool = True,
    ):
        self.thread_allowlist = tuple(thread_allowlist)
        self.track_fds = track_fds
        self._threads: set = set()
        self._fds: set = set()
        self._procs: frozenset = frozenset()

    def snapshot(self) -> "LeakTracker":
        self._threads = thread_snapshot()
        self._fds = open_fds() if self.track_fds else set()
        self._procs = process_snapshot()
        return self

    def check(self, grace_s: float = 2.0) -> LeakReport:
        threads = leaked_threads(
            self._threads, self.thread_allowlist, grace_s=grace_s
        )
        fds = (
            leaked_fds(self._fds, grace_s=min(grace_s, 1.0))
            if self.track_fds
            else []
        )
        procs = leaked_processes(self._procs, grace_s=min(grace_s, 2.0))
        return LeakReport(threads=threads, fds=fds, procs=procs)
