"""Standalone server: all roles in one process (pkg/cmdsetup/standalone.go
analog) behind the gRPC bus.

Run: python -m banyandb_tpu.server --root /var/lib/banyandb --port 17912

User-facing topics (the MeasureService/StreamService/TraceService/
PropertyService + registry + BydbQLService analog): measure/stream/trace
writes and queries, property apply/get/query, registry CRUD, BydbQL,
health, snapshot.
"""

from __future__ import annotations

import base64
import time
from pathlib import Path

from banyandb_tpu import bydbql
from banyandb_tpu.api import schema as schema_mod
from banyandb_tpu.api.model import QueryRequest, QueryResult
from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.cluster import serde
from banyandb_tpu.cluster.bus import LocalBus, Topic
from banyandb_tpu.admin.accesslog import AccessLog
from banyandb_tpu.admin.metrics import SelfMeasureSink
from banyandb_tpu.obs.tracer import attach_tree
from banyandb_tpu.admin.protector import MemoryProtector
from banyandb_tpu.cluster.rpc import GrpcBusServer
from banyandb_tpu.models.measure import MeasureEngine
from banyandb_tpu.models.property import Property, PropertyEngine
from banyandb_tpu.models.stream import Stream, StreamEngine
from banyandb_tpu.models.trace import Trace, TraceEngine
from banyandb_tpu.qos import tenant_of_group, tenant_scope
from banyandb_tpu.qos.plane import global_qos

# user-facing topics beyond the internal cluster set
TOPIC_QL = "bydbql"
TOPIC_REGISTRY = "registry"
TOPIC_STREAM_QUERY = "stream-query-user"
TOPIC_SNAPSHOT = "snapshot"
TOPIC_METRICS = "metrics"
TOPIC_SLOWLOG = "slowlog"
from banyandb_tpu.admin.diagnostics import DIAG_TOPIC as TOPIC_DIAGNOSTICS  # noqa: E402
TOPIC_TOPN = "topn"
TOPIC_STREAMAGG = "streamagg"
TOPIC_QOS = "qos"

# conservative per-point admission estimate for the memory protector
_POINT_BYTES = 256


def _rss() -> int:
    from banyandb_tpu.admin.protector import process_rss

    return process_rss()


def _served_class(tree: dict) -> str:
    """Classify how a query was answered from its span tree:

    - ``materialized``: a ``streamagg`` span ran — the answer folded
      materialized rolling windows (query/streamagg.py), possibly with
      bounded head/tail rescans;
    - ``replay``: every reduce leg was a partials serving-cache hit —
      the latency measures cache replay, not scan work;
    - ``scan``: at least one real (cache-miss) reduction ran.
    """
    reduce_tags: list[dict] = []
    saw_streamagg = False

    def walk(node):
        nonlocal saw_streamagg
        if not isinstance(node, dict):
            return
        if node.get("name") == "streamagg" and (
            (node.get("tags") or {}).get("coverage") in ("covered", "partial")
        ):
            saw_streamagg = True
        if node.get("name") == "reduce":
            reduce_tags.append(node.get("tags", {}) or {})
        for c in node.get("children", ()) or ():
            walk(c)

    walk(tree)
    if saw_streamagg:
        return "materialized"
    # (a streamagg span tagged coverage="lost" fell back to rescan and
    # is deliberately NOT counted as materialized — see walk() above)
    if reduce_tags and all(
        t.get("partials_cache") == "hit" for t in reduce_tags
    ):
        return "replay"
    return "scan"


def _jsonable(v):
    """bytes anywhere in a reply (data_binary tags, bodies, groups) ride
    as base64 strings — json.dumps must never see raw bytes."""
    if isinstance(v, bytes):
        return base64.b64encode(v).decode()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def result_to_json(res: QueryResult) -> dict:
    out = {
        "groups": [_jsonable(list(g)) for g in res.groups],
        "values": {k: _jsonable(list(vs)) for k, vs in res.values.items()},
        "data_points": [_jsonable(dp) for dp in res.data_points],
    }
    if res.rep_tags:
        out["rep_tags"] = {
            t: _jsonable(list(vs)) for t, vs in res.rep_tags.items()
        }
    if res.trace is not None:
        out["trace"] = res.trace
    if getattr(res, "degraded", False):
        # explicit partial-result markers (docs/robustness.md): callers
        # must be able to tell "empty" from "missing replicas"
        out["degraded"] = True
        out["unavailable_nodes"] = sorted(res.unavailable_nodes)
    return out


class StandaloneServer:
    def __init__(
        self,
        root: str | Path,
        port: int = 17912,
        wire_port: int | None = None,
        http_port: int | None = None,
        pprof_port: int | None = None,
        auth_file: str | None = None,
        slow_query_ms: float | None = None,
        serving_cache_cap: int | None = None,
        workers: int | None = None,
    ):
        from banyandb_tpu.obs import SlowQueryRecorder
        from banyandb_tpu.obs.metrics import global_meter
        from banyandb_tpu.utils.envflag import env_float, env_int

        self.root = Path(root)
        self.registry = SchemaRegistry(self.root)
        self.measure = MeasureEngine(self.registry, self.root / "data")
        self.stream = StreamEngine(self.registry, self.root / "data")
        self.trace = TraceEngine(self.registry, self.root / "data")
        self.property = PropertyEngine(self.registry, self.root / "data")
        # Multi-process data plane (docs/performance.md): BYDB_WORKERS=N
        # maps shard ownership to N worker subprocesses — measure/stream/
        # trace writes partition by shard hash to the owning worker,
        # queries scatter-gather over the intra-node liaison machinery,
        # and result JSON stays byte-identical to the N=0 layout.  The
        # parent engines above then hold no data-plane rows; they keep
        # serving the property plane and schema state.
        self.pool = None
        n_workers = (
            workers if workers is not None else env_int("BYDB_WORKERS", 0)
        )
        if n_workers > 0:
            from banyandb_tpu.cluster.workers import (
                PoolMeasureAdapter,
                PoolStreamAdapter,
                PoolTraceAdapter,
                WorkerPool,
            )

            self.pool = WorkerPool(self.root, self.registry, n_workers)
            self._pool_measure = PoolMeasureAdapter(self.pool)
            self._pool_stream = PoolStreamAdapter(self.pool)
            self._pool_trace = PoolTraceAdapter(self.pool)
        # the process-global registry: engine/executor/fabric instruments
        # (query stages, rpc, lifecycle loops) land in the same exposition
        # as the server's own counters
        self.meter = global_meter()
        # self-measures ride the data plane: in worker mode they route
        # through the pool like any other measure write
        self.self_metrics = SelfMeasureSink(
            self.meter,
            self._pool_measure if self.pool is not None else self.measure,
        )
        # dogfood loop (docs/observability.md "Self-trace"): slow/sampled
        # query span trees persist as trace rows in _monitoring.self_query
        # through the DB's own trace write path (pool-routed like the
        # self-measures when workers own the data plane)
        from banyandb_tpu.obs.selftrace import SelfTraceSink

        self.self_trace = SelfTraceSink(
            self._pool_trace if self.pool is not None else self.trace,
            self.registry,
            node="standalone",
        )
        # multi-tenant QoS (docs/robustness.md "Multi-tenant QoS"):
        # tenant = group namespace; ingest token buckets + weighted
        # query admission shed with the retryable ServerBusy wire kind,
        # and the protector charges in-flight bytes per tenant
        self.qos = global_qos()
        self.protector = MemoryProtector(
            tenant_limit_fn=self.qos.inflight_limit
        )
        from banyandb_tpu.admin.diskmonitor import DiskMonitor

        self.disk = DiskMonitor(self.root)
        # slow-query plane: one threshold governs the access log's slow
        # mark and the flight recorder (server config / BYDB_SLOW_QUERY_MS)
        if slow_query_ms is None:
            slow_query_ms = env_float(
                "BYDB_SLOW_QUERY_MS", AccessLog.DEFAULT_SLOW_QUERY_MS
            )
        self.slow_query_ms = slow_query_ms
        # serving-cache entry capacity (flag > BYDB_SERVING_CACHE_CAP
        # env > bytes-only): the r06 load run showed entry churn is an
        # operator-sized knob, not a constant
        if serving_cache_cap is not None and serving_cache_cap > 0:
            from banyandb_tpu.storage.cache import global_cache

            global_cache().set_cap(serving_cache_cap)
        self.slowlog = SlowQueryRecorder()
        self.access_log = AccessLog(
            self.root / "logs" / "access.log", slow_query_ms=slow_query_ms
        )
        # self-driving materialization (query/planner): the query
        # epilogue feeds per-signature hit counts (slow queries weighted
        # double) and the bydb-autoreg loop registers hot eligible
        # signatures through the same streamagg surface operators use
        from banyandb_tpu.obs.recorder import SignatureStats
        from banyandb_tpu.query import planner as planner_mod
        from banyandb_tpu.query.precompile import default_registry as _pre_reg

        self.sig_stats = SignatureStats()
        self.autoreg = planner_mod.AutoRegistrar(
            self.root / "autoreg.json",
            sig_stats=self.sig_stats,
            register_fn=lambda g, m, kt, f: self._streamagg({
                "op": "register", "group": g, "measure": m,
                "key_tags": list(kt), "fields": list(f),
                "origin": "auto",
            }),
            unregister_fn=lambda g, m, kt, f: bool(
                self._streamagg({
                    "op": "unregister", "group": g, "measure": m,
                    "key_tags": list(kt), "fields": list(f),
                }).get("unregistered")
            ),
            stats_fn=self._streamagg_signature_rows,
            plan_registry=_pre_reg(),
        )
        # schema docs dogfood the property engine (schemaserver analog);
        # the registry's own JSON files remain as a migration-safe mirror
        from banyandb_tpu.cluster.schema_plane import PropertySchemaStore

        self.schema_store = PropertySchemaStore(self.registry, self.property)
        self.bus = LocalBus()
        self._register()
        self.grpc = GrpcBusServer(self.bus, port=port)
        # reference-proto surfaces (banyandb.*.v1 gRPC + HTTP gateway);
        # None disables a tier
        self.wire = None
        self.http = None
        # wire/http surfaces speak to the data plane through whatever
        # shape is live: engines in-process, or the liaison adapters
        # over the worker pool (the cluster_server trio — the pool's
        # embedded Liaison has the same surface as the cluster one)
        if self.pool is not None:
            # every model rides its pool adapter, not a bare liaison
            # one: wire writes must journal-then-forward (the crash
            # contract covers EVERY ack, not just bus writes) and wire
            # TopN needs the pool's scatter plane (topn_scatter)
            _wire_measure = self._pool_measure
            _wire_stream = self._pool_stream
            _wire_trace = self._pool_trace
        else:
            _wire_measure, _wire_stream, _wire_trace = (
                self.measure, self.stream, self.trace,
            )
        if wire_port is not None:
            from banyandb_tpu.api.grpc_server import WireServer, WireServices

            self._wire_services = WireServices(
                self.registry,
                _wire_measure,
                _wire_stream,
                property_engine=self.property,
                trace_engine=_wire_trace,
                node_info={
                    "name": "standalone",
                    "grpc_address": f"127.0.0.1:{wire_port}",
                    "roles": ("data", "liaison"),
                },
                schema_store=self.schema_store,
            )
            self.wire = WireServer(
                self._wire_services, port=wire_port, auth_file=auth_file
            )
        if http_port is not None:
            from banyandb_tpu.api.grpc_server import WireServices
            from banyandb_tpu.api.http_gateway import HttpGateway

            svcs = getattr(self, "_wire_services", None) or WireServices(
                self.registry,
                _wire_measure,
                _wire_stream,
                property_engine=self.property,
                trace_engine=_wire_trace,
            )
            # one users file governs both surfaces: an auth_file that only
            # locked gRPC while HTTP served the same CRUD would be a trap
            http_auth = None
            if auth_file:
                if self.wire is not None and self.wire.auth is not None:
                    http_auth = self.wire.auth
                else:
                    from banyandb_tpu.api.auth import AuthReloader

                    http_auth = AuthReloader(auth_file)
            self.http = HttpGateway(
                svcs, port=http_port, auth=http_auth, slowlog=self.slowlog
            )
        self.pprof = None
        if pprof_port is not None:
            from banyandb_tpu.admin.profiling import ProfilingServer

            self.pprof = ProfilingServer(port=pprof_port)
        # FODC agent plane (fodc/agent analog): watchdog feeds a flight
        # recorder from the node's meter + process stats; the pressure
        # profiler rides it, capturing artifacts when RSS crosses the
        # cgroup-derived threshold.  A FodcAgentClient (admin/fodc_wire)
        # can stream both to a proxy; on-demand pprof capture is served
        # over the bus (PPROF_TOPIC).
        from banyandb_tpu.admin import fodc_agent

        self.flight_recorder = fodc_agent.FlightRecorder()
        self.watchdog = fodc_agent.Watchdog(
            self.flight_recorder,
            [
                fodc_agent.meter_source(self.meter),
                fodc_agent.process_source,
                fodc_agent.io_source(),  # ktm io-monitor host re-scope
            ],
            node_role="standalone",
        )
        self.pressure_profiler = None
        if self.protector.limit:
            self.pressure_profiler = fodc_agent.PressureProfiler(
                self.root / "pressure-profiles",
                limit_bytes=self.protector.limit,
            )
            self.watchdog.add_post_poll_hook(self.pressure_profiler.hook)

    # -- wiring -------------------------------------------------------------
    def _register(self) -> None:
        b = self.bus
        b.subscribe(Topic.HEALTH, lambda env: {"status": "ok", "role": "standalone"})
        from banyandb_tpu.admin import fodc_agent as _fa

        b.subscribe(_fa.PPROF_TOPIC, _fa.pprof_capture_handler)
        b.subscribe(Topic.MEASURE_WRITE, self._measure_write)
        b.subscribe(Topic.MEASURE_WRITE_COLUMNS, self._measure_write_columns)
        b.subscribe(Topic.MEASURE_QUERY_RAW, self._measure_query)
        b.subscribe(Topic.STREAM_WRITE, self._stream_write)
        b.subscribe(Topic.TRACE_WRITE, self._trace_write)
        b.subscribe(Topic.TRACE_QUERY_BY_ID, self._trace_query)
        b.subscribe(Topic.PROPERTY_APPLY, self._property_apply)
        b.subscribe(Topic.PROPERTY_QUERY, self._property_query)
        b.subscribe(TOPIC_QL, self._ql)
        b.subscribe(TOPIC_REGISTRY, self._registry_op)
        b.subscribe(TOPIC_STREAM_QUERY, self._stream_query)
        b.subscribe(TOPIC_SNAPSHOT, self._snapshot)
        b.subscribe(TOPIC_METRICS, self._metrics)
        b.subscribe(TOPIC_SLOWLOG, self._slowlog)
        b.subscribe(TOPIC_DIAGNOSTICS, self._diagnostics)
        b.subscribe(TOPIC_TOPN, self._topn)
        b.subscribe(TOPIC_STREAMAGG, self._streamagg)
        b.subscribe(TOPIC_QOS, self._qos)

    # -- handlers -----------------------------------------------------------
    def _measure_write(self, env):
        req = serde.write_request_from_json(env["request"])
        size = len(req.points) * _POINT_BYTES
        # write-side admission control (protector.AcquireResource +
        # disk_monitor.go:86 analogs, plus the per-tenant QoS token
        # bucket): shed load with ServerBusy / DiskFull instead of
        # OOMing or filling the data filesystem — never a silent drop
        self.disk.check_write()
        tenant = self.qos.admit_write(req.group, len(req.points))
        self.protector.acquire(size, tenant=tenant)
        t0 = time.perf_counter()
        try:
            with tenant_scope(tenant):
                if self.pool is not None:
                    # shard-partitioned forward to the owning workers
                    # (journaled ack — docs/performance.md)
                    n = self.pool.write_measure(req)
                else:
                    # batch decode -> columns -> bulk path (identical
                    # semantics to the row path incl. TopN observation;
                    # VERDICT r4 missing #3)
                    n = self.measure.write_points_bulk(req)
        finally:
            self.protector.release(size, tenant=tenant)
        ms = (time.perf_counter() - t0) * 1000
        self.meter.counter_add("measure_write_points", n)
        self.meter.observe("write_ms", ms, {"model": "measure"})
        self.access_log.log_write(req.group, req.name, n, ms, tenant=tenant)
        return {"written": n}

    def _measure_write_columns(self, env):
        """Columnar write envelope (Topic.MEASURE_WRITE_COLUMNS): ts and
        numeric fields ride as base64-packed little-endian arrays, tag
        columns as JSON string lists or {"dict": [...], "codes": b64-i32}
        dictionary pairs.  One decode pass feeds write_columns — the
        envelope exists because per-point JSON dicts were the measured
        hot loop of the wire ingest path (VERDICT r4 weak #3)."""
        group, name = env["group"], env["name"]
        # row count from base64 length arithmetic — the ts column is
        # decoded exactly once, inside the codec (or the pool's router)
        ts_b64 = env["ts"]
        pad = 2 if ts_b64.endswith("==") else (1 if ts_b64.endswith("=") else 0)
        n = ((len(ts_b64) // 4) * 3 - pad) // 8
        size = n * _POINT_BYTES
        self.disk.check_write()
        tenant = self.qos.admit_write(group, n)
        self.protector.acquire(size, tenant=tenant)
        t0 = time.perf_counter()
        try:
            with tenant_scope(tenant):
                if self.pool is not None:
                    # vectorized shard routing + per-worker envelope
                    # slices (cluster/workers.py); the codes stay
                    # dictionary-encoded end-to-end on both paths
                    written = self.pool.write_measure_columns(env)
                else:
                    # shared wire codec (cluster/serde.py): engine +
                    # memtable consume the decoded codes directly
                    written = self.measure.write_columns(
                        **serde.write_columns_env_decode(env)
                    )
        finally:
            self.protector.release(size, tenant=tenant)
        ms = (time.perf_counter() - t0) * 1000
        self.meter.counter_add("measure_write_points", written)
        self.meter.observe("write_ms", ms, {"model": "measure"})
        self.access_log.log_write(group, name, written, ms, tenant=tenant)
        return {"written": written}

    def _admit_query(self, req, env):
        """Weighted per-tenant query admission (docs/robustness.md
        "Multi-tenant QoS"): entering the returned ticket may queue
        while the query's propagated deadline still has headroom, then
        sheds with the retryable ServerBusy wire kind."""
        deadline_ms = env.get("deadline_ms")
        return self.qos.admit_query(
            req.groups[0] if req.groups else "",
            deadline_s=(
                float(deadline_ms) / 1000.0 if deadline_ms else None
            ),
        )

    @staticmethod
    def _tag_qos(tracer, adm) -> None:
        """The ``qos`` span on the obs plane: which tenant ran, and how
        long admission queued it (only tagged when it actually queued)."""
        with tracer.span("qos") as sp:
            sp.tag("tenant", adm.tenant)
            if adm.queued_ms >= 1.0:
                sp.tag("queued_ms", round(adm.queued_ms, 2))

    def _measure_query(self, env):
        from banyandb_tpu.obs import Tracer

        # the server always runs a tracer (a handful of spans per query,
        # sub-microsecond): slow queries land in the flight recorder with
        # their full tree whether or not the client asked for trace=true;
        # the tree only rides the RESPONSE when req.trace is set
        tracer = Tracer("standalone:measure")
        with tracer.span("wire_decode"):
            req = serde.query_request_from_json(env["request"])
        adm = self._admit_query(req, env)
        with adm, tenant_scope(adm.tenant):
            self._tag_qos(tracer, adm)
            t0 = time.perf_counter()
            if self.pool is not None:
                res = self.pool.query_measure(req, tracer=tracer)
            else:
                res = self.measure.query(req, tracer=tracer)
            ms = (time.perf_counter() - t0) * 1000
        tree = tracer.finish()
        self.meter.observe("measure_query_ms", ms)
        self._observe_query(
            "measure", req, ms,
            rows=len(res.data_points) or len(res.groups),
            tree=tree, res=res, tenant=adm.tenant,
        )
        attach_tree(res, req, tree)
        return {"result": result_to_json(res)}

    def _observe_query(
        self, engine: str, req, ms: float, *, rows: int, tree: dict,
        res=None, ql=None, tenant: str = "",
    ) -> None:
        """Shared query epilogue: access log + slow-query flight record
        (span tree + plan text, bounded ring — cli.py slowlog)."""
        from banyandb_tpu.obs.recorder import record_slow_query

        group = req.groups[0] if req.groups else ""
        tenant = tenant or tenant_of_group(group)
        self.access_log.log_query(
            group, req.name, ms, ql=ql, rows=rows, tenant=tenant
        )
        if engine == "measure":
            # autoreg evidence: every measure query's streamagg-eligible
            # signature counts; slow ones count double (materialization
            # helps them most)
            from banyandb_tpu.query import planner as planner_mod

            self.sig_stats.observe(
                planner_mod.signature_of(req),
                weight=2 if ms >= self.slow_query_ms else 1,
            )

        def render_plan():
            # post-hoc plan render: slow queries only, never hot
            from banyandb_tpu.query import logical

            if engine == "measure":
                m = self.registry.get_measure(group, req.name)
                return logical.analyze_measure(m, req).explain()
            if engine == "stream":
                s = self.registry.get_stream(group, req.name)
                return logical.analyze_stream(s, req).explain()
            if engine == "trace":
                from banyandb_tpu.models import trace as trace_model

                t = self.registry.get_trace(group, req.name)
                kind, _, _, _, _ = trace_model.classify_plan(
                    req, t.trace_id_tag
                )
                return (
                    f"trace plan={kind} order_by={req.order_by_tag or '-'}"
                    f" limit={req.limit} offset={req.offset}"
                )
            return None

        record_slow_query(
            self.slowlog, self.slow_query_ms,
            engine=engine, group=group, name=req.name,
            duration_ms=ms, rows=rows, span_tree=tree, ql=ql,
            plan=(res.trace or {}).get("plan") if res is not None else None,
            plan_fn=render_plan,
            tenant=tenant,
        )
        self.self_trace.offer(
            engine=engine, group=group, name=req.name,
            duration_ms=ms, tree=tree, tenant=tenant, ql=ql,
        )

    def _slowlog(self, env):
        from banyandb_tpu.obs.recorder import slowlog_topic_reply

        return slowlog_topic_reply(self.slowlog, env, self.slow_query_ms)

    def _metrics(self, env):
        self.meter.gauge_set("rss_bytes", _rss())
        # cache planes surface through /metrics so the bench and
        # operators read hit/miss/eviction counters from the RUNNING
        # server, not process-local globals (ISSUE 3 satellite)
        from banyandb_tpu.query.precompile import default_registry
        from banyandb_tpu.storage.cache import device_cache, global_cache
        from banyandb_tpu.utils import compile_cache

        for scope, cache in (
            ("serving", global_cache()),
            ("device", device_cache()),
        ):
            st = cache.stats()
            for k in (
                "hits", "misses", "evictions", "entries", "bytes",
                "cap", "churn",
            ):
                self.meter.gauge_set(f"{scope}_cache_{k}", float(st[k]))
        # materialized rolling-window plane (query/streamagg.py):
        # window/state population + per-signature watermark gauges
        self.measure.streamagg.export_gauges()
        cc = compile_cache.stats()
        self.meter.gauge_set("compile_cache_enabled", float(cc["enabled"]))
        for k in ("hits", "misses", "entries"):
            self.meter.gauge_set(f"compile_cache_{k}", float(cc[k]))
        # multi-tenant QoS plane: admission gauges + per-tenant cache
        # partitions (tenant-labeled rows; the default tenant keeps its
        # original unlabeled series — no renames)
        self.qos.export_gauges(self.meter)
        from banyandb_tpu.storage.cache import partition_stats

        for tenant, st in partition_stats().items():
            for k in ("hits", "misses", "evictions", "entries", "bytes"):
                self.meter.gauge_set(
                    f"serving_cache_{k}", float(st[k]), {"tenant": tenant}
                )
        for tenant, used in self.protector.tenant_usage().items():
            self.meter.gauge_set(
                "qos_inflight_bytes", float(used), {"tenant": tenant}
            )
        pr = default_registry().stats()
        for k in ("recorded", "compiled", "errors"):
            self.meter.gauge_set(f"precompile_{k}", float(pr[k]))
        ar = self.autoreg.stats()
        for k in ("known_signatures", "registered_total", "evicted_total"):
            self.meter.gauge_set(f"autoreg_{k}", float(ar[k]))
        if self.pool is not None:
            # pool gauges set BEFORE the render so the scrape that
            # matters most — every worker down, empty worker_text —
            # still carries workers_alive/workers_total
            self.meter.gauge_set("workers_alive", float(len(self.pool.liaison.alive)))
            self.meter.gauge_set("workers_total", float(self.pool.n))
        text = self.meter.prometheus_text()
        if self.pool is not None:
            # graft worker expositions with per-worker labels — the
            # scrapers (obs/prom.py) aggregate across the worker label
            worker_text = self.pool.metrics_text()
            if worker_text:
                text = text + "\n" + worker_text
        return {"prometheus": text}

    def _streamagg(self, env):
        """Streaming-aggregation control surface (query/streamagg.py):
        register/unregister materialized dashboard signatures / read
        window state.  ``origin: "auto"`` marks autoreg registrations
        (budget-evictable; manual ones never are)."""
        op = env.get("op", "stats")
        if self.pool is not None:
            # windows are worker-local per shard: registrations
            # broadcast (with rejoin catch-up), stats fan out
            return self.pool.streamagg(env)
        if op == "register":
            info = self.measure.streamagg.register(
                env["group"],
                env["measure"],
                key_tags=tuple(env.get("key_tags", ())),
                fields=tuple(env.get("fields", ())),
                window_millis=env.get("window_millis"),
                max_windows=env.get("max_windows"),
                origin=env.get("origin", "manual"),
            )
            return {"registered": info}
        if op == "unregister":
            removed = self.measure.streamagg.unregister(
                env["group"],
                env["measure"],
                key_tags=tuple(env.get("key_tags", ())),
                fields=tuple(env.get("fields", ())),
                window_millis=env.get("window_millis"),
            )
            return {"unregistered": removed}
        if op == "stats":
            return {"streamagg": self.measure.streamagg.stats()}
        raise KeyError(f"bad streamagg op {op!r}")

    def _streamagg_signature_rows(self) -> list:
        """Flat signature-stat rows for the autoreg budget (pool mode
        merges per-worker rows: states/hits sum, last-hit maxes)."""
        st = self._streamagg({"op": "stats"}).get("streamagg") or {}
        if self.pool is None:
            return st.get("signatures", [])
        merged: dict = {}
        for wstats in st.values():
            for row in (wstats or {}).get("signatures", ()):
                key = (
                    row.get("group"), row.get("measure"),
                    tuple(row.get("key_tags", ())),
                    tuple(row.get("fields", ())),
                )
                cur = merged.get(key)
                if cur is None:
                    merged[key] = dict(row)
                else:
                    cur["states"] = int(cur.get("states", 0)) + int(
                        row.get("states", 0)
                    )
                    cur["hits"] = int(cur.get("hits", 0)) + int(
                        row.get("hits", 0)
                    )
                    cur["last_hit_ms"] = max(
                        cur.get("last_hit_ms") or 0,
                        row.get("last_hit_ms") or 0,
                    ) or None
        return list(merged.values())

    def _topn(self, env):
        """TopN query over pre-aggregated windows (TopNService analog)."""
        from banyandb_tpu.api.model import TimeRange
        from banyandb_tpu.models import topn as topn_mod

        rules = {r.name for r in self.registry.list_topn(env["group"])}
        if env["name"] not in rules:
            raise KeyError(
                f"topn rule {env['name']} not found in group {env['group']}"
            )
        adm = self.qos.admit_query(
            env["group"],
            deadline_s=(
                float(env["deadline_ms"]) / 1000.0
                if env.get("deadline_ms")
                else None
            ),
        )
        with adm, tenant_scope(adm.tenant):
            if self.pool is not None:
                # scatter the node-local ranking; entities are shard-
                # routed so the concat re-rank is exact (cluster/workers)
                return self.pool.topn(env)
            ranked = topn_mod.query_topn(
                self.measure,
                env["group"],
                env["name"],
                TimeRange(*env["time_range"]),
                n=env.get("n", 10),
                direction=env.get("direction", "desc"),
                agg=env.get("agg", "sum"),
                # same envelope contract as DataNode._on_topn, so the
                # pool/0-mode A/B stays symmetric when a caller filters
                conditions=tuple(
                    (c[0], c[1], c[2]) for c in env.get("conditions", ())
                ),
            )
        return {
            "items": [
                {"entity": list(ent), "value": val} for ent, val in ranked
            ]
        }

    def _qos(self, env):
        """QoS introspection topic (cli.py qos): per-tenant admission
        counters, limits, cache partitions and in-flight charges."""
        from banyandb_tpu.storage.cache import partition_stats

        return {
            "qos": self.qos.stats(),
            "cache_partitions": partition_stats(),
            "inflight_bytes": self.protector.tenant_usage(),
        }

    def _diagnostics(self, env):
        from banyandb_tpu.admin.diagnostics import DiagnosticsCollector

        collector = DiagnosticsCollector(self.root, self.meter)
        return collector.collect(
            include_threads=bool(env.get("include_threads"))
        )

    def _stream_write(self, env):
        self.disk.check_write()
        tenant = self.qos.admit_write(env["group"], len(env["elements"]))
        t0 = time.perf_counter()
        with tenant_scope(tenant):
            if self.pool is not None:
                # elements already ride the liaison wire shape; the pool
                # routes them by entity-hash shard to the owning workers
                n = self.pool.write_stream(
                    env["group"], env["name"], env["elements"]
                )
            else:
                n = self.stream.write(
                    env["group"], env["name"],
                    serde.elements_from_json(env["elements"]),
                )
        self.meter.observe(
            "write_ms", (time.perf_counter() - t0) * 1000, {"model": "stream"}
        )
        return {"written": n}

    def _stream_query(self, env):
        from banyandb_tpu.obs import Tracer

        req = serde.query_request_from_json(env["request"])
        tracer = Tracer("standalone:stream")
        adm = self._admit_query(req, env)
        with adm, tenant_scope(adm.tenant):
            self._tag_qos(tracer, adm)
            t0 = time.perf_counter()
            if self.pool is not None:
                res = self.pool.query_stream(req, tracer=tracer)
            else:
                res = self.stream.query(req, tracer=tracer)
            ms = (time.perf_counter() - t0) * 1000
        tree = tracer.finish()
        self._observe_query(
            "stream", req, ms, rows=len(res.data_points), tree=tree,
            res=res, tenant=adm.tenant,
        )
        attach_tree(res, req, tree)
        return {"result": result_to_json(res)}

    def _trace_write(self, env):
        self.disk.check_write()
        tenant = self.qos.admit_write(env["group"], len(env["spans"]))
        t0 = time.perf_counter()
        with tenant_scope(tenant):
            if self.pool is not None:
                n = self.pool.write_trace(
                    env["group"], env["name"], env["spans"],
                    ordered_tags=tuple(env.get("ordered_tags", ())),
                )
            else:
                n = self.trace.write(
                    env["group"], env["name"],
                    serde.spans_from_json(env["spans"]),
                    ordered_tags=tuple(env.get("ordered_tags", ())),
                )
        self.meter.observe(
            "write_ms", (time.perf_counter() - t0) * 1000, {"model": "trace"}
        )
        return {"written": n}

    def _trace_query(self, env):
        if self.pool is not None:
            spans = self.pool.query_trace_by_id(
                env["group"], env["name"], env["trace_id"]
            )
        else:
            spans = self.trace.query_by_trace_id(
                env["group"], env["name"], env["trace_id"]
            )
        return {"spans": serde.spans_to_json(spans)}

    def _property_apply(self, env):
        self.disk.check_write()
        p = self.property.apply(
            Property(
                group=env["group"], name=env["name"], id=env["id"],
                tags=env.get("tags", {}),
            ),
            strategy=env.get("strategy", "merge"),
            ttl_seconds=env.get("ttl_seconds"),
        )
        return {"mod_revision": p.mod_revision, "create_revision": p.create_revision}

    def _property_query(self, env):
        if "id" in env:
            p = self.property.get(env["group"], env["name"], env["id"])
            return {"properties": [p.tags] if p else []}
        props = self.property.query(
            env["group"], env["name"],
            tag_filters=env.get("tag_filters"),
            limit=env.get("limit", 100),
        )
        return {"properties": [{"id": p.id, "tags": p.tags} for p in props]}

    def _ql(self, env):
        from banyandb_tpu.obs import Tracer

        catalog, req = bydbql.parse_with_catalog(
            env["ql"], env.get("params", ())
        )
        if env.get("trace"):
            # cli.py explain (and any caller wanting the in-band tree):
            # force request-level tracing so the reply carries plan text
            # + span tree without a QL syntax extension
            import dataclasses as _dc

            req = _dc.replace(req, trace=True)
        tracer = Tracer(f"standalone:{catalog}")
        adm = self._admit_query(req, env)
        with adm, tenant_scope(adm.tenant):
            self._tag_qos(tracer, adm)
            t0 = time.perf_counter()
            if catalog == "stream":
                if self.pool is not None:
                    res = self.pool.query_stream(req, tracer=tracer)
                else:
                    res = self.stream.query(req, tracer=tracer)
            elif catalog == "trace":
                with tracer.span("execute"):
                    res = self._ql_trace(req, tracer=tracer)
            elif catalog == "property":
                with tracer.span("execute"):
                    res = self._ql_property(req)
            else:
                if self.pool is not None:
                    res = self.pool.query_measure(req, tracer=tracer)
                else:
                    res = self.measure.query(req, tracer=tracer)
            ms = (time.perf_counter() - t0) * 1000
        tree = tracer.finish()
        self._observe_query(
            catalog, req, ms,
            rows=len(res.data_points) or len(res.groups),
            tree=tree, res=res, ql=env["ql"], tenant=adm.tenant,
        )
        attach_tree(res, req, tree)
        # serve-path marker OUTSIDE the result payload (the A/B byte
        # parity contracts compare reply["result"] only): the load
        # harness splits its latency headline into cache replay vs real
        # (cache-miss) scans vs materialized-window reads with this
        return {"result": result_to_json(res), "served": _served_class(tree)}

    def _ql_trace(self, req: QueryRequest, tracer=None) -> QueryResult:
        from banyandb_tpu.query import ql_exec

        engine = self._pool_trace if self.pool is not None else self.trace
        return ql_exec.execute_trace_ql(engine, req, tracer=tracer)

    def _ql_property(self, req: QueryRequest) -> QueryResult:
        from banyandb_tpu.query import ql_exec

        return ql_exec.execute_property_ql(self.property, req)

    def _registry_op(self, env):
        op, kind = env["op"], env["kind"]
        if op == "create":
            cls = schema_mod._KINDS[kind]
            obj = schema_mod._from_jsonable(cls, env["item"])
            if kind == "group":
                rev = self.registry.create_group(obj)
            elif kind == "measure":
                rev = self.registry.create_measure(obj)
            elif kind == "index_rule":
                rev = self.registry.create_index_rule(obj)
            elif kind == "topn":
                rev = self.registry.create_topn(obj)
            else:
                raise KeyError(kind)
            return {"revision": rev}
        if op == "create_stream":
            item = env["item"]
            self.stream.create_stream(
                Stream(
                    group=item["group"], name=item["name"],
                    tags=tuple(
                        schema_mod.TagSpec(t["name"], schema_mod.TagType(t["type"]))
                        for t in item["tags"]
                    ),
                    entity=tuple(item["entity"]),
                )
            )
            return {"revision": self.registry.revision}
        if op == "create_trace":
            item = env["item"]
            self.trace.create_trace(
                Trace(
                    group=item["group"], name=item["name"],
                    tags=tuple(
                        schema_mod.TagSpec(t["name"], schema_mod.TagType(t["type"]))
                        for t in item["tags"]
                    ),
                    trace_id_tag=item["trace_id_tag"],
                )
            )
            return {"revision": self.registry.revision}
        if op == "list":
            if kind == "group":
                items = self.registry.list_groups()
            elif kind == "measure":
                items = self.registry.list_measures(env["group"])
            else:
                raise KeyError(kind)
            return {"items": [schema_mod._to_jsonable(i) for i in items]}
        raise KeyError(f"bad registry op {op}")

    def _snapshot(self, env):
        # flush everything so on-disk state is complete, then report dirs
        flushed = []
        if self.pool is not None:
            # worker flushes also trim the parent write journal to the
            # flush watermark (cluster/workers.py)
            flushed += self.pool.flush()
        else:
            flushed += self.measure.flush()
            flushed += self.stream.flush()
            flushed += self.trace.flush()
        self.property.persist()
        self.self_metrics.flush()  # self-measures land in _monitoring
        self.self_trace.flush()  # queued self-query span trees likewise
        return {"flushed": flushed, "root": str(self.root)}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        # plan precompile at schema load: bind the per-root signature
        # store and warm recorded + builtin plan kernels on a background
        # thread, so the first query after boot hits a warm jit cache
        # (paired with the persistent XLA cache wired at process start —
        # see utils/compile_cache and docs/performance.md)
        from banyandb_tpu.query.precompile import default_registry

        reg = default_registry()
        reg.attach_store(self.root / "plan-registry.json")
        reg.warm_async()
        # the bydb-autoreg loop (query/planner): self-driving streamagg
        # registration under an eviction budget (BYDB_AUTOREG=0 disables)
        from banyandb_tpu.query import planner as planner_mod

        if planner_mod.autoreg_enabled():
            self.autoreg.start()
        # one lifecycle group drives storage loops for ALL engines' TSDBs
        # AND property-lease GC
        self.measure.start_lifecycle(
            extra_tick=self._sweep_properties,
            # ordering keys must be durable BEFORE the span memtables they
            # describe flush (sidx-first commit ordering; mirrors the
            # data-node wiring in cluster/data_node.py)
            pre_flush=self.trace._flush_sidx_first,
            extra_tsdbs=lambda: (
                list(self.stream._tsdbs.values())
                + list(self.trace._tsdbs.values())
            ),
        )
        self.grpc.start()
        self.watchdog.start()
        # periodic _monitoring population (the native-meter provider
        # cadence); thread owned here, joined in stop()
        self.self_metrics.start()
        self.self_trace.start()
        if self.wire is not None:
            self.wire.start()
        if self.http is not None:
            self.http.start()
        if self.pprof is not None:
            self.pprof.start()

    def _sweep_properties(self) -> None:
        for g in self.registry.list_groups():
            try:
                self.property.sweep_expired(g.name)
            except Exception:  # noqa: BLE001 - GC must not kill the loop
                pass
        try:
            # trace maintenance: bloom sidecars + sidx merge only — the
            # sidx flush already ran in pre_flush, ahead of span memtables
            self.trace.maintain(flush_sidx=False)
        except Exception:  # noqa: BLE001
            pass

    def stop(self) -> None:
        # cancel + join in-flight plan warming FIRST: exiting while the
        # daemon thread is inside an XLA compile aborts the interpreter
        from banyandb_tpu.query.precompile import default_registry

        default_registry().shutdown()
        self.autoreg.stop()
        self.measure.stop_lifecycle()
        self.self_metrics.stop()
        self.self_trace.stop()
        self.watchdog.stop()
        self.grpc.stop()
        # ALL ingress surfaces close before the pool: a write landing
        # after pool.stop() would ack into a journal that dies with the
        # process (acked-write loss on graceful shutdown)
        if self.wire is not None:
            self.wire.stop()
        if self.http is not None:
            self.http.stop()
        if self.pool is not None:
            # graceful worker shutdown: lifecycle loops stop, engines
            # close, processes reap (bdsan process hygiene)
            self.pool.stop()
        if self.pprof is not None:
            self.pprof.stop()
        self.access_log.close()
        # release index mmaps/fds deterministically (bdsan fd hygiene)
        self.measure.close()
        self.stream.close()
        self.trace.close()
        self.property.close()

    @property
    def addr(self) -> str:
        return self.grpc.addr


def build_config():
    """Flag registry (pkg/config analog: CLI > BYDB_* env > --config
    JSON file > default)."""
    from banyandb_tpu.config import Config

    cfg = Config("banyandb-tpu server")
    cfg.register("root", None, "data root directory", str, required=True)
    cfg.register("port", 17912, "bus gRPC port", int)
    cfg.register(
        "wire-port", 17914,
        "reference-proto gRPC surface (banyandb.*.v1); -1 disables", int,
    )
    cfg.register("http-port", 17913, "HTTP/JSON gateway; -1 disables", int)
    cfg.register("pprof-port", -1, "profiling endpoints; -1 disables", int)
    cfg.register(
        "compile-cache-dir", "",
        "persistent XLA compile cache; empty = <root>/compile-cache, "
        "'off' disables", str,
    )
    cfg.register(
        "slow-query-ms", 500.0,
        "slow-query threshold: queries at/over it get the access-log "
        "slow mark AND a flight-recorder entry (cli.py slowlog)", float,
    )
    cfg.register(
        "serving-cache-cap", 0,
        "serving-cache ENTRY capacity on top of the byte budget "
        "(BYDB_SERVING_CACHE_CAP env; 0 = bytes-only)", int,
    )
    cfg.register(
        "workers", -1,
        "shard-owning worker processes for the data plane "
        "(BYDB_WORKERS env): N>0 partitions shards over N subprocesses, "
        "0 = single-process layout, -1 = auto (on by default on hosts "
        "with >= 4 cores)", int,
    )
    # role topology (pkg/cmdsetup/root.go:89-91 standalone/data/liaison)
    cfg.register("role", "standalone", "standalone | data | liaison", str)
    cfg.register("name", "", "node name (data role)", str)
    cfg.register(
        "discovery", "", "node-list JSON file (liaison role)", str
    )
    cfg.register("replicas", 0, "replica count (liaison role)", int)
    return cfg


def main(argv=None) -> None:
    from banyandb_tpu.run import FuncUnit, Group

    s = build_config().load(argv)
    # persistent XLA compile cache, wired before any kernel compiles:
    # plan kernels compile once per machine, not once per process.  The
    # flag has already folded CLI > BYDB_COMPILE_CACHE_DIR env > config
    # file precedence via config.py.
    from pathlib import Path as _Path

    from banyandb_tpu.utils import compile_cache

    if s.compile_cache_dir:
        compile_cache.enable_at(s.compile_cache_dir)
    else:
        compile_cache.enable_at(_Path(s.root) / "compile-cache")
    # an armed fault plane must be impossible to miss in a server log
    # (docs/robustness.md): chaos harnesses set it on purpose, a stray
    # env var in production must not inject faults silently
    from banyandb_tpu.utils.envflag import env_str

    _faults_spec = env_str("BYDB_FAULTS").strip()
    if _faults_spec:
        import sys as _sys

        print(
            f"warning: fault injection ARMED via BYDB_FAULTS="
            f"{_faults_spec!r}",
            file=_sys.stderr,
            flush=True,
        )
    # role-irrelevant flags must not silently do nothing (an operator
    # passing --http-port to a liaison would wait on a port never bound)
    _ignored = {
        "data": [
            ("wire-port", s.wire_port != 17914),
            ("http-port", s.http_port != 17913),
            ("pprof-port", s.pprof_port != -1),
            ("discovery", bool(s.discovery)),
            ("replicas", s.replicas != 0),
            # the multi-process data plane currently lives in the
            # standalone role; cluster data nodes scale by adding node
            # processes (ROADMAP item 3)
            ("workers", s.workers not in (-1, 0)),
        ],
        "liaison": [
            ("pprof-port", s.pprof_port != -1),
            ("name", bool(s.name)),
            # liaisons hold no serving cache; data nodes size theirs via
            # the BYDB_SERVING_CACHE_CAP env (per-process)
            ("serving-cache-cap", s.serving_cache_cap != 0),
            ("workers", s.workers not in (-1, 0)),
        ],
        "standalone": [
            ("discovery", bool(s.discovery)),
            ("replicas", s.replicas != 0),
            ("name", bool(s.name)),
        ],
    }.get(s.role, [])
    for flag, was_set in _ignored:
        if was_set:
            import sys as _sys

            print(
                f"warning: --{flag} has no effect with --role {s.role}",
                file=_sys.stderr,
                flush=True,
            )
    if s.role == "data":
        from banyandb_tpu.cluster_server import DataServer

        if s.serving_cache_cap:
            # data nodes hold the serving cache in cluster mode: the
            # entry-cap knob applies there exactly like standalone
            from banyandb_tpu.storage.cache import global_cache

            global_cache().set_cap(s.serving_cache_cap)
        srv = DataServer(s.root, name=s.name, port=s.port)

        def announce():
            srv.start()
            print(
                f"banyandb-tpu data node {srv.name!r} on {srv.addr}",
                flush=True,
            )
    elif s.role == "liaison":
        from banyandb_tpu.cluster_server import LiaisonServer

        if not s.discovery:
            raise SystemExit("liaison role requires --discovery <nodes.json>")
        srv = LiaisonServer(
            s.root, s.discovery, port=s.port, replicas=s.replicas,
            wire_port=None if s.wire_port < 0 else s.wire_port,
            http_port=None if s.http_port < 0 else s.http_port,
            slow_query_ms=s.slow_query_ms,
        )

        def announce():
            srv.start()
            print(
                f"banyandb-tpu liaison on {srv.addr} "
                f"(data nodes alive: {sorted(srv.liaison.alive)})",
                flush=True,
            )
            if srv.wire is not None:
                print(f"wire gRPC (banyandb.*.v1) on :{srv.wire.port}", flush=True)
            if srv.http is not None:
                print(f"HTTP gateway + console on :{srv.http.port}", flush=True)
    elif s.role != "standalone":
        raise SystemExit(f"unknown role {s.role!r}")
    else:
        # on-by-default A/B flag (docs/performance.md "Multi-process
        # data plane"): auto resolves to a worker fleet on hosts with
        # enough cores to win from one; tiny hosts keep the
        # single-process layout (a 2-core box convoys either way)
        workers = s.workers
        if workers < 0:
            cpu = _os.cpu_count() or 1
            workers = min(4, cpu // 2) if cpu >= 4 else 0
        srv = StandaloneServer(
            s.root,
            s.port,
            wire_port=None if s.wire_port < 0 else s.wire_port,
            http_port=None if s.http_port < 0 else s.http_port,
            pprof_port=None if s.pprof_port < 0 else s.pprof_port,
            slow_query_ms=s.slow_query_ms,
            serving_cache_cap=s.serving_cache_cap or None,
            workers=workers,
        )

        def announce():
            srv.start()
            print(f"banyandb-tpu standalone listening on {srv.addr}", flush=True)
            if srv.pool is not None:
                print(
                    f"multi-process data plane: {srv.pool.n} shard workers",
                    flush=True,
                )
            if srv.wire is not None:
                print(f"wire gRPC (banyandb.*.v1) on :{srv.wire.port}", flush=True)
            if srv.http is not None:
                print(f"HTTP gateway + console on :{srv.http.port}", flush=True)
            if srv.pprof is not None:
                print(f"profiling endpoints on :{srv.pprof.port}", flush=True)

    group = Group(s.role)
    group.add(FuncUnit("server", serve=announce, stop=srv.stop))
    # panic supervisor: uncaught exceptions on any thread write a crash
    # artifact and trigger orderly teardown (supervisor.go analog)
    from banyandb_tpu.admin.supervisor import Supervisor

    Supervisor(srv.root, on_crash=group.trigger_stop).install()
    group.run()
    # grpc's worker threads are non-daemon; an in-flight slow handler
    # (e.g. a TPU compile) must not wedge process exit after SIGTERM.
    import os

    os._exit(0)


if __name__ == "__main__":
    main()
