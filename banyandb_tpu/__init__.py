"""banyandb_tpu — a TPU-native observability database framework.

A brand-new implementation of the capabilities of Apache SkyWalking BanyanDB
(reference: /root/reference, see SURVEY.md) designed JAX/XLA/Pallas-first:

- Four data models: Measure (metrics), Stream (logs), Trace (spans),
  Property (mutable documents)  -> `banyandb_tpu.models`
- Columnar, snapshot-MVCC LSM storage substrate with time-segmented shards
  -> `banyandb_tpu.storage`
- The query execution plane (columnar scan, filter, group-by, aggregation,
  top-N, percentile) runs as fused XLA/Pallas TPU kernels
  -> `banyandb_tpu.ops`, `banyandb_tpu.query`
- Distributed execution over `jax.sharding.Mesh` with psum/all_gather
  collectives replacing the reference's proto partial-aggregate exchange
  -> `banyandb_tpu.parallel`, `banyandb_tpu.cluster`

Dtype policy (TPU-first):
- int64 quantities (timestamps, series ids, versions) live on the host / on
  disk as NumPy int64; the *device* hot path is explicitly 32-bit:
  timestamps are int32 offsets from the segment/batch epoch, tag values are
  int32 dictionary codes, float fields are float32. Kernels are
  dtype-explicit, and global JAX config (x64) is never mutated — host-side
  64-bit work stays in NumPy at the host/device boundary.
"""

__version__ = "0.1.0"
