"""Hot-path rules: host syncs, recompile churn, precision drift.

These encode the project's JAX performance contract (ROADMAP north star,
BENCH_r05.json): device work in query/, ops/, parallel/ and index/ must
not round-trip to the host per column, must not rebuild jit wrappers per
call, and must not silently promote kernel inputs to float64.

Device-value taint is deliberately convention-driven: a call to any
callable whose final name segment is ``kernel``, ``jitted`` or ``step``
(or a name bound from ``jax.jit(...)`` / a ``@jax.jit`` function in the
same module) is treated as producing device arrays.  The codebase names
its compiled entry points exactly this way (measure_exec/stream_exec
``kernel``, dist_exec ``step``/``jitted``), which keeps the analysis
local and false-positive-light; cross-module device returns are covered
by the always-flagged explicit sync APIs (device_get/block_until_ready).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from banyandb_tpu.lint.core import FileContext, Finding, dotted_name

HOT_SCOPE = ("query/", "ops/", "parallel/", "index/")

_DEVICE_CALLEE_RE = re.compile(r"^_?([a-z0-9]+_)*(kernel|jitted|step)$")
_DEVICE_MODULES = ("jnp.", "jax.numpy.", "jax.lax.", "jax.ops.")
_CLOCK_CALLS = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.perf_counter",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
_SYNC_CASTS = {"float", "int", "bool"}
_SYNC_NP = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
}


def _is_jax_jit(node: ast.AST) -> bool:
    d = dotted_name(node)
    if d in ("jax.jit", "jit"):
        return True
    # functools.partial(jax.jit, ...) decorator form
    if isinstance(node, ast.Call) and dotted_name(node.func) in (
        "partial",
        "functools.partial",
    ):
        return bool(node.args) and dotted_name(node.args[0]) in (
            "jax.jit",
            "jit",
        )
    return False


class ModuleJaxFacts:
    """Module-level jit analysis shared by host-sync / recompile-hazard.

    - ``jitted_names``: names bound to jit-compiled callables
      (``x = jax.jit(f)``, ``@jax.jit def f``)
    - ``traced_fns``: FunctionDef nodes whose BODIES run under trace
      (decorated with jax.jit, or whose name is passed to jax.jit
      anywhere in the module — the nested-``kernel`` build pattern)
    """

    def __init__(self, tree: ast.Module):
        self.jitted_names: set[str] = set()
        traced_names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jax_jit(d) for d in node.decorator_list):
                    self.jitted_names.add(node.name)
                    traced_names.add(node.name)
            elif isinstance(node, ast.Call) and _is_jax_jit(node.func):
                if node.args and isinstance(node.args[0], ast.Name):
                    traced_names.add(node.args[0].id)
            elif isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Call) and _is_jax_jit(
                    node.value.func
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.jitted_names.add(t.id)
        self.traced_fns = [
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in traced_names
        ]


def _walk_own(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's subtree WITHOUT descending into nested function
    defs — each nested def is visited as its own function by the caller,
    so descending here would report its findings once per enclosing
    scope."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _assign_targets(node: ast.AST) -> Iterator[str]:
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, ast.Tuple):
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        yield e.id
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        if isinstance(node.target, ast.Name):
            yield node.target.id


class _FnTaint:
    """Single-pass device-value taint over one function body."""

    def __init__(self, fn: ast.AST, facts: ModuleJaxFacts):
        self.facts = facts
        self.tainted: set[str] = set()
        self.jitted_locals: set[str] = set(facts.jitted_names)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = node.value
                if value is None:
                    continue
                if isinstance(value, ast.Call) and _is_jax_jit(value.func):
                    self.jitted_locals.update(_assign_targets(node))
                elif self.expr_tainted(value):
                    self.tainted.update(_assign_targets(node))

    def callee_is_device(self, func: ast.AST) -> bool:
        d = dotted_name(func)
        last = d.rsplit(".", 1)[-1] if d else ""
        if last in self.jitted_locals:
            return True
        return bool(_DEVICE_CALLEE_RE.match(last))

    def expr_tainted(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, (ast.Subscript, ast.Attribute)):
            return self.expr_tainted(e.value)
        if isinstance(e, ast.Call):
            d = dotted_name(e.func)
            if d.startswith(_DEVICE_MODULES) or d == "jax.device_put":
                return True
            return self.callee_is_device(e.func)
        if isinstance(e, ast.BinOp):
            return self.expr_tainted(e.left) or self.expr_tainted(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.expr_tainted(e.operand)
        if isinstance(e, ast.IfExp):
            return self.expr_tainted(e.body) or self.expr_tainted(e.orelse)
        return False


class HostSyncRule:
    """host-sync: device->host round-trips in hot modules.

    Flags block_until_ready / jax.device_get anywhere in scope (the
    legitimate result-boundary transfer carries a suppression with a
    reason — that is the point: boundaries become greppable decisions),
    np.asarray/np.array/float/int/bool applied to device-tainted values
    (each one is a separate blocking transfer; batch them into ONE
    device_get at the boundary), and wall-clock reads inside traced
    functions (they freeze at trace time)."""

    name = "host-sync"
    summary = "device->host sync (transfer/cast/clock) in a hot module"
    scope = HOT_SCOPE

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        facts = ctx.jax_facts
        fns = [
            n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        traced = set(map(id, facts.traced_fns))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                yield ctx.finding(
                    node,
                    self.name,
                    "block_until_ready stalls the dispatch pipeline; "
                    "batch at the result boundary",
                )
            elif d == "jax.device_get":
                yield ctx.finding(
                    node,
                    self.name,
                    "explicit device->host transfer; if this is the "
                    "result boundary, suppress with a reason",
                )
        for fn in fns:
            taint = _FnTaint(fn, facts)
            for node in _walk_own(fn):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                d = dotted_name(node.func)
                is_cast = d in _SYNC_CASTS and len(node.args) == 1
                if (d in _SYNC_NP or is_cast) and taint.expr_tainted(
                    node.args[0]
                ):
                    yield ctx.finding(
                        node,
                        self.name,
                        f"{d}() on a device value forces a blocking "
                        "transfer; use one jax.device_get at the boundary",
                    )
            if id(fn) in traced:
                for node in _walk_own(fn):
                    if (
                        isinstance(node, ast.Call)
                        and dotted_name(node.func) in _CLOCK_CALLS
                    ):
                        yield ctx.finding(
                            node,
                            self.name,
                            "wall-clock read inside a traced function "
                            "freezes at trace time; pass time in as an "
                            "argument",
                        )


class RecompileHazardRule:
    """recompile-hazard: jit wrapper churn and trace-time formatting.

    ``jax.jit(lambda ...)`` and ``jax.jit(f)(...)`` build a fresh
    wrapper (and compile cache entry) per evaluation; a jit call inside
    a loop does so per iteration.  The blessed pattern is the module
    cache keyed by a static PlanSpec (measure_exec._KERNEL_CACHE).
    F-strings over traced parameters concretize under trace."""

    name = "recompile-hazard"
    summary = "per-call jit wrapper / trace-time string formatting"
    scope = HOT_SCOPE

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_jax_jit(node.func)):
                continue
            if node.args and isinstance(node.args[0], ast.Lambda):
                yield ctx.finding(
                    node,
                    self.name,
                    "jax.jit(lambda): a fresh lambda never hits the jit "
                    "cache; jit a named function once",
                )
            parent = ctx.parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                yield ctx.finding(
                    node,
                    self.name,
                    "jax.jit(f)(...) compiles per call; bind the jitted "
                    "callable once and reuse it",
                )
            anc = parent
            while anc is not None:
                if isinstance(anc, (ast.For, ast.While)):
                    yield ctx.finding(
                        node,
                        self.name,
                        "jax.jit inside a loop rebuilds the wrapper per "
                        "iteration; hoist it (or cache by plan spec)",
                    )
                    break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break  # enclosing fn may itself be cached: loop scan ends
                anc = ctx.parents.get(anc)
        for fn in ctx.jax_facts.traced_fns:
            params = {
                a.arg
                for a in list(fn.args.args)
                + list(fn.args.posonlyargs)
                + list(fn.args.kwonlyargs)
            }
            for node in ast.walk(fn):
                if isinstance(node, ast.JoinedStr):
                    used = {
                        n.id
                        for v in node.values
                        if isinstance(v, ast.FormattedValue)
                        for n in ast.walk(v.value)
                        if isinstance(n, ast.Name)
                    }
                    if used & params:
                        yield ctx.finding(
                            node,
                            self.name,
                            "f-string over a traced argument concretizes "
                            "at trace time",
                        )


class PrecisionDriftRule:
    """precision-drift: dtype-less float64-defaulting constructors.

    ``np.zeros/ones/empty/full/arange`` default to float64; in kernel
    paths that either doubles HBM traffic when the array crosses to the
    device, or silently widens a host accumulator.  Both are real
    decisions (the f32-device/f64-host-merge precision contract,
    docs/soak_r05.json) — make them explicit with a dtype."""

    name = "precision-drift"
    summary = "numpy constructor without explicit dtype in a kernel path"
    scope = ("query/", "ops/", "parallel/")

    _CTORS = {
        "np.zeros": 1,
        "np.ones": 1,
        "np.empty": 1,
        "np.full": 2,
        "np.arange": None,  # dtype is keyword-only in practice
        "numpy.zeros": 1,
        "numpy.ones": 1,
        "numpy.empty": 1,
        "numpy.full": 2,
        "numpy.arange": None,
    }

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d not in self._CTORS:
                continue
            if any(k.arg == "dtype" for k in node.keywords):
                continue
            pos = self._CTORS[d]
            if pos is not None and len(node.args) > pos:
                continue  # positional dtype present
            yield ctx.finding(
                node,
                self.name,
                f"{d}() defaults to float64; state the dtype the "
                "precision contract intends",
            )


RULES = (HostSyncRule(), RecompileHazardRule(), PrecisionDriftRule())
