"""wire-kind: the wire-kind taxonomy audit.

The fabric's four kinds (``deadline``/``error``/``shed``/``stale_epoch``,
cluster/rpc.py TransportError) are load-bearing: the liaison's retry,
spool and eviction decisions all switch on them.  This analyzer pins
the taxonomy three ways:

1. **Vocabulary** — every kind literal the package raises, classifies
   or compares (``TransportError(..., kind=...)``, classifier returns,
   ``e.kind == ...`` switches) must be a DECLARED_KINDS member.  A typo
   (``"staleepoch"``) or an undeclared new kind fails immediately.
2. **Per-transport consistency** — each transport module's kind set
   must equal its TRANSPORT_KINDS entry, both directions: a transport
   that stops carrying a declared kind (or grows an undeclared one)
   fails, so the retryable set stays expressible on every wire.
3. **Classifier exhaustiveness** — each CLASSIFIER_SWITCHES qual must
   mention every kind its entry declares.  Adding a kind to
   DECLARED_KINDS without teaching ``_error_kind`` and the liaison
   delivery/scatter switches fails the gate — the "new kind added
   without full classification" ISSUE case.

Kind-literal collection is deliberately narrow to dodge the package's
many non-wire ``kind`` attributes (plan-node kinds, fault kinds, CLI
kinds): switch sites key off the ``getattr(e, "kind", ...)`` idiom —
the one every wire consumer uses, because the duck-typed TransportError
surface guarantees nothing — never bare ``X.kind`` attribute access;
raise sites are the error classes' own constructor arguments.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from banyandb_tpu.lint.core import Finding
from banyandb_tpu.lint.whole_program.callgraph import Program, _walk_own

from banyandb_tpu.lint.wire import wire_config as _cfg

RULE = "wire-kind"


@dataclass(frozen=True)
class KindSite:
    kind: str
    qual: str
    module: str
    path: str
    line: int
    col: int
    role: str  # "raise" | "classify" | "switch"


def _is_kind_source(expr: ast.AST) -> bool:
    """True for expressions that denote a wire kind:
    ``getattr(X, "kind", ...)`` — bare ``X.kind`` is NOT accepted (the
    package is full of non-wire ``kind`` attributes; the wire idiom is
    always the getattr-with-default form)."""
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id == "getattr"
        and len(expr.args) >= 2
        and isinstance(expr.args[1], ast.Constant)
        and expr.args[1].value == "kind"
    )


def _last_name(expr: ast.AST) -> str:
    while isinstance(expr, ast.Attribute):
        return expr.attr
    return expr.id if isinstance(expr, ast.Name) else ""


def collect_kind_sites(
    program: Program,
    *,
    error_classes: tuple[str, ...],
    classifier_names: tuple[str, ...] = ("_error_kind",),
) -> list[KindSite]:
    sites: list[KindSite] = []
    for info in program.functions.values():
        fn_short = info.qual.split(":", 1)[1].split(".")[-1]
        is_classifier = fn_short in classifier_names
        # wire-kind locals in this function: names assigned from .kind
        kind_vars: set[str] = set()
        for node in _walk_own(info.node):
            if isinstance(node, ast.Assign) and _is_kind_source(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        kind_vars.add(t.id)

        def emit(node: ast.AST, kind: str, role: str) -> None:
            sites.append(
                KindSite(
                    kind=kind,
                    qual=info.qual,
                    module=info.module,
                    path=info.path,
                    line=node.lineno,
                    col=node.col_offset,
                    role=role,
                )
            )

        for node in _walk_own(info.node):
            # TransportError(msg, "kind") / TransportError(msg, kind="kind")
            # / TransportError(msg, kind=X.get("kind", "default"))
            if isinstance(node, ast.Call) and _last_name(node.func) in (
                error_classes
            ):
                if len(node.args) >= 2 and isinstance(
                    node.args[1], ast.Constant
                ):
                    emit(node, node.args[1].value, "raise")
                for kw in node.keywords:
                    if kw.arg != "kind":
                        continue
                    if isinstance(kw.value, ast.Constant):
                        emit(node, kw.value.value, "raise")
                    elif (
                        isinstance(kw.value, ast.Call)
                        and isinstance(kw.value.func, ast.Attribute)
                        and kw.value.func.attr == "get"
                        and len(kw.value.args) >= 2
                        and isinstance(kw.value.args[1], ast.Constant)
                    ):
                        emit(node, kw.value.args[1].value, "raise")
            # classifier returns
            elif (
                is_classifier
                and isinstance(node, ast.Return)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                emit(node, node.value.value, "classify")
            # getattr(e, "kind", "default")'s default is itself a kind
            elif (
                isinstance(node, ast.Call)
                and _is_kind_source(node)
                and len(node.args) >= 3
                and isinstance(node.args[2], ast.Constant)
            ):
                emit(node, node.args[2].value, "switch")
            # switches: <kindvar|.kind> == "x" / in ("x", "y")
            elif isinstance(node, ast.Compare):
                left = node.left
                lefty = _is_kind_source(left) or (
                    isinstance(left, ast.Name) and left.id in kind_vars
                )
                if not lefty:
                    continue
                for comp in node.comparators:
                    if isinstance(comp, ast.Constant) and isinstance(
                        comp.value, str
                    ):
                        emit(comp, comp.value, "switch")
                    elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                        for el in comp.elts:
                            if isinstance(el, ast.Constant) and isinstance(
                                el.value, str
                            ):
                                emit(el, el.value, "switch")
    return sites


def analyze_kinds(
    program: Program,
    *,
    declared: Optional[tuple[str, ...]] = None,
    retryable: Optional[frozenset] = None,
    error_classes: Optional[tuple[str, ...]] = None,
    transport_kinds: Optional[dict[str, frozenset]] = None,
    classifier_switches: Optional[dict[str, frozenset]] = None,
    baseline_path: str = "<wire-config>",
) -> list[Finding]:
    declared = _cfg.DECLARED_KINDS if declared is None else declared
    retryable = _cfg.RETRYABLE_KINDS if retryable is None else retryable
    error_classes = (
        _cfg.ERROR_CLASSES if error_classes is None else error_classes
    )
    transport_kinds = (
        _cfg.TRANSPORT_KINDS if transport_kinds is None else transport_kinds
    )
    classifier_switches = (
        _cfg.CLASSIFIER_SWITCHES
        if classifier_switches is None
        else classifier_switches
    )
    sites = collect_kind_sites(program, error_classes=error_classes)
    findings: list[Finding] = []
    declared_set = set(declared)

    # 1. vocabulary
    for s in sites:
        if s.kind not in declared_set:
            findings.append(
                Finding(
                    path=s.path,
                    line=s.line,
                    col=s.col,
                    rule=RULE,
                    message=(
                        f"wire kind {s.kind!r} ({s.role} site in "
                        f"{s.qual.split(':', 1)[1]}) is not in "
                        f"DECLARED_KINDS {sorted(declared_set)}; declare it "
                        f"(and teach every CLASSIFIER_SWITCHES site) or fix "
                        f"the literal"
                    ),
                )
            )

    # the retryable set must be declared
    for k in sorted(set(retryable) - declared_set):
        findings.append(
            Finding(
                path=baseline_path,
                line=1,
                col=0,
                rule=RULE,
                message=(
                    f"RETRYABLE_KINDS contains undeclared kind {k!r}"
                ),
            )
        )

    # 2. per-transport consistency (only transports in this package)
    by_module: dict[str, set[str]] = {}
    mod_anchor: dict[str, tuple[str, int]] = {}
    for s in sites:
        by_module.setdefault(s.module, set()).add(s.kind)
        mod_anchor.setdefault(s.module, (s.path, s.line))
    for mod, expect in sorted(transport_kinds.items()):
        if not any(info.module == mod for info in program.functions.values()):
            continue
        live = by_module.get(mod, set())
        anchor = mod_anchor.get(mod, (baseline_path, 1))
        for k in sorted(expect - live):
            findings.append(
                Finding(
                    path=anchor[0],
                    line=anchor[1],
                    col=0,
                    rule=RULE,
                    message=(
                        f"transport module {mod} no longer carries declared "
                        f"kind {k!r} (TRANSPORT_KINDS) — the retryable "
                        f"contract is not expressible on this wire"
                    ),
                )
            )
        for k in sorted(live - expect):
            findings.append(
                Finding(
                    path=anchor[0],
                    line=anchor[1],
                    col=0,
                    rule=RULE,
                    message=(
                        f"transport module {mod} carries kind {k!r} missing "
                        f"from its TRANSPORT_KINDS entry — update the "
                        f"checked-in table"
                    ),
                )
            )

    # 3. classifier exhaustiveness
    by_qual: dict[str, set[str]] = {}
    qual_anchor: dict[str, tuple[str, int]] = {}
    for s in sites:
        by_qual.setdefault(s.qual, set()).add(s.kind)
        qual_anchor.setdefault(s.qual, (s.path, s.line))
    for qual, expect in sorted(classifier_switches.items()):
        info = program.functions.get(qual)
        if info is None:
            continue
        live = by_qual.get(qual, set())
        for k in sorted(expect - live):
            findings.append(
                Finding(
                    path=info.path,
                    line=info.node.lineno,
                    col=0,
                    rule=RULE,
                    message=(
                        f"classifier switch {qual.split(':', 1)[1]} does not "
                        f"handle declared kind {k!r} — a "
                        f"{'retryable ' if k in retryable else ''}rejection "
                        f"of that kind falls into its default branch"
                    ),
                )
            )
    return findings
