"""wire-retry: retryable rejections must reach a retry/spool/shed path.

The TransportError contract (cluster/rpc.py) says shed / deadline /
stale-epoch rejecting nodes are healthy: the sender must retry, spool,
degrade or re-route — never swallow the error or treat the node as
dead.  This analyzer walks every ``except TransportError`` handler in
the package and requires its body to reach a recovery verb:

- a call whose dotted name contains one of RETRY_SUBSTRINGS (``spool``,
  ``retry``, ``mark``, ``reload``, ``failover``, ...), possibly one hop
  down through a helper defined in the program, or
- a ``continue`` (per-node loops that record the failure and move on),

or the handler qual must carry a RETRY_EXEMPT reason (terminal
surfaces: a CLI that prints the error, a diagnostics collector that
reports "unreachable").  A bare ``raise``/``pass`` handler on a fabric
path is a finding — that is a retryable rejection dying on the floor.
"""

from __future__ import annotations

import ast
from typing import Optional

from banyandb_tpu.lint.core import Finding, dotted_name
from banyandb_tpu.lint.whole_program.callgraph import Program, _walk_own

from banyandb_tpu.lint.wire import wire_config as _cfg

RULE = "wire-retry"


def _handler_matches(htype: ast.AST, error_classes: tuple[str, ...]) -> bool:
    """True when an except clause catches one of the error classes,
    including tuple clauses and dotted references."""
    if htype is None:
        return False
    if isinstance(htype, ast.Tuple):
        return any(_handler_matches(e, error_classes) for e in htype.elts)
    name = dotted_name(htype) or ""
    short = name.split(".")[-1]
    return short in error_classes


def _body_recovers(
    program: Program,
    info,
    body: list[ast.stmt],
    substrings: tuple[str, ...],
    depth: int = 1,
) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Continue):
                return True
            if not isinstance(node, ast.Call):
                continue
            name = (dotted_name(node.func) or "").lower()
            if any(s in name for s in substrings):
                return True
            if depth > 0:
                # one hop through helpers defined in the program
                for site in info.calls:
                    if site.node is node and site.callee:
                        callee = program.functions.get(site.callee)
                        if callee is not None and _body_recovers(
                            program,
                            callee,
                            callee.node.body,
                            substrings,
                            depth - 1,
                        ):
                            return True
    return False


def analyze_retryable(
    program: Program,
    *,
    error_classes: Optional[tuple[str, ...]] = None,
    substrings: Optional[tuple[str, ...]] = None,
    exempt: Optional[dict[str, str]] = None,
    baseline_path: str = "<wire-config>",
) -> list[Finding]:
    error_classes = (
        _cfg.ERROR_CLASSES if error_classes is None else error_classes
    )
    substrings = _cfg.RETRY_SUBSTRINGS if substrings is None else substrings
    exempt = _cfg.RETRY_EXEMPT if exempt is None else exempt
    findings: list[Finding] = []
    seen_quals: set[str] = set()
    for qual, info in sorted(program.functions.items()):
        for node in _walk_own(info.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _handler_matches(handler.type, error_classes):
                    continue
                seen_quals.add(qual)
                if qual in exempt:
                    continue
                if _body_recovers(
                    program, info, handler.body, substrings
                ):
                    continue
                findings.append(
                    Finding(
                        path=info.path,
                        line=handler.lineno,
                        col=handler.col_offset,
                        rule=RULE,
                        message=(
                            f"{qual.split(':', 1)[1]} catches "
                            f"{error_classes[0]} but reaches no "
                            f"retry/spool/shed path "
                            f"(RETRY_SUBSTRINGS) — a retryable rejection "
                            f"dies here; recover, re-route, or add a "
                            f"reasoned RETRY_EXEMPT entry"
                        ),
                    )
                )
    for qual in sorted(set(exempt) - seen_quals):
        mod = qual.split(":", 1)[0]
        if not any(i.module == mod for i in program.functions.values()):
            continue  # module absent from this package (seeded pkgs)
        findings.append(
            Finding(
                path=baseline_path,
                line=1,
                col=0,
                rule=RULE,
                message=(
                    f"stale RETRY_EXEMPT entry {qual!r}: the function no "
                    f"longer catches {error_classes[0]} — delete the entry"
                ),
            )
        )
    return findings
