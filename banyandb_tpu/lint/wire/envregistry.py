"""wire-envflag: one parser, one registry, one doc for BYDB_* flags.

Config knobs are wire contract too — an operator sets them on one node
and expects the documented behavior on every role.  Three checks:

1. **Single parser** — every ``BYDB_*`` environment read must go
   through utils/envflag (``env_flag``/``env_int``/``env_float``/
   ``env_str``).  Raw ``os.environ[...]``/``os.getenv(...)`` reads
   outside that module re-grow the hand-rolled truthiness tables the
   module exists to kill.
2. **Registry** — every flag name passed to an ``env_*`` helper must
   appear in ``envflag.FLAGS`` (the checked-in table), and every FLAGS
   entry must still have a live read (stale entries fail: the table
   tracks the code, not history).
3. **Docs** — every FLAGS name must appear in docs/flags.md and every
   ``BYDB_*`` token in that doc must be a registered flag, so the
   operator page can never cite a knob that does not exist.  (Skipped
   when the doc is absent — seeded test packages.)
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from banyandb_tpu.lint.core import Finding, dotted_name

from banyandb_tpu.lint.wire import wire_config as _cfg

RULE = "wire-envflag"


def _literal_env_name(node: ast.Call, prefix: str) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant):
        v = node.args[0].value
        if isinstance(v, str) and v.startswith(prefix):
            return v
    return None


def analyze_envflags(
    trees: dict,
    repo_root: Optional[Path],
    *,
    envflag_module: Optional[str] = None,
    envflag_funcs: Optional[tuple[str, ...]] = None,
    prefix: Optional[str] = None,
    flags_doc: Optional[str] = None,
) -> list[Finding]:
    envflag_module = (
        _cfg.ENVFLAG_MODULE if envflag_module is None else envflag_module
    )
    envflag_funcs = (
        _cfg.ENVFLAG_FUNCS if envflag_funcs is None else envflag_funcs
    )
    prefix = _cfg.ENV_PREFIX if prefix is None else prefix
    flags_doc = _cfg.FLAGS_DOC if flags_doc is None else flags_doc
    findings: list[Finding] = []

    used: dict[str, tuple[str, int]] = {}  # flag -> one (path, line)
    for mod, (path, tree) in sorted(trees.items()):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            short = name.split(".")[-1]
            # raw reads: os.environ[...] handled below; .get/getenv here
            if mod != envflag_module and (
                name.endswith("os.environ.get")
                or name in ("os.getenv", "getenv")
                or (short == "get" and name.endswith("environ.get"))
            ):
                flag = _literal_env_name(node, prefix)
                if flag is not None:
                    findings.append(
                        Finding(
                            path=path,
                            line=node.lineno,
                            col=node.col_offset,
                            rule=RULE,
                            message=(
                                f"raw environment read of {flag} — every "
                                f"{prefix}* flag goes through "
                                f"{envflag_module} (env_flag/env_int/"
                                f"env_float/env_str) and its FLAGS table"
                            ),
                        )
                    )
            elif short in envflag_funcs:
                flag = _literal_env_name(node, prefix)
                if flag is not None:
                    used.setdefault(flag, (path, node.lineno))
        # raw subscript reads: os.environ["BYDB_X"]
        if mod == envflag_module:
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Subscript)
                and (dotted_name(node.value) or "").endswith("os.environ")
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
                and node.slice.value.startswith(prefix)
            ):
                findings.append(
                    Finding(
                        path=path,
                        line=node.lineno,
                        col=node.col_offset,
                        rule=RULE,
                        message=(
                            f"raw environment read of {node.slice.value} — "
                            f"every {prefix}* flag goes through "
                            f"{envflag_module} and its FLAGS table"
                        ),
                    )
                )

    # the registry itself
    if envflag_module not in trees:
        return findings
    reg_path, reg_tree = trees[envflag_module]
    flags: dict[str, int] = {}
    for node in reg_tree.body:
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
            if isinstance(node, ast.AnnAssign)
            else []
        )
        value = getattr(node, "value", None)
        if not any(
            isinstance(t, ast.Name) and t.id == "FLAGS" for t in targets
        ):
            continue
        if isinstance(value, ast.Dict):
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    flags[key.value] = key.lineno
    if not flags:
        findings.append(
            Finding(
                path=reg_path,
                line=1,
                col=0,
                rule=RULE,
                message=(
                    f"{envflag_module} defines no FLAGS dict literal — the "
                    f"{prefix}* registry the audit and docs key off"
                ),
            )
        )
        return findings

    for flag, (path, line) in sorted(used.items()):
        if flag not in flags:
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"flag {flag} is read but missing from "
                        f"envflag.FLAGS — register it (one line: name -> "
                        f"what it tunes)"
                    ),
                )
            )
    for flag in sorted(set(flags) - set(used)):
        findings.append(
            Finding(
                path=reg_path,
                line=flags[flag],
                col=0,
                rule=RULE,
                message=(
                    f"stale FLAGS entry {flag}: no env_* read remains — "
                    f"delete the entry (the table tracks the code)"
                ),
            )
        )

    # docs cross-reference (skipped when the doc is absent)
    if repo_root is None:
        return findings
    doc_path = Path(repo_root) / flags_doc
    if not doc_path.exists():
        return findings
    text = doc_path.read_text()
    doc_flags = set(re.findall(rf"{re.escape(prefix)}\w+", text))
    for flag in sorted(set(flags) - doc_flags):
        findings.append(
            Finding(
                path=reg_path,
                line=flags[flag],
                col=0,
                rule=RULE,
                message=(
                    f"flag {flag} is registered but undocumented — add it "
                    f"to {flags_doc}"
                ),
            )
        )
    for flag in sorted(doc_flags - set(flags)):
        findings.append(
            Finding(
                path=str(doc_path),
                line=1,
                col=0,
                rule=RULE,
                message=(
                    f"{flags_doc} cites {flag} but no such flag is "
                    f"registered in envflag.FLAGS — fix the doc or "
                    f"register the flag"
                ),
            )
        )
    return findings
