"""wire-envelope: producer/consumer field matching per envelope plane.

For each plane in wire_config.ENVELOPE_GROUPS (write / scatter / sync):

- **Produced fields** — every string key stored inside a producer
  function: dict literals, ``dict(env, field=...)`` keyword rebuilds,
  and ``env["field"] = ...`` subscript stores.
- **Consumed fields** — every read of the handler's envelope parameter
  in a consumer function: ``env["field"]`` hard reads, ``env.get(...)``
  soft reads; when the envelope is passed whole to a helper in the same
  program, the helper's reads of that parameter count too (one hop).

Two finding classes, both manually ratcheted through the checked-in
accepted tables (entries carry reasons and the tables only shrink):

1. **write-only** — a field every producer stamps but no consumer ever
   reads: dead wire weight, or worse, a consumer that silently ignores
   a fence ("epoch stamped but never checked").
2. **silent-default** — a consumer reads a *produced* field through
   ``env.get(field, default)``: if a producer path forgets the stamp,
   the consumer silently proceeds with the default instead of failing —
   the "epoch fence missing on the streaming ship path" class.
"""

from __future__ import annotations

import ast
from typing import Optional

from banyandb_tpu.lint.core import Finding
from banyandb_tpu.lint.whole_program.callgraph import Program, _walk_own

from banyandb_tpu.lint.wire import wire_config as _cfg

RULE = "wire-envelope"


def _produced_fields(program: Program, quals: tuple[str, ...]) -> dict[str, tuple[str, int]]:
    """field -> (path, line) of one producing site."""
    fields: dict[str, tuple[str, int]] = {}
    for qual in quals:
        info = program.functions.get(qual)
        if info is None:
            continue
        for node in _walk_own(info.node):
            if isinstance(node, ast.Dict):
                for key in node.keys:
                    if isinstance(key, ast.Constant) and isinstance(
                        key.value, str
                    ):
                        fields.setdefault(
                            key.value, (info.path, key.lineno)
                        )
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "dict"
            ):
                for kw in node.keywords:
                    if kw.arg is not None:
                        fields.setdefault(
                            kw.arg, (info.path, node.lineno)
                        )
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)
                    ):
                        fields.setdefault(
                            t.slice.value, (info.path, t.lineno)
                        )
    return fields


def _is_param_ref(expr: ast.AST, param: str) -> bool:
    """True when ``expr`` denotes the envelope parameter: the bare name
    or the ``(env or {})`` guard idiom optional-envelope helpers use."""
    if isinstance(expr, ast.Name):
        return expr.id == param
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or):
        return bool(expr.values) and _is_param_ref(expr.values[0], param)
    return False


def _env_param(node: ast.AST) -> Optional[str]:
    """Name of the envelope parameter: the first argument after self."""
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    args = [a.arg for a in node.args.args]
    if args and args[0] in ("self", "cls"):
        args = args[1:]
    return args[0] if args else None


def _reads_of(
    program: Program, qual: str, param: str, depth: int
) -> list[tuple[str, bool, str, int]]:
    """(field, has_silent_default, path, line) reads of ``param`` inside
    ``qual``, following the envelope one hop when passed whole."""
    info = program.functions.get(qual)
    if info is None:
        return []
    reads: list[tuple[str, bool, str, int]] = []
    for node in _walk_own(info.node):
        if (
            isinstance(node, ast.Subscript)
            and _is_param_ref(node.value, param)
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
            and isinstance(node.ctx, ast.Load)
        ):
            reads.append(
                (node.slice.value, False, info.path, node.lineno)
            )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and _is_param_ref(node.func.value, param)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            reads.append(
                (
                    node.args[0].value,
                    len(node.args) >= 2,
                    info.path,
                    node.lineno,
                )
            )
        elif depth > 0 and isinstance(node, ast.Call):
            # env passed whole to a resolvable helper: follow one hop
            for idx, arg in enumerate(node.args):
                if not (isinstance(arg, ast.Name) and arg.id == param):
                    continue
                for site in info.calls:
                    if site.node is not node or not site.callee:
                        continue
                    callee = program.functions.get(site.callee)
                    if callee is None:
                        continue
                    cargs = [a.arg for a in callee.node.args.args]
                    if cargs and cargs[0] in ("self", "cls"):
                        cargs = cargs[1:]
                    if idx < len(cargs):
                        reads.extend(
                            _reads_of(
                                program,
                                site.callee,
                                cargs[idx],
                                depth - 1,
                            )
                        )
    return reads


def analyze_envelopes(
    program: Program,
    *,
    groups: Optional[dict[str, dict]] = None,
    baseline_path: str = "<wire-config>",
) -> list[Finding]:
    groups = _cfg.ENVELOPE_GROUPS if groups is None else groups
    findings: list[Finding] = []
    for plane, spec in sorted(groups.items()):
        produced = _produced_fields(program, spec["producers"])
        if not produced and not any(
            q in program.functions for q in spec["consumers"]
        ):
            continue  # plane absent from this package (seeded pkgs)
        consumed: set[str] = set()
        soft_reads: list[tuple[str, str, int]] = []
        for qual in spec["consumers"]:
            info = program.functions.get(qual)
            if info is None:
                continue
            param = _env_param(info.node)
            if param is None:
                continue
            for field, silent, path, line in _reads_of(
                program, qual, param, depth=1
            ):
                consumed.add(field)
                if silent:
                    soft_reads.append((field, path, line))

        accepted_wo: dict[str, str] = spec.get("accepted_write_only", {})
        accepted_sd: dict[str, str] = spec.get("accepted_silent_default", {})

        # 1. write-only fields
        for field in sorted(set(produced) - consumed):
            if field in accepted_wo:
                continue
            path, line = produced[field]
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"envelope field `{field}` on the {plane} plane is "
                        f"produced but never read by any consumer "
                        f"({', '.join(q.split(':', 1)[1] for q in spec['consumers'])}); "
                        f"dead wire weight or an unchecked fence — consume "
                        f"it or add a reasoned accepted_write_only entry"
                    ),
                )
            )
        for field in sorted(set(accepted_wo) & consumed):
            findings.append(
                Finding(
                    path=baseline_path,
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"stale accepted_write_only entry `{field}` on the "
                        f"{plane} plane: a consumer now reads it — delete "
                        f"the entry (the table only shrinks)"
                    ),
                )
            )

        # 2. silent-default reads of produced fields
        flagged: set[str] = set()
        for field, path, line in sorted(soft_reads):
            if field not in produced or field in accepted_sd:
                continue
            if field in flagged:
                continue
            flagged.add(field)
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"envelope field `{field}` on the {plane} plane is "
                        f"read with a silent default (.get) although every "
                        f"producer stamps it — a producer path that forgets "
                        f"the stamp proceeds silently; hard-read it or add "
                        f"a reasoned accepted_silent_default entry"
                    ),
                )
            )
        live_sd = {f for f, _p, _l in soft_reads if f in produced}
        for field in sorted(set(accepted_sd) - live_sd):
            findings.append(
                Finding(
                    path=baseline_path,
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"stale accepted_silent_default entry `{field}` on "
                        f"the {plane} plane: no soft read remains — delete "
                        f"the entry (the table only shrinks)"
                    ),
                )
            )
    return findings
