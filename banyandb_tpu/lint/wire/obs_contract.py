"""wire-obs: the instrument contract between code and the dashboard doc.

docs/observability.md is the operator's contract: every metric it cites
must exist, and every instrument must use ONE label set — a counter
bumped with ``{"node": ...}`` here and ``{"peer": ...}`` there is two
series the dashboard cannot join.  Three checks over every
``counter_add`` / ``gauge_set`` / ``observe`` / ``histogram`` call with
a literal (or f-string-prefixed) instrument name:

1. the name must appear in wire_config.OBS_CONTRACT (exact, or under a
   ``prefix*`` pattern entry for f-string families like ``rpc_*_ms``);
2. when the contract pins a label-key set, every call site's literal
   label dict must use exactly those keys;
3. stale contract entries (no live call site) fail, and — when the doc
   exists — every contract name must be mentioned in
   docs/observability.md and every ``banyandb_*`` token the doc cites
   must normalize (strip scope prefix, ``_total``/``_bucket``/
   ``_count``/``_sum`` suffixes) to a contracted instrument.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from banyandb_tpu.lint.core import Finding

from banyandb_tpu.lint.wire import wire_config as _cfg

RULE = "wire-obs"

_METER_FUNCS = {
    # method -> index of the labels argument (after name)
    "counter_add": 2,
    "gauge_set": 2,
    "observe": 2,
    "histogram": 1,
}


def _instr_name(expr: ast.AST) -> Optional[str]:
    """Literal instrument name, or ``prefix*`` for f-string families."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr) and expr.values:
        head = expr.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value + "*"
        return "*"
    return None


def _label_keys(expr: Optional[ast.AST]) -> Optional[frozenset]:
    """Keys of a literal labels dict; None when not statically known."""
    if expr is None:
        return frozenset()
    if isinstance(expr, ast.Constant) and expr.value is None:
        return frozenset()
    if isinstance(expr, ast.Dict):
        keys = []
        for k in expr.keys:
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                return None  # **spread / computed key
            keys.append(k.value)
        return frozenset(keys)
    return None


def instrument_sites(
    trees: dict,
) -> list[tuple[str, Optional[frozenset], str, int]]:
    """(name-or-pattern, label keys or None, path, line) per call."""
    sites = []
    for _mod, (path, tree) in sorted(trees.items()):
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METER_FUNCS
            ):
                continue
            if not node.args:
                continue
            name = _instr_name(node.args[0])
            if name is None or name == "*":
                continue
            idx = _METER_FUNCS[node.func.attr]
            labels_expr: Optional[ast.AST] = None
            if len(node.args) > idx:
                labels_expr = node.args[idx]
            else:
                for kw in node.keywords:
                    if kw.arg == "labels":
                        labels_expr = kw.value
            sites.append((name, _label_keys(labels_expr), path, node.lineno))
    return sites


def _contract_entry(
    name: str, contract: dict
) -> Optional[tuple[str, Optional[frozenset]]]:
    """The contract entry covering ``name``: exact first, then the
    longest ``prefix*`` pattern."""
    if name in contract:
        return name, contract[name]
    best = None
    for key, labels in contract.items():
        if key.endswith("*") and name.startswith(key[:-1]):
            if best is None or len(key) > len(best[0]):
                best = (key, labels)
    return best


def analyze_obs(
    trees: dict,
    repo_root: Optional[Path],
    *,
    contract: Optional[dict] = None,
    obs_doc: Optional[str] = None,
    scope: str = "banyandb",
) -> list[Finding]:
    contract = _cfg.OBS_CONTRACT if contract is None else contract
    obs_doc = _cfg.OBS_DOC if obs_doc is None else obs_doc
    findings: list[Finding] = []
    sites = instrument_sites(trees)
    hit_entries: set[str] = set()
    flagged_names: set[str] = set()
    for name, labels, path, line in sites:
        entry = _contract_entry(name, contract)
        if entry is None:
            if name in flagged_names:
                continue
            flagged_names.add(name)
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"instrument `{name}` is not in OBS_CONTRACT — "
                        f"declare it (name -> label keys) and cite it in "
                        f"{obs_doc}"
                    ),
                )
            )
            continue
        key, want_labels = entry
        hit_entries.add(key)
        if want_labels is None or labels is None:
            continue  # pattern entry / dynamic labels: no label check
        if labels != want_labels:
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"instrument `{name}` used with label keys "
                        f"{sorted(labels)} but OBS_CONTRACT pins "
                        f"{sorted(want_labels)} — one instrument, one "
                        f"label set"
                    ),
                )
            )
    for key in sorted(set(contract) - hit_entries):
        findings.append(
            Finding(
                path="<wire-config>",
                line=1,
                col=0,
                rule=RULE,
                message=(
                    f"stale OBS_CONTRACT entry `{key}`: no live call site "
                    f"— delete the entry (the contract tracks the code)"
                ),
            )
        )

    # docs cross-reference (skipped when the doc is absent)
    if repo_root is None or not contract:
        return findings
    doc_path = Path(repo_root) / obs_doc
    if not doc_path.exists():
        return findings
    text = doc_path.read_text()
    for key in sorted(contract):
        bare = key.rstrip("*")
        if bare and bare not in text:
            findings.append(
                Finding(
                    path=str(doc_path),
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"contracted instrument `{bare}` is not mentioned "
                        f"in {obs_doc} — document it"
                    ),
                )
            )
    pfx = scope + "_"
    for token in sorted(set(re.findall(rf"{re.escape(pfx)}\w+", text))):
        bare = token[len(pfx):]
        if bare.startswith("tpu"):
            # "banyandb_tpu..." is the package name, not a metric: the
            # scope prefix collides with it by construction
            continue
        for suffix in ("_total", "_bucket", "_count", "_sum"):
            if bare.endswith(suffix):
                bare = bare[: -len(suffix)]
                break
        if _contract_entry(bare, contract) is None:
            findings.append(
                Finding(
                    path=str(doc_path),
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"{obs_doc} cites `{token}` but no contracted "
                        f"instrument matches `{bare}` — fix the doc or "
                        f"declare the instrument"
                    ),
                )
            )
    return findings
