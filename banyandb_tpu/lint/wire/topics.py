"""wire-topic: role/topic exhaustiveness over the bus fabric.

Three passes over the shared parsed package + callgraph:

1. **Topic constant discovery** — every ``Topic`` enum member and every
   module-level ``*TOPIC*`` string constant (including one-hop aliases)
   becomes a dotted-name -> wire-string entry, so call sites and
   registrations resolve without importing the package.
2. **Registration discovery** — per role (wire_config.ROLES), a BFS
   from the registrar function over resolved callees collects every
   ``<bus>.subscribe(topic, handler)`` call: the role's served set,
   with the handler qual when the handler is a resolvable name/method
   (lambdas register the topic but expose no envelope consumer).
3. **Client call-site audit** — every ``X.call(...)`` whose topic
   argument resolves to a known topic constant, in a module with
   declared targets (wire_config.CLIENT_TARGETS): the topic must be
   served by every target role or carry a TOPIC_EXEMPTIONS reason.
   The PR-10 "liaison missing the streamagg surface" bug class is a
   finding here, permanently.

The discovered matrix is additionally diffed against
wire_config.EXPECTED_MATRIX (the golden the smoke prints): a topic
registered but not declared — or declared but no longer registered —
fails, so the checked-in matrix can never drift from the code.
"""

from __future__ import annotations

import ast
from typing import Optional

from banyandb_tpu.lint.core import Finding, dotted_name
from banyandb_tpu.lint.whole_program.callgraph import FuncInfo, Program, _walk_own

from banyandb_tpu.lint.wire import wire_config as _cfg

RULE = "wire-topic"


# -- topic constant discovery -------------------------------------------------


def topic_constants(trees: dict) -> dict[str, str]:
    """dotted constant name -> wire topic string, package-wide.

    Collects ``Topic`` enum members (``mod.Topic.NAME``), module-level
    string constants whose name contains ``TOPIC`` (``mod.NAME``), and
    one-hop aliases of either (``TOPIC_DIAGNOSTICS = DIAG_TOPIC``).
    """
    consts: dict[str, str] = {}
    aliases: dict[str, str] = {}  # dotted -> dotted it refers to
    for mod, (_path, tree) in trees.items():
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == "Topic":
                for stmt in node.body:
                    if (
                        isinstance(stmt, ast.Assign)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)
                    ):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                consts[f"{mod}.Topic.{t.id}"] = stmt.value.value
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if not (isinstance(t, ast.Name) and "TOPIC" in t.id):
                        continue
                    if isinstance(node.value, ast.Constant) and isinstance(
                        node.value.value, str
                    ):
                        consts[f"{mod}.{t.id}"] = node.value.value
                    else:
                        ref = dotted_name(node.value)
                        if ref:
                            aliases[f"{mod}.{t.id}"] = f"{mod}.{ref}"
    # resolve aliases through imports is the resolver's job; here only
    # same-module references resolve (TOPIC_X = OTHER_TOPIC)
    for name, ref in aliases.items():
        if ref in consts:
            consts[name] = consts[ref]
    return consts


def resolve_topic(
    expr: ast.AST,
    module: str,
    imports: dict[str, str],
    consts: dict[str, str],
) -> Optional[str]:
    """Wire topic string for a call-site/registration expression, or
    None when the expression is not statically a topic constant."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    ids: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        ids.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    ids.append(node.id)
    ids.reverse()
    if ids[-1] == "value":  # Topic.X.value
        ids = ids[:-1]
    head = ids[0]
    candidates = [f"{module}." + ".".join(ids)]
    if head in imports:
        candidates.append(".".join([imports[head], *ids[1:]]))
    for cand in candidates:
        if cand in consts:
            return consts[cand]
    return None


# -- registration discovery ---------------------------------------------------


def _resolve_handler(
    expr: ast.AST, info: FuncInfo, program: Program
) -> Optional[str]:
    """Qual of a subscribe() handler argument: ``self._fn`` ->
    "mod:Class._fn"; a bare name -> "mod:fn" when it exists."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id in ("self", "cls")
        and info.cls
    ):
        qual = f"{info.module}:{info.cls}.{expr.attr}"
        return qual if qual in program.functions else None
    if isinstance(expr, ast.Name):
        qual = f"{info.module}:{expr.id}"
        return qual if qual in program.functions else None
    return None


def subscriptions(
    program: Program,
    registrars: tuple[str, ...],
    consts: dict[str, str],
    max_depth: int = 3,
) -> dict[str, tuple[Optional[str], str, int]]:
    """topic -> (handler qual or None, path, line) reachable from the
    role's registrar functions (BFS over resolved callees, so helper
    registrars like schema_gossip.register_handlers count)."""
    out: dict[str, tuple[Optional[str], str, int]] = {}
    seen: set[str] = set()
    work: list[tuple[str, int]] = [(q, 0) for q in registrars]
    while work:
        qual, depth = work.pop()
        if qual in seen or qual not in program.functions:
            continue
        seen.add(qual)
        info = program.functions[qual]
        imports = program.tables.get(info.module, {})
        for node in _walk_own(info.node):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "subscribe"
                and len(node.args) >= 2
            ):
                topic = resolve_topic(node.args[0], info.module, imports, consts)
                if topic is not None and topic not in out:
                    handler = _resolve_handler(node.args[1], info, program)
                    out[topic] = (handler, info.path, node.lineno)
        if depth < max_depth:
            for site in info.calls:
                if site.callee:
                    work.append((site.callee, depth + 1))
    return out


def role_topic_matrix(
    program: Program,
    trees: dict,
    roles: Optional[dict[str, tuple[str, ...]]] = None,
) -> dict[str, dict[str, tuple[Optional[str], str, int]]]:
    """role -> {topic -> (handler qual, path, line)} for every role
    whose registrar exists in the program (seeded test packages resolve
    none of the real roles and get an empty matrix)."""
    roles = _cfg.ROLES if roles is None else roles
    consts = topic_constants(trees)
    out: dict[str, dict] = {}
    for role, regs in roles.items():
        if any(q in program.functions for q in regs):
            out[role] = subscriptions(program, regs, consts)
    return out


# -- client call sites --------------------------------------------------------


def client_sites(
    program: Program,
    consts: dict[str, str],
    client_targets: dict[str, tuple[str, ...]],
    known: Optional[set[str]] = None,
) -> list[tuple[str, tuple[str, ...], str, int, str]]:
    """(topic, target roles, path, line, caller qual) for every
    ``X.call(...)`` whose topic argument resolves, in client modules.

    Handles both transport signatures: ``transport.call(addr, topic,
    env)`` (topic in position 1 — taken whenever it resolves, so a
    typo'd or unregistered topic still surfaces) and the worker
    client's ``client.call(topic, env)`` (position 0 — accepted only
    when the resolved string is a ``known`` topic, so address literals
    in position 0 of the other signature never masquerade as topics).
    """
    sites = []
    for info in program.functions.values():
        targets = client_targets.get(info.module)
        if not targets:
            continue
        imports = program.tables.get(info.module, {})
        for node in _walk_own(info.node):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "call"
                and len(node.args) >= 2
            ):
                continue
            topic = resolve_topic(node.args[1], info.module, imports, consts)
            if topic is None:
                topic = resolve_topic(node.args[0], info.module, imports, consts)
                if known is not None and topic not in known:
                    topic = None
            if topic is not None:
                sites.append((topic, targets, info.path, node.lineno, info.qual))
    return sites


# -- the analyzer -------------------------------------------------------------


def analyze_topics(
    program: Program,
    trees: dict,
    *,
    roles: Optional[dict[str, tuple[str, ...]]] = None,
    client_targets: Optional[dict[str, tuple[str, ...]]] = None,
    exemptions: Optional[dict[tuple[str, str], str]] = None,
    expected_matrix: Optional[dict[str, tuple[str, ...]]] = None,
    baseline_path: str = "<wire-config>",
) -> list[Finding]:
    roles = _cfg.ROLES if roles is None else roles
    client_targets = (
        _cfg.CLIENT_TARGETS if client_targets is None else client_targets
    )
    exemptions = _cfg.TOPIC_EXEMPTIONS if exemptions is None else exemptions
    expected_matrix = (
        _cfg.EXPECTED_MATRIX if expected_matrix is None else expected_matrix
    )
    consts = topic_constants(trees)
    matrix = role_topic_matrix(program, trees, roles)
    findings: list[Finding] = []

    # 1. every client-invoked topic served by every target role
    known = set(consts.values())
    for served in matrix.values():
        known.update(served)
    used_exemptions: set[tuple[str, str]] = set()
    flagged: set[tuple[str, str]] = set()
    for topic, targets, path, line, qual in client_sites(
        program, consts, client_targets, known
    ):
        for role in targets:
            if role not in matrix:
                continue  # registrar not in this package (seeded pkgs)
            if topic in matrix[role]:
                continue
            if (role, topic) in exemptions:
                used_exemptions.add((role, topic))
                continue
            if (role, topic) in flagged:
                continue  # one finding per gap, not per call site
            flagged.add((role, topic))
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"topic `{topic}` is invoked against role "
                        f"`{role}` (from {qual.split(':', 1)[1]}) but the "
                        f"role registers no handler for it; register one "
                        f"in {', '.join(roles[role])} or add a reasoned "
                        f"TOPIC_EXEMPTIONS entry"
                    ),
                )
            )
    # stale exemptions: the gap no longer exists (or the role vanished)
    for (role, topic), _reason in sorted(exemptions.items()):
        if role in matrix and topic in matrix[role]:
            findings.append(
                Finding(
                    path=baseline_path,
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"stale TOPIC_EXEMPTIONS entry ({role!r}, "
                        f"{topic!r}): the role now serves the topic — "
                        f"delete the entry (the table only shrinks)"
                    ),
                )
            )

    # 2. golden matrix drift, both directions
    for role, served in sorted(matrix.items()):
        declared = set(expected_matrix.get(role, ()))
        live = set(served)
        for topic in sorted(live - declared):
            _h, path, line = served[topic]
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"role `{role}` registers topic `{topic}` that "
                        f"EXPECTED_MATRIX does not declare; add it to the "
                        f"checked-in matrix (wire_config.py)"
                    ),
                )
            )
        for topic in sorted(declared - live):
            findings.append(
                Finding(
                    path=baseline_path,
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"EXPECTED_MATRIX declares topic `{topic}` on role "
                        f"`{role}` but no registration exists — remove the "
                        f"stale entry or restore the handler"
                    ),
                )
            )
    return findings
