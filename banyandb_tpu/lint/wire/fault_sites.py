"""wire-fault: every fabric boundary must sit behind a chaos hook.

cluster/faults.py is the package's single fault plane; scale-out tests
drive it to prove the retry/spool/failover machinery.  A boundary the
plane cannot reach is a boundary the chaos suite silently stopped
testing — this analyzer makes that a gate:

1. **RPC transports** — every ``*Transport`` class with a ``call``
   method must invoke ``faults.maybe_fail_rpc`` inside that method (or
   carry a FAULT_TRANSPORT_EXEMPT reason).  New transports are covered
   the day they are written.
2. **Chunked-sync streams** — each SYNC_MODULES module must install
   ``plane_sync_injector`` at least once, so stream-level fault points
   (truncate / flip / stall) stay reachable.
3. **Spool/part disk writes** — a disk-write call (``atomic_write`` /
   ``write_bytes`` / ``write_text`` / ``open(..., "w"/"a"/"x")``) in a
   DISK_SCAN_PREFIXES module must have ``faults.check_disk`` in the
   enclosing function or a transitive caller (3 hops), or carry a
   DISK_EXEMPT reason — the gate the cold tier's remote reads will be
   built under (ROADMAP item 2).
"""

from __future__ import annotations

import ast
from typing import Optional

from banyandb_tpu.lint.core import Finding, dotted_name
from banyandb_tpu.lint.whole_program.callgraph import Program, _walk_own

from banyandb_tpu.lint.wire import wire_config as _cfg

RULE = "wire-fault"

_DISK_WRITE_ATTRS = ("atomic_write", "write_bytes", "write_text")


def _calls_matching(info, needle: str) -> bool:
    for node in _walk_own(info.node):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if needle in name:
                return True
    return False


def _reverse_edges(program: Program) -> dict[str, set[str]]:
    rev: dict[str, set[str]] = {}
    for qual, info in program.functions.items():
        for site in info.calls:
            if site.callee:
                rev.setdefault(site.callee, set()).add(qual)
    return rev


def _covered(
    program: Program,
    rev: dict[str, set[str]],
    qual: str,
    needle: str,
    max_depth: int = 3,
) -> bool:
    """True when ``qual`` or a transitive caller (within max_depth)
    calls something matching ``needle``."""
    seen: set[str] = set()
    work = [(qual, 0)]
    while work:
        q, depth = work.pop()
        if q in seen:
            continue
        seen.add(q)
        info = program.functions.get(q)
        if info is not None and _calls_matching(info, needle):
            return True
        if depth < max_depth:
            for caller in rev.get(q, ()):
                work.append((caller, depth + 1))
    return False


def _disk_write_sites(info) -> list[tuple[str, int]]:
    sites: list[tuple[str, int]] = []
    for node in _walk_own(info.node):
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _DISK_WRITE_ATTRS
        ):
            sites.append((node.func.attr, node.lineno))
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id == "open"
            and len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
            and any(m in node.args[1].value for m in ("w", "a", "x"))
        ):
            sites.append(("open", node.lineno))
    return sites


def analyze_fault_sites(
    program: Program,
    *,
    transport_exempt: Optional[dict[str, str]] = None,
    disk_prefixes: Optional[tuple[str, ...]] = None,
    disk_exempt: Optional[dict[tuple[str, str], str]] = None,
    sync_modules: Optional[tuple[str, ...]] = None,
    baseline_path: str = "<wire-config>",
) -> list[Finding]:
    transport_exempt = (
        _cfg.FAULT_TRANSPORT_EXEMPT
        if transport_exempt is None
        else transport_exempt
    )
    disk_prefixes = (
        _cfg.DISK_SCAN_PREFIXES if disk_prefixes is None else disk_prefixes
    )
    disk_exempt = _cfg.DISK_EXEMPT if disk_exempt is None else disk_exempt
    sync_modules = _cfg.SYNC_MODULES if sync_modules is None else sync_modules
    findings: list[Finding] = []

    # 1. transports: every *Transport.call behind maybe_fail_rpc
    for qual, info in sorted(program.functions.items()):
        if info.cls is None or not info.cls.endswith("Transport"):
            continue
        if qual.split(".")[-1] != "call" or qual.rsplit(".", 1)[0] != (
            f"{info.module}:{info.cls}"
        ):
            continue
        key = f"{info.module}:{info.cls}"
        if key in transport_exempt:
            continue
        if not _calls_matching(info, "maybe_fail_rpc"):
            findings.append(
                Finding(
                    path=info.path,
                    line=info.node.lineno,
                    col=0,
                    rule=RULE,
                    message=(
                        f"transport {info.cls}.call carries RPCs without a "
                        f"faults.maybe_fail_rpc hook — the chaos plane "
                        f"cannot reach this wire; hook it or add a "
                        f"FAULT_TRANSPORT_EXEMPT reason"
                    ),
                )
            )

    # 2. chunked-sync streams: plane_sync_injector present per module
    for mod in sync_modules:
        mod_fns = [i for i in program.functions.values() if i.module == mod]
        if not mod_fns:
            continue
        if not any(_calls_matching(i, "plane_sync_injector") for i in mod_fns):
            anchor = min(mod_fns, key=lambda i: i.node.lineno)
            findings.append(
                Finding(
                    path=anchor.path,
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"sync module {mod} installs no plane_sync_injector "
                        f"— stream-level fault points (truncate/flip/stall) "
                        f"are unreachable"
                    ),
                )
            )

    # 3. spool/part disk writes behind check_disk
    rev = _reverse_edges(program)
    for qual, info in sorted(program.functions.items()):
        if not info.module.startswith(disk_prefixes):
            continue
        sites = _disk_write_sites(info)
        if not sites:
            continue
        fn = qual.split(":", 1)[1]
        if any(
            info.module == mod and fn.endswith(suffix)
            for (mod, suffix) in disk_exempt
        ):
            continue
        if _covered(program, rev, qual, "check_disk"):
            continue
        writer, line = sites[0]
        findings.append(
            Finding(
                path=info.path,
                line=line,
                col=0,
                rule=RULE,
                message=(
                    f"disk-write boundary ({writer}) in {fn} has no "
                    f"faults.check_disk on its path — ENOSPC/short-write "
                    f"chaos cannot reach it; add a check_disk site or a "
                    f"reasoned DISK_EXEMPT entry"
                ),
            )
        )

    # stale exemption hygiene: every exempt key must still match a live
    # disk-writing function / transport
    live_transport = {
        f"{i.module}:{i.cls}"
        for i in program.functions.values()
        if i.cls and i.cls.endswith("Transport")
    }
    for key in sorted(set(transport_exempt) - live_transport):
        findings.append(
            Finding(
                path=baseline_path,
                line=1,
                col=0,
                rule=RULE,
                message=(
                    f"stale FAULT_TRANSPORT_EXEMPT entry {key!r}: no such "
                    f"transport class exists — delete the entry"
                ),
            )
        )
    for (mod, suffix), _reason in sorted(disk_exempt.items()):
        hit = any(
            i.module == mod
            and q.split(":", 1)[1].endswith(suffix)
            and _disk_write_sites(i)
            for q, i in program.functions.items()
        )
        if not hit and any(i.module == mod for i in program.functions.values()):
            findings.append(
                Finding(
                    path=baseline_path,
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"stale DISK_EXEMPT entry ({mod!r}, {suffix!r}): no "
                        f"matching disk-write site remains — delete the "
                        f"entry"
                    ),
                )
            )
    return findings
