"""bdwire checked-in policy: the wire-contract facts the analyzers gate.

This module is the protocol's source of truth the way layer_config.py is
the layer map's: every table here is reviewed policy, not cache.  The
analyzers (lint/wire/*.py) discover the live facts from the AST and
diff them against these tables — drift in EITHER direction is a
finding, so adding a topic, a wire kind, an envelope field or a fault
boundary without updating the contract fails ``--check``.

Tables:

- ``ROLES`` / ``EXPECTED_MATRIX``   who registers which bus topics
- ``CLIENT_TARGETS`` / ``TOPIC_EXEMPTIONS``   who dials whom, and which
  role/topic gaps are by design (each with its reviewed reason)
- ``DECLARED_KINDS`` / ``RETRYABLE_KINDS`` / ``TRANSPORT_KINDS`` /
  ``CLASSIFIER_SWITCHES``   the wire-kind taxonomy and every site that
  must stay exhaustive over it
- ``ENVELOPE_GROUPS``   producer/consumer quals per envelope plane plus
  the accepted write-only / silent-default baselines (ratcheted)
- ``DISK_SCAN_PREFIXES`` / ``DISK_EXEMPT`` / ``SYNC_MODULES``   the
  fault-coverage surface (cluster/faults.py sites)
- ``RETRY_SUBSTRINGS`` / ``RETRY_EXEMPT``   what counts as reaching a
  retry/spool/shed path after a retryable rejection
- ``OBS_CONTRACT``   instrument name -> label-key set (docs/observability.md)
- ``ENVFLAG_MODULE``   where the one BYDB_* parser + FLAGS registry live
"""

from __future__ import annotations

PKG = "banyandb_tpu"

# -- roles: registrar functions whose reachable bus.subscribe() calls
# define the role's served topic set --------------------------------------
ROLES: dict[str, tuple[str, ...]] = {
    "standalone": ("banyandb_tpu.server:StandaloneServer._register",),
    "liaison": ("banyandb_tpu.cluster_server:LiaisonServer._register",),
    "data": ("banyandb_tpu.cluster.data_node:DataNode._register_handlers",),
    # a worker serves the full DataNode surface plus the parent's
    # control topic (registered in worker_main, the process entry)
    "worker": (
        "banyandb_tpu.cluster.data_node:DataNode._register_handlers",
        "banyandb_tpu.cluster.workers:worker_main",
    ),
}

# The golden role/topic matrix (scripts/wire_smoke.py prints it; the
# topic analyzer fails on drift in either direction).  Sorted tuples.
EXPECTED_MATRIX: dict[str, tuple[str, ...]] = {
    "standalone": (
        "bydbql",
        "diagnostics",
        "fodc-pprof",
        "health",
        "measure-query-raw",
        "measure-write",
        "measure-write-cols",
        "metrics",
        "property-apply",
        "property-query",
        "qos",
        "registry",
        "slowlog",
        "snapshot",
        "stream-query-user",
        "stream-write",
        "streamagg",
        "topn",
        "trace-query-by-id",
        "trace-write",
    ),
    "liaison": (
        "bydbql",
        "health",
        "measure-write",
        "metrics",
        "qos",
        "rebalance",
        "registry",
        "slowlog",
        "stream-write",
        "streamagg",
        "trace-query-by-id",
        "trace-write",
    ),
    "data": (
        "diagnostics",
        "flush",
        "health",
        "measure-query-partial",
        "measure-query-raw",
        "measure-write",
        "measure-write-cols",
        "metrics",
        "placement",
        "rebalance",
        "schema-digest",
        "schema-get",
        "schema-pull",
        "schema-sync",
        "stream-query",
        "stream-write",
        "streamagg",
        "sync-part",
        "topn",
        "trace-query-by-id",
        "trace-query-exec",
        "trace-query-ordered",
        "trace-write",
    ),
    "worker": (
        "diagnostics",
        "flush",
        "health",
        "measure-query-partial",
        "measure-query-raw",
        "measure-write",
        "measure-write-cols",
        "metrics",
        "placement",
        "rebalance",
        "schema-digest",
        "schema-get",
        "schema-pull",
        "schema-sync",
        "stream-query",
        "stream-write",
        "streamagg",
        "sync-part",
        "topn",
        "trace-query-by-id",
        "trace-query-exec",
        "trace-query-ordered",
        "trace-write",
        "worker-ctl",
    ),
}

# Which roles each client module dials.  Every resolved topic a module
# invokes must be served by EVERY listed role, or carry a
# TOPIC_EXEMPTIONS entry.
CLIENT_TARGETS: dict[str, tuple[str, ...]] = {
    "banyandb_tpu.cli": ("standalone", "liaison"),
    "banyandb_tpu.cluster.liaison": ("data",),
    "banyandb_tpu.cluster_server": ("data",),
    "banyandb_tpu.cluster.rebalance": ("data",),
    "banyandb_tpu.cluster.schema_plane": ("data",),
    "banyandb_tpu.cluster.schema_gossip": ("data",),
    "banyandb_tpu.cluster.workers": ("worker",),
    "banyandb_tpu.admin.fodc": ("data",),
}

# (role, topic) pairs a client dials that the role does NOT serve — by
# design, with the reviewed reason.  Removing the gap (registering the
# handler) makes the entry stale, which fails the gate: the table only
# shrinks.
TOPIC_EXEMPTIONS: dict[tuple[str, str], str] = {
    ("liaison", "snapshot"): (
        "cli snapshot targets part-owning roles; the liaison holds no "
        "parts (wqueue spool snapshots ride the data-node topic)"
    ),
    ("liaison", "property-apply"): (
        "the property plane is standalone-only until the cold tier "
        "lands its replicated property store (ROADMAP item 2)"
    ),
    ("liaison", "property-query"): (
        "the property plane is standalone-only until the cold tier "
        "lands its replicated property store (ROADMAP item 2)"
    ),
    ("standalone", "rebalance"): (
        "a standalone server owns every shard by definition; there is "
        "no placement to rebalance (cli rebalance is cluster-only)"
    ),
}

# -- wire kinds -----------------------------------------------------------
DECLARED_KINDS: tuple[str, ...] = ("deadline", "error", "shed", "stale_epoch")
# kinds a healthy node uses to refuse work: the sender must retry /
# spool / degrade, never evict (TransportError docstring, cluster/rpc.py)
RETRYABLE_KINDS: frozenset[str] = frozenset(
    {"deadline", "shed", "stale_epoch"}
)

# exception classes that carry a wire kind
ERROR_CLASSES: tuple[str, ...] = ("TransportError",)

# per-transport-module kind vocabulary: every kind literal the module
# raises/classifies must appear here and vice versa (both-direction
# drift fails).  A transport that cannot express a declared kind cannot
# carry its contract.
TRANSPORT_KINDS: dict[str, frozenset[str]] = {
    "banyandb_tpu.cluster.rpc": frozenset(DECLARED_KINDS),
    # the worker wire relays rpc._error_kind's verdict through a dict
    # passthrough ({"kind": _error_kind(e)}) — the only LITERALS the
    # module itself speaks are the deadline raise and the "error"
    # default; shed/stale_epoch ride the passthrough untyped
    "banyandb_tpu.cluster.workers": frozenset({"deadline", "error"}),
}

# classifier/receiver switches that must stay exhaustive: qual -> the
# kind literals that MUST appear in the function body.  Adding a kind to
# DECLARED_KINDS without teaching these sites fails the gate.
CLASSIFIER_SWITCHES: dict[str, frozenset[str]] = {
    # the one server-side exception->kind classifier (both transports)
    "banyandb_tpu.cluster.rpc:_error_kind": frozenset(DECLARED_KINDS),
    # the write-plane delivery switch: every retryable kind needs an
    # explicit healthy-node branch (the else marks the node dead)
    "banyandb_tpu.cluster.liaison:Liaison._deliver_writes": RETRYABLE_KINDS,
    # the scatter failover switch: retryable kinds mark the guard, a
    # hard error marks the node dead and retries elsewhere
    "banyandb_tpu.cluster.liaison:Liaison._scatter_one": frozenset(
        {"deadline", "shed", "stale_epoch"}
    ),
}

# -- envelope planes ------------------------------------------------------
# Each group: producer quals (envelope-building functions; every dict
# key/dict(x, k=...) keyword/subscript store inside them is a produced
# field), consumer quals (topic handlers; env-param reads are consumed
# fields, followed one hop when the env is passed whole), and the
# ratcheted accepted sets.
ENVELOPE_GROUPS: dict[str, dict] = {
    "write": {
        "producers": (
            "banyandb_tpu.cluster.liaison:Liaison.write_measure.env_for",
            "banyandb_tpu.cluster.liaison:Liaison.write_stream.env_for",
            "banyandb_tpu.cluster.liaison:Liaison.write_trace.env_for",
            "banyandb_tpu.cluster.liaison:Liaison._stamp_epoch",
            "banyandb_tpu.cluster.liaison:Liaison._stamp_tenant",
        ),
        "consumers": (
            "banyandb_tpu.cluster.data_node:DataNode._on_measure_write",
            "banyandb_tpu.cluster.data_node:DataNode._on_stream_write",
            "banyandb_tpu.cluster.data_node:DataNode._on_trace_write",
        ),
        "accepted_write_only": {},
        "accepted_silent_default": {
            "ordered_tags": (
                "trace writes spooled before the ordered-retrieval era "
                "replay without the field; the () default degrades to "
                "unordered sidx build instead of stranding the spool"
            ),
        },
    },
    "scatter": {
        "producers": (
            "banyandb_tpu.cluster.liaison:Liaison._scatter_one",
            "banyandb_tpu.cluster.liaison:Liaison._stamp_epoch",
        ),
        "consumers": (
            "banyandb_tpu.cluster.data_node:DataNode._on_measure_query_partial",
            "banyandb_tpu.cluster.data_node:DataNode._on_measure_query_raw",
            "banyandb_tpu.cluster.data_node:DataNode._on_stream_query",
            "banyandb_tpu.cluster.data_node:DataNode._on_trace_query_exec",
            "banyandb_tpu.cluster.data_node:DataNode._on_trace_query_ordered",
        ),
        "accepted_write_only": {},
        "accepted_silent_default": {},
    },
    "sync": {
        "producers": (
            "banyandb_tpu.cluster.liaison:ChunkedSyncClient.sync_part",
        ),
        "consumers": (
            "banyandb_tpu.cluster.data_node:DataNode._on_sync_part",
        ),
        "accepted_write_only": {},
        "accepted_silent_default": {},
    },
}

# -- fault-site coverage --------------------------------------------------
# transports whose .call() needs no maybe_fail_rpc hook, with reasons
FAULT_TRANSPORT_EXEMPT: dict[str, str] = {}
# modules whose spool/part write boundaries the disk site must cover
DISK_SCAN_PREFIXES: tuple[str, ...] = ("banyandb_tpu.cluster.",)
# (module, function-suffix) -> reason: disk writes that are NOT part of
# the spool/part data plane (control-plane metadata, bounded caches)
DISK_EXEMPT: dict[tuple[str, str], str] = {
    ("banyandb_tpu.cluster.data_node", "DataNode.__init__"): (
        "advisory .bydb-node.pid owner record at startup; a failed "
        "write fails the boot, there is no wire retry to exercise"
    ),
    ("banyandb_tpu.cluster.workers", "WorkerClient.__init__"): (
        "worker.log append handle opened once at spawn for crash "
        "forensics; no data-plane bytes ride it"
    ),
}
# modules that must carry at least one plane_sync_injector hook
SYNC_MODULES: tuple[str, ...] = ("banyandb_tpu.cluster.chunked_sync",)

# -- retryable handling ---------------------------------------------------
# A TransportError handler body (or a call it makes) must reach one of
# these — substring match on called-name segments — to count as a
# retry/spool/shed path rather than a bare swallow/raise.
RETRY_SUBSTRINGS: tuple[str, ...] = (
    "retry",
    "retries",
    "spool",
    "replay",
    "restart",
    "respawn",
    "mark",
    "evict",
    "reload",
    "shed",
    "degrad",
    "requeue",
    "pending",
    "failover",
    "backoff",
    "redeliver",
    "probe",
)
# qual -> reason: handlers that legitimately terminate the error
RETRY_EXEMPT: dict[str, str] = {
    "banyandb_tpu.admin.fodc:FodcProxy._poll_node": (
        "terminal diagnostics collector: an unreachable node is "
        "REPORTED as unreachable in the bundle — that is the output"
    ),
    "banyandb_tpu.cluster.liaison:Liaison.probe": (
        "the probe IS the recovery detector; the supervisor's next "
        "probe tick retries by construction"
    ),
    "banyandb_tpu.cluster.liaison:Liaison.schema_barrier": (
        "the enclosing barrier loop polls until its deadline; one "
        "failed round is just a not-yet-converged node"
    ),
    "banyandb_tpu.cluster.rebalance:Rebalancer._ship_round": (
        "a missing remote manifest degrades to have={} and ships "
        "every part — over-shipping is the recovery"
    ),
    "banyandb_tpu.cluster.rebalance:ReplicaRepairer.run_once": (
        "anti-entropy: a failed repair leg is retried on the next "
        "repair round, state lives in the part manifests"
    ),
    "banyandb_tpu.cluster.schema_gossip:SchemaGossiper.run_once": (
        "anti-entropy: digests re-exchange next gossip round; no "
        "per-message recovery exists or is needed"
    ),
    "banyandb_tpu.cluster.schema_plane:LiaisonBarrier.await_deleted.check": (
        "await-loop predicate: the caller polls check() until its "
        "deadline; a transport failure is one false poll"
    ),
    "banyandb_tpu.cluster.workers:WorkerPool._forward_write": (
        "journal-ack spool: the parent journal holds the write until "
        "the worker acks; restart replay redelivers it"
    ),
    "banyandb_tpu.cluster.workers:WorkerPool.topn": (
        "scatter degrades over surviving workers; the supervisor "
        "restarts the dead one out of band"
    ),
    "banyandb_tpu.cluster.workers:WorkerPool.streamagg": (
        "stats fan-in is degradable: a missing worker's slice is "
        "absent from the merged view until its restart"
    ),
    "banyandb_tpu.cluster.workers:WorkerPool.flush": (
        "the supervise tick re-drives flush; the journal watermark "
        "guarantees nothing is lost between ticks"
    ),
    "banyandb_tpu.cluster.workers:WorkerPool._restart": (
        "kill+close then re-raise to the supervise loop, which "
        "respawns the worker — the raise IS the recovery hand-off"
    ),
    "banyandb_tpu.cluster.workers:WorkerPool._supervise": (
        "the supervise loop is the retry: failure state persists to "
        "the next tick's health pass"
    ),
    "banyandb_tpu.cluster.workers:WorkerPool.stop": (
        "best-effort shutdown: a worker that cannot be told to stop "
        "is killed by the process-group teardown"
    ),
}

# -- env-flag registry ----------------------------------------------------
ENVFLAG_MODULE = "banyandb_tpu.utils.envflag"
ENVFLAG_FUNCS = ("env_flag", "env_int", "env_float", "env_str")
ENV_PREFIX = "BYDB_"
FLAGS_DOC = "docs/flags.md"

# -- obs contract ---------------------------------------------------------
# instrument name -> the one label-key set every call site must use
# (frozenset(); None = pattern entry, names are matched as prefixes for
# f-string instruments).  docs/observability.md must mention each name.
# Populated from the audited inventory; drift in either direction fails.
OBS_CONTRACT: dict[str, frozenset | None] = {
    # f-string families (prefix patterns); labels pinned where the
    # whole family shares one set
    "autoreg_*": frozenset(),
    "compile_cache_*": frozenset(),
    "precompile_*": frozenset(),
    "qos_*": frozenset({"tenant"}),
    "rpc_*": frozenset({"topic"}),
    "serving_cache_*": frozenset({"tenant"}),
    # exact instruments
    "autoreg_signatures": frozenset({"source"}),
    "blocks_skipped": frozenset({"reason"}),
    "compile_cache_enabled": frozenset(),
    "decode_ship_bytes": frozenset({"form"}),
    "failover_attempts": frozenset(),
    "fault_injected": frozenset({"kind", "site"}),
    "kernel_dispatch_budget": frozenset({"signature"}),
    "lifecycle_stage_ms": frozenset({"stage"}),
    "measure_query_ms": frozenset(),
    "measure_write_points": frozenset(),
    "placement_epoch": frozenset(),
    "planner_decisions": frozenset({"path"}),
    "qos_enabled": frozenset(),
    "qos_inflight_bytes": frozenset({"tenant"}),
    "qos_inflight_shed": frozenset({"tenant"}),
    "qos_query_active": frozenset({"tenant"}),
    "qos_query_waiting": frozenset({"tenant"}),
    "qos_queue_ms": frozenset({"tenant"}),
    "query_degraded": frozenset({"engine"}),
    "query_ms": frozenset({"engine"}),
    "query_stage_ms": frozenset({"stage"}),
    "rebalance_parts_moved": frozenset(),
    "rebalance_parts_planned": frozenset(),
    "rebalance_shards_to_move": frozenset(),
    "repair_parts_shipped": frozenset(),
    "rss_bytes": frozenset(),
    "selftrace_dropped": frozenset(),
    "selftrace_spans": frozenset(),
    "stale_epoch_rejected": frozenset({"site"}),
    "streamagg_invalidated": frozenset(),
    "streamagg_late_dropped": frozenset(),
    "streamagg_reads": frozenset({"kind"}),
    "streamagg_rows": frozenset(),
    "streamagg_signatures": frozenset(),
    "streamagg_states": frozenset(),
    "streamagg_watermark_ms": frozenset({"signature"}),
    "streamagg_windows": frozenset(),
    "streamagg_windows_evicted": frozenset(),
    "worker_journal_shed": frozenset({"worker"}),
    "worker_restarts": frozenset({"worker"}),
    "workers_alive": frozenset(),
    "workers_total": frozenset(),
    "wqueue_sealed_rows": frozenset(),
    "wqueue_shed": frozenset(),
    "wqueue_ship_retry": frozenset(),
    "wqueue_shipped": frozenset(),
    "wqueue_spool_bytes": frozenset(),
    "write_ms": frozenset({"model"}),
}
OBS_DOC = "docs/observability.md"
