"""bdwire: whole-program wire-contract & fault-coverage audit.

The fourth whole-program family on the bdlint engine (after layering /
sync / shared-state and the bdjit kernel audit).  Seven analyzers over
the shared parsed package + callgraph, each diffing discovered facts
against the checked-in policy in wire_config.py — drift in either
direction is a finding:

- ``wire-topic``     role/topic exhaustiveness: every client-invoked bus
                     topic served on every target role; the golden
                     matrix (EXPECTED_MATRIX) cannot drift
- ``wire-kind``      the error/shed/deadline/stale_epoch taxonomy:
                     vocabulary, per-transport consistency, classifier
                     switch exhaustiveness
- ``wire-envelope``  producer/consumer field matching per envelope
                     plane; write-only and silent-default fields
- ``wire-fault``     every RPC transport, chunked-sync stream and
                     spool/part disk write behind a cluster/faults.py
                     hook
- ``wire-retry``     every TransportError catch reaches a
                     retry/spool/shed path, never a bare swallow
- ``wire-envflag``   all BYDB_* reads through utils/envflag + the FLAGS
                     registry + docs/flags.md, cross-checked both ways
- ``wire-obs``       instrument names/label sets vs OBS_CONTRACT and
                     docs/observability.md

Findings reuse bdlint's Finding/suppression machinery (``# bdlint:
disable=wire-<x> -- reason``); the accepted/exempt tables in
wire_config.py are the family's ratchets — every entry carries its
reviewed reason and stale entries fail, so the tables only shrink.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Optional

from banyandb_tpu.lint.core import Finding

WIRE_RULES = (
    ("wire-topic", "bus topic invoked against a role with no handler"),
    ("wire-kind", "wire-kind taxonomy drift or non-exhaustive classifier"),
    ("wire-envelope", "envelope field write-only or read with silent default"),
    ("wire-fault", "fabric boundary unreachable by the cluster/faults plane"),
    ("wire-retry", "retryable rejection caught without a recovery path"),
    ("wire-envflag", "BYDB_* flag outside envflag/FLAGS/docs registry"),
    ("wire-obs", "instrument outside the obs contract or label-set drift"),
)


def run_wire(
    program,
    trees: dict,
    pkg_root: Optional[Path] = None,
) -> tuple[list[Finding], dict]:
    """Run the bdwire family -> (findings, stats).

    The checked-in wire_config tables name banyandb_tpu quals; on a
    foreign package (the seeded trees the whole-program meta-tests
    build) none of them resolve, so the family is skipped outright —
    seeded wire tests drive the analyzers directly with injected
    config.
    """
    from banyandb_tpu.lint.wire import wire_config as _cfg

    is_home = any(
        m == _cfg.PKG or m.startswith(_cfg.PKG + ".") for m in trees
    )
    if not is_home:
        return [], {"wire_topics": 0, "wire_kind_sites": 0}

    from banyandb_tpu.lint.wire.envelopes import analyze_envelopes
    from banyandb_tpu.lint.wire.envregistry import analyze_envflags
    from banyandb_tpu.lint.wire.fault_sites import analyze_fault_sites
    from banyandb_tpu.lint.wire.kinds import analyze_kinds, collect_kind_sites
    from banyandb_tpu.lint.wire.obs_contract import analyze_obs
    from banyandb_tpu.lint.wire.retryable import analyze_retryable
    from banyandb_tpu.lint.wire.topics import analyze_topics, role_topic_matrix

    cfg_path = str(Path(__file__).parent / "wire_config.py")
    repo_root = Path(pkg_root).parent if pkg_root is not None else None

    findings: list[Finding] = []
    findings += analyze_topics(program, trees, baseline_path=cfg_path)
    findings += analyze_kinds(program, baseline_path=cfg_path)
    findings += analyze_envelopes(program, baseline_path=cfg_path)
    findings += analyze_fault_sites(program, baseline_path=cfg_path)
    findings += analyze_retryable(program, baseline_path=cfg_path)
    findings += analyze_envflags(trees, repo_root)
    findings += analyze_obs(trees, repo_root)
    # callgraph paths arrive as Path objects; Finding sorts path-first,
    # so normalize to str before the engine merges families
    findings = [
        dataclasses.replace(f, path=str(f.path)) for f in findings
    ]

    matrix = role_topic_matrix(program, trees)
    topics: set[str] = set()
    for served in matrix.values():
        topics.update(served)
    stats = {
        "wire_topics": len(topics),
        "wire_kind_sites": len(
            collect_kind_sites(program, error_classes=_cfg.ERROR_CLASSES)
        ),
    }
    return findings, stats
