"""Import-graph layering analyzer.

Scans every module in the package for *module-level* imports (function-
local and ``if TYPE_CHECKING:`` imports are the documented lazy-boundary
escape hatch and do not create layer edges), maps both endpoints through
the checked-in layer config, and reports every edge the policy forbids:

- **upward**: importing a strictly higher layer;
- **skip-layer**: importing a lower layer the importer's ``may_import``
  set does not include (the config states, per layer, exactly which
  lower layers it may reach);
- **unknown module**: a module the config cannot place — the map must
  stay total, so growth forces a policy decision.

Pre-existing violations ride a **ratcheted baseline**
(layer_config.BASELINE): a baselined edge that still exists is tolerated
(and counted), a new edge fails, and a baselined edge that no longer
exists fails too ("stale baseline entry") so the list only ever shrinks.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Optional

from banyandb_tpu.lint.core import Finding, apply_ratchet

RULE = "layering"


@dataclass(frozen=True)
class LayerConfig:
    """The checked-in layer policy.

    - ``layers``: bottom-up layer names (index = height).
    - ``may_import``: layer -> lower layers it may import (same layer is
      always allowed; anything else is upward or skip-layer).
    - ``layer_of``: package-relative dotted module prefix -> layer,
      longest prefix wins ("" may map the package root).  A module no
      prefix covers is an unknown-module failure.
    """

    layers: tuple[str, ...]
    may_import: dict[str, tuple[str, ...]]
    layer_of: dict[str, str]

    def module_layer(self, rel_mod: str) -> Optional[str]:
        """Layer of a package-relative dotted module, longest prefix
        first.  The "" entry maps ONLY the package-root module itself —
        it is not a catch-all, so an unmapped module stays unknown (and
        fails the gate)."""
        if rel_mod == "":
            return self.layer_of.get("")
        probe = rel_mod
        while probe:
            layer = self.layer_of.get(probe)
            if layer is not None:
                return layer
            probe = probe.rpartition(".")[0]
        return None

    def allowed(self, src_layer: str, dst_layer: str) -> bool:
        if src_layer == dst_layer:
            return True
        return dst_layer in self.may_import.get(src_layer, ())


@dataclass(frozen=True)
class ImportEdge:
    src: str  # full dotted module
    dst: str  # full dotted module
    path: str
    line: int
    col: int


def iter_py_modules(pkg_root: Path, pkgname: str) -> Iterable[tuple[str, Path]]:
    """(full dotted module name, file path) for every .py in the package."""
    for p in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        rel = p.relative_to(pkg_root).with_suffix("")
        parts = list(rel.parts)
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        yield ".".join([pkgname, *parts]).rstrip("."), p


def parse_package(
    pkg_root: Path, pkgname: str
) -> dict[str, tuple[Path, "ast.Module"]]:
    """module -> (path, parsed tree) for the whole package, skipping
    files that do not parse (the per-file linter reports those).  Parsed
    ONCE here and shared by every whole-program analyzer."""
    trees: dict[str, tuple[Path, ast.Module]] = {}
    for mod, path in iter_py_modules(pkg_root, pkgname):
        try:
            trees[mod] = (path, ast.parse(path.read_text(encoding="utf-8")))
        except SyntaxError:
            continue
    return trees


def resolve_relative_base(mod: str, node: ast.ImportFrom, is_pkg: bool) -> str:
    """Dotted base module an ImportFrom refers to, resolving relative
    levels against the importing module.  Shared by the layering scan
    and the call-graph import tables so both resolve identically."""
    if node.level == 0:
        return node.module or ""
    anchor = mod.split(".")
    up = node.level - (1 if is_pkg else 0)
    anchor = anchor[: len(anchor) - up] if up else anchor
    return ".".join(anchor + ([node.module] if node.module else []))


def _is_type_checking_test(test: ast.AST) -> bool:
    return (
        isinstance(test, ast.Name)
        and test.id == "TYPE_CHECKING"
        or isinstance(test, ast.Attribute)
        and test.attr == "TYPE_CHECKING"
    )


def _module_level_imports(tree: ast.Module):
    """Yield Import/ImportFrom nodes executed at module import time.

    Descends into module-level ``if``/``try`` (conditional imports run at
    import time) but not into functions, classes with methods only
    executing later... class bodies DO execute at import time, so they
    are included; ``if TYPE_CHECKING:`` arms are excluded.
    """
    stack: list[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, ast.If):
            if not _is_type_checking_test(node.test):
                stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, (ast.Try, ast.ClassDef, ast.With)):
            for field in ("body", "handlers", "orelse", "finalbody", "items"):
                for child in getattr(node, field, []):
                    if isinstance(child, ast.ExceptHandler):
                        stack.extend(child.body)
                    elif isinstance(child, ast.AST):
                        stack.append(child)


def scan_import_edges(
    pkg_root: Path,
    pkgname: str,
    trees: Optional[dict] = None,
) -> tuple[list[ImportEdge], set[str]]:
    """-> (package-internal module-level import edges, all module names).
    Pass pre-parsed ``trees`` (parse_package) to avoid re-reading."""
    if trees is None:
        trees = parse_package(pkg_root, pkgname)
    names = set(trees)
    edges: list[ImportEdge] = []

    def resolve_from(mod: str, node: ast.ImportFrom, is_pkg: bool) -> list[str]:
        base = resolve_relative_base(mod, node, is_pkg)
        if not (base == pkgname or base.startswith(pkgname + ".")):
            return []
        out = []
        for alias in node.names:
            sub = f"{base}.{alias.name}"
            out.append(sub if sub in names else base)
        return out

    for mod, (path, tree) in trees.items():
        is_pkg = path.name == "__init__.py"
        for node in _module_level_imports(tree):
            targets: list[str] = []
            if isinstance(node, ast.Import):
                targets = [
                    a.name
                    for a in node.names
                    if a.name == pkgname or a.name.startswith(pkgname + ".")
                ]
            else:
                targets = resolve_from(mod, node, is_pkg)
            for t in targets:
                if t != mod:
                    edges.append(
                        ImportEdge(mod, t, str(path), node.lineno, node.col_offset)
                    )
    return edges, names


def _rel(mod: str, pkgname: str) -> str:
    return mod[len(pkgname) + 1 :] if mod != pkgname else ""


def analyze_layers(
    pkg_root: Path,
    pkgname: str,
    config: LayerConfig,
    baseline: frozenset = frozenset(),
    trees: Optional[dict] = None,
) -> list[Finding]:
    """Report forbidden import edges, unknown modules and stale baseline
    entries.  Baselined live violations are tolerated (the ratchet)."""
    if trees is None:
        trees = parse_package(pkg_root, pkgname)
    edges, names = scan_import_edges(pkg_root, pkgname, trees)
    module_paths = {mod: path for mod, (path, _tree) in trees.items()}
    findings: list[Finding] = []
    violations: list[tuple[str, Finding]] = []
    height = {layer: i for i, layer in enumerate(config.layers)}

    for mod in sorted(names):
        if config.module_layer(_rel(mod, pkgname)) is None:
            findings.append(
                Finding(
                    path=str(module_paths[mod]),
                    line=1,
                    col=0,
                    rule=RULE,
                    message=(
                        f"module `{mod}` maps to no layer; add it to "
                        "lint/whole_program/layer_config.py (the map is total)"
                    ),
                )
            )

    for e in edges:
        src_layer = config.module_layer(_rel(e.src, pkgname))
        dst_layer = config.module_layer(_rel(e.dst, pkgname))
        if src_layer is None or dst_layer is None:
            continue  # unknown modules already reported above
        if config.allowed(src_layer, dst_layer):
            continue
        kind = (
            "upward"
            if height[dst_layer] > height[src_layer]
            else "skip-layer"
        )
        violations.append(
            (
                f"{e.src} -> {e.dst}",
                Finding(
                    path=e.path,
                    line=e.line,
                    col=e.col,
                    rule=RULE,
                    message=(
                        f"{kind} import: `{e.src}` ({src_layer}) must not "
                        f"import `{e.dst}` ({dst_layer}); invert the "
                        "dependency, move the shared piece down a layer, or "
                        "use a function-local lazy import at the boundary"
                    ),
                ),
            )
        )

    findings += apply_ratchet(
        violations,
        baseline,
        rule=RULE,
        baseline_path=str(
            pkg_root / "lint" / "whole_program" / "layer_config.py"
        ),
    )
    return findings
