"""wp-shared-state: whole-program cross-thread attribute race analysis.

The reference gates every merge on ``go test -race``; this is the static
half of the Python analog (the runtime half is ``banyandb_tpu/sanitize``).
Four passes over the callgraph.Program:

1. **Root discovery** — every function that can run on a thread of its
   own: ``threading.Thread(target=...)`` / ``threading.Timer`` targets,
   ``pool.submit`` callables, ``bus.subscribe`` handlers (the gRPC bus
   server dispatches every topic handler on executor threads),
   ``ThreadingHTTPServer`` handler-class ``do_*`` methods, and — by the
   documented class-name convention — public methods of ``*Services`` /
   ``*Servicer`` classes (the wire-plane gRPC surface, which the server
   binds through generic handler tables the resolver cannot follow).
2. **Access collection** — per-class attribute reads/writes with
   *declaration-based* identity (``module.Class.attr``, the same scheme
   lockorder.py uses for locks).  Writes include direct rebinding,
   ``self.x[k] = v`` container stores, augmented assignment, ``del`` and
   known mutator calls (``self.x.append(...)``).  ``__init__`` bodies are
   exempt (Thread.start() publishes constructor writes with a
   happens-before edge), as are attributes declared as thread-safe
   primitives (Event/Condition/Semaphore/Queue/local) and the locks
   themselves.
3. **Must-hold lockset propagation** — per root, the set of locks
   *always* held when control reaches each function: intersection over
   call paths, seeded by lexical ``with <lock>:`` scoping at every call
   site (RLocks guard exactly like Locks; reentrancy only matters to the
   self-deadlock rule).
4. **Race report** — an attribute written from >= 2 distinct roots whose
   write-site guard sets share no common lock is one finding, anchored
   at the first write, with a witness chain per root.  Pre-existing
   accepted states ride a ratcheted ``BASELINE`` (same contract as
   layering: a fixed entry must be deleted, a new race fails).

Resolution is conservative (unresolvable calls create no reachability),
so a clean report means "no race among the facts the resolver can see" —
the runtime sanitizer covers the dynamic remainder.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable, Optional

from banyandb_tpu.lint.core import Finding, apply_ratchet
from banyandb_tpu.lint.whole_program.callgraph import (
    FuncInfo,
    Program,
    lock_identity,
)

RULE = "wp-shared-state"

# The ratchet.  Keys are attribute identities ("module.Class.attr").
# Empty by policy: new cross-thread state must ship guarded (or carry a
# reasoned per-line suppression at the write site).  A stale entry —
# one whose race no longer exists — fails the gate so the set only
# shrinks.
BASELINE: frozenset[str] = frozenset()

# Constructors whose instances are internally synchronized: attribute
# reads/mutations through them are not data races.
_SYNC_CTORS = {
    "threading.Event",
    "Event",
    "threading.Condition",
    "Condition",
    "threading.Semaphore",
    "Semaphore",
    "threading.BoundedSemaphore",
    "BoundedSemaphore",
    "threading.Barrier",
    "Barrier",
    "threading.local",
    "local",
    "queue.Queue",
    "Queue",
    "queue.SimpleQueue",
    "SimpleQueue",
    "queue.LifoQueue",
    "LifoQueue",
    "queue.PriorityQueue",
    "PriorityQueue",
    # deque.append/popleft are documented GIL-atomic: the single-producer
    # queue idioms built on it (schema watcher events) are not races
    "collections.deque",
    "deque",
}

# Mutating container methods: `self.x.append(v)` writes x's value even
# though the attribute binding itself is only read.
_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "clear",
    "discard",
    "extend",
    "insert",
    "pop",
    "popitem",
    "popleft",
    "remove",
    "setdefault",
    "update",
}

_SERVICER_SUFFIXES = ("Services", "Servicer")
_HTTP_HANDLER_BASES = ("BaseHTTPRequestHandler",)

# Worker-entry functions: each runs as the MAIN thread of a spawned
# subprocess (``python -m banyandb_tpu.cluster.workers``).  The static
# Thread/Timer/subscribe discovery cannot see across an exec boundary,
# so process entries are declared here — everything one reaches is a
# concurrent root population exactly like a Thread target (the worker's
# serve loop then spawns its own writer/executor threads, which the
# ordinary registration discovery picks up inside the entry's closure).
_PROCESS_ENTRY_QUALS = (
    "banyandb_tpu.cluster.workers:worker_main",
    "banyandb_tpu.cluster.workers:_WorkerServer.serve",
)


@dataclass(frozen=True)
class Root:
    qual: str
    kind: str  # thread|timer|executor|subscriber|http|grpc
    label: str


@dataclass(frozen=True)
class Access:
    attr: str  # "module.Class.attr" declaration-based identity
    qual: str  # function containing the access
    path: str
    line: int
    col: int
    write: bool
    locks: frozenset  # lexically-held lock ids at the access site


def discover_roots(program: Program) -> list[Root]:
    """Every thread entry point the resolver can see, one Root per
    distinct target function (first registration's label wins)."""
    roots: dict[str, Root] = {}

    def put(qual: str, kind: str, label: str) -> None:
        roots.setdefault(qual, Root(qual=qual, kind=kind, label=label))

    for info in program.functions.values():
        for r in info.registrations:
            short = r.target.split(":", 1)[1]
            label = f'{r.kind} "{r.name}"' if r.name else f"{r.kind} {short}"
            put(r.target, r.kind, label)
    for qual in _PROCESS_ENTRY_QUALS:
        if qual in program.functions:
            short = qual.split(":", 1)[1]
            put(qual, "process", f"process {short}")
    for mod, cls_name, methods in program.iter_classes():
        if cls_name.endswith(_SERVICER_SUFFIXES):
            for meth, qual in sorted(methods.items()):
                if not meth.startswith("_"):
                    put(qual, "grpc", f"grpc {cls_name}.{meth}")
        elif any(
            b.split(".")[-1] in _HTTP_HANDLER_BASES
            for b in program.class_bases(mod, cls_name)
        ):
            for meth, qual in sorted(methods.items()):
                if meth.startswith("do_"):
                    put(qual, "http", f"http {cls_name}.{meth}")
    return sorted(roots.values(), key=lambda r: r.qual)


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


def collect_accesses(program: Program) -> list[Access]:
    """Per-class attribute accesses with lexical lock context, whole
    package.  ``__init__`` bodies, lock attributes and synchronized
    primitives are exempt (see module docstring)."""
    out: list[Access] = []
    for info in program.functions.values():
        if info.cls is None:
            continue
        if info.qual.split(":", 1)[1].split(".")[-1] == "__init__":
            continue
        imports = program.tables.get(info.module, {})
        _scan_function(program, info, imports, out)
    return out


def _scan_function(
    program: Program,
    info: FuncInfo,
    imports: dict,
    out: list[Access],
) -> None:
    mod, cls = info.module, info.cls

    def exempt(attr: str) -> bool:
        if "lock" in attr.lower():
            return True  # the guards themselves
        ctor = program.attr_ctor_on(mod, cls, attr)
        return ctor in _SYNC_CTORS

    def emit(node: ast.AST, attr: str, write: bool, locks: frozenset) -> None:
        if exempt(attr):
            return
        out.append(
            Access(
                attr=f"{mod}.{cls}.{attr}",
                qual=info.qual,
                path=info.path,
                line=node.lineno,
                col=node.col_offset,
                write=write,
                locks=locks,
            )
        )

    def visit(node: ast.AST, locks: frozenset, parent: Optional[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested defs are their own FuncInfo
        attr = _is_self_attr(node)
        if attr is not None:
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, (ast.Store, ast.Del)):
                emit(node, attr, True, locks)
            else:
                write = False
                if isinstance(parent, ast.Subscript) and isinstance(
                    getattr(parent, "ctx", None), (ast.Store, ast.Del)
                ):
                    # self.x[k] = v / del self.x[k]; an AugAssign target
                    # subscript also carries Store ctx, so += is covered
                    write = True
                elif (
                    isinstance(parent, ast.Attribute)
                    and parent.attr in _MUTATORS
                    and isinstance(
                        getattr(parent, "parent_call", None), ast.Call
                    )
                ):
                    write = True  # self.x.append(v)
                emit(node, attr, write, locks)
        inner = locks
        if isinstance(node, (ast.With, ast.AsyncWith)):
            ids = set()
            for item in node.items:
                lid = lock_identity(item.context_expr, mod, cls, imports)
                if lid is not None:
                    ids.add(lid)
            inner = locks | frozenset(ids)
        for child in ast.iter_child_nodes(node):
            # annotate the parent shape the mutator classifier needs
            if isinstance(node, ast.Call) and child is node.func:
                child.parent_call = node  # type: ignore[attr-defined]
            visit(child, inner, node)

    for child in ast.iter_child_nodes(info.node):
        visit(child, frozenset(), info.node)


def _lexical_call_locks(info: FuncInfo) -> dict[int, frozenset]:
    """id(call ast node) -> lock ids lexically held around that call."""
    held: dict[int, set] = {}
    for region in info.lock_regions:
        for site in region.calls:
            held.setdefault(id(site.node), set()).add(region.lock_id)
    return {k: frozenset(v) for k, v in held.items()}


def must_hold(
    program: Program, root_qual: str
) -> tuple[dict[str, frozenset], dict[str, Optional[tuple[str, int]]]]:
    """-> (entry locksets, witness parents) for everything reachable from
    ``root_qual``.  entry[q] is the intersection over all discovered call
    paths of the locks held when q is entered; parents[q] names the
    first-discovered caller for witness chains."""
    entry: dict[str, frozenset] = {root_qual: frozenset()}
    parents: dict[str, Optional[tuple[str, int]]] = {root_qual: None}
    work = [root_qual]
    while work:
        q = work.pop()
        info = program.functions.get(q)
        if info is None:
            continue
        if q.split(".")[-1] == "__init__" and q != root_qual:
            # construction is pre-publication: whatever a constructor
            # (and its helpers) writes becomes visible to other threads
            # only through the publishing store that follows, so call
            # edges out of __init__ carry no shared-state reachability
            continue
        base = entry[q]
        lex = _lexical_call_locks(info)
        for site in info.calls:
            callee = site.callee
            if not callee or callee not in program.functions:
                continue
            cand = base | lex.get(id(site.node), frozenset())
            cur = entry.get(callee)
            if cur is None:
                entry[callee] = cand
                parents[callee] = (q, site.line)
                work.append(callee)
            else:
                inter = cur & cand
                if inter != cur:
                    entry[callee] = inter
                    work.append(callee)
    return entry, parents


def _witness(
    parents: dict[str, Optional[tuple[str, int]]], qual: str
) -> str:
    """root -> ... -> qual as short function names."""
    chain = []
    cur: Optional[str] = qual
    seen = set()
    while cur is not None and cur not in seen:
        seen.add(cur)
        chain.append(cur.split(":", 1)[1])
        nxt = parents.get(cur)
        cur = nxt[0] if nxt else None
    return " -> ".join(reversed(chain))


def analyze_shared_state(
    program: Program,
    baseline: frozenset = BASELINE,
    baseline_path: str = "<shared-state-baseline>",
    roots: Optional[list[Root]] = None,
) -> list[Finding]:
    if roots is None:
        roots = discover_roots(program)
    accesses = collect_accesses(program)
    by_fn: dict[str, list[Access]] = {}
    for a in accesses:
        by_fn.setdefault(a.qual, []).append(a)

    # attr -> {root qual -> (witness, [guard sets of write accesses],
    #          first write access)}
    writes: dict[str, dict[str, tuple[str, list, Access]]] = {}
    labels = {r.qual: r.label for r in roots}
    for root in roots:
        entry, parents = must_hold(program, root.qual)
        for qual, held in entry.items():
            for a in by_fn.get(qual, ()):
                if not a.write:
                    continue
                guards = held | a.locks
                rec = writes.setdefault(a.attr, {})
                if root.qual in rec:
                    w, gs, first = rec[root.qual]
                    gs.append(guards)
                    if (a.path, a.line) < (first.path, first.line):
                        rec[root.qual] = (w, gs, a)
                else:
                    rec[root.qual] = (_witness(parents, qual), [guards], a)

    violations: list[tuple[str, Finding]] = []
    for attr in sorted(writes):
        rec = writes[attr]
        if len(rec) < 2:
            continue
        common: Optional[frozenset] = None
        for _w, guard_sets, _a in rec.values():
            for g in guard_sets:
                common = g if common is None else (common & g)
        if common:
            continue
        anchor = min(
            (a for _w, _g, a in rec.values()), key=lambda a: (a.path, a.line)
        )
        chains = "; ".join(
            f"[{labels[rq]}] {w}"
            for rq, (w, _g, _a) in sorted(rec.items())[:3]
        )
        more = len(rec) - min(len(rec), 3)
        violations.append(
            (
                attr,
                Finding(
                    path=anchor.path,
                    line=anchor.line,
                    col=anchor.col,
                    rule=RULE,
                    message=(
                        f"`{attr}` is written from {len(rec)} thread roots "
                        f"with no common lock guard: {chains}"
                        + (f" (+{more} more roots)" if more else "")
                        + "; guard the writes with one shared lock, or "
                        "document the invariant and suppress at the write"
                    ),
                ),
            )
        )
    return apply_ratchet(
        violations,
        baseline,
        rule=RULE,
        baseline_path=baseline_path,
        what="the shared-state race",
    )


def iter_root_labels(program: Program) -> Iterable[str]:
    """Debug/docs helper: the discovered root population."""
    for r in discover_roots(program):
        yield f"{r.kind:10s} {r.qual}"
