"""The checked-in layer policy for banyandb_tpu (SURVEY.md §1, L0-L6).

This file IS the architecture decision record the layering analyzer
enforces.  Three tables:

- ``CONFIG.layers`` / ``CONFIG.may_import``: the bottom-up layer order
  and, per layer, exactly which lower layers it may import.  Anything
  else is an upward or skip-layer violation.
- ``CONFIG.layer_of``: dotted module-prefix -> layer, longest prefix
  wins.  The map is TOTAL: a module no prefix covers fails the gate
  (tests/test_whole_program.py pins this as a golden test), so adding a
  top-level module forces a layering decision in this file.
- ``BASELINE``: the ratchet.  Pre-existing violations, tolerated until
  fixed; new violations fail immediately, and entries whose violation
  disappeared fail as stale so the list only shrinks.

Mapping notes (where the TPU build deviates from a naive directory map):

- ``api/model.py`` + ``api/schema.py`` are the shared data-model and
  schema-registry *types* (SURVEY's api/proto model plane) — imported by
  storage, engines and query alike, so they live in L1-substrate, not in
  the L5 entry surface.  Generated ``api/pb`` protos are leaf data: L1.
- ``ops/blocks.py`` is the padded columnar block model the storage
  substrate builds on (the 8192-row part-block analog); it sits in
  L1-substrate while the rest of ops/ (kernels) is L3-exec.
- ``server.py``/``cluster_server.py``/``mcp_server.py``/``run.py``/
  ``cli.py`` are role *composition*: they wire admin units, engines and
  the API surface into one process (pkg/cmdsetup analog) and therefore
  live in the top L6-adminops layer with admin/ and lint/.
- Function-local and ``if TYPE_CHECKING:`` imports are deliberate lazy
  boundaries and create no edges (e.g. cluster/data_node reaching up to
  admin diagnostics at runtime, cluster/schema_plane reaching the
  grpc_server barrier kinds).
"""

from __future__ import annotations

from banyandb_tpu.lint.whole_program.layers import LayerConfig

PACKAGE = "banyandb_tpu"

L0 = "L0-platform"
L1 = "L1-substrate"
L2 = "L2-engines"
L3 = "L3-exec"
L4 = "L4-fabric"
L5 = "L5-api"
L6 = "L6-adminops"

CONFIG = LayerConfig(
    layers=(L0, L1, L2, L3, L4, L5, L6),
    # Per-layer import policy (SURVEY §1 "Below it" column).  Every layer
    # may reach L0 (platform) and L1 (substrate + model types); query
    # deliberately skips the engines layer (it consumes decoded
    # ColumnData, not engine objects), and the fabric deliberately skips
    # nothing below it — it ships engine parts, runs device plans and
    # serializes model types.
    may_import={
        L0: (),
        L1: (L0,),
        L2: (L1, L0),
        L3: (L1, L0),  # exec consumes substrate directly, never engines
        L4: (L3, L2, L1, L0),
        L5: (L4, L3, L2, L1, L0),
        L6: (L5, L4, L3, L2, L1, L0),
    },
    layer_of={
        # L0 — platform utilities
        "": L0,  # package root __init__
        "utils": L0,
        "config": L0,
        # self-observability primitives (tracer/metrics/recorder/prom):
        # dependency-free by design so storage, engines, query and the
        # fabric can all instrument themselves without upward edges
        "obs": L0,
        # multi-tenant QoS plane: tenancy + admission primitives consulted
        # by storage (cache partitions), query (streamagg caps) and the
        # serving roles alike — platform, like obs (its ServerBusy shed
        # exception is reached lazily, so no upward edge to admin/)
        "qos": L0,
        # L1 — storage substrate + shared model/schema types
        "storage": L1,
        "index": L1,
        "api.model": L1,
        "api.schema": L1,
        "api.pb": L1,
        "ops.blocks": L1,
        # L2 — data-model engines
        "models": L2,
        # L3 — device execution (query plans, kernels, mesh)
        "query": L3,
        "ops": L3,
        "parallel": L3,
        "bydbql": L3,
        "flow": L3,
        # L4 — cluster fabric
        "cluster": L4,
        # L5 — API surface (wire codecs, gRPC/HTTP servers, auth)
        "api": L5,
        # L6 — admin/ops + process composition + tooling
        "admin": L6,
        "server": L6,
        "cluster_server": L6,
        "mcp_server": L6,
        "run": L6,
        "cli": L6,
        "lint": L6,
        # bdsan runtime sanitizers: tooling like lint/ (its static lock
        # model loads lint.whole_program lazily — no import-time edge)
        "sanitize": L6,
    },
)

# The ratchet: every entry is a pre-existing, known upward edge.  Do not
# add entries for new code — fix the layering instead.  Removing the
# violation requires removing the entry (a lingering entry fails as
# stale).
#
# models -> query: the engines call the device executors directly
# (engine.query() builds ColumnData then runs the plan).  The clean shape
# is an executor interface the engines depend on downward — tracked as a
# refactor, not re-baselined.
BASELINE = frozenset(
    {
        "banyandb_tpu.models.measure -> banyandb_tpu.query.filter",
        "banyandb_tpu.models.measure -> banyandb_tpu.query.measure_exec",
        "banyandb_tpu.models.stream -> banyandb_tpu.query.filter",
        "banyandb_tpu.models.stream -> banyandb_tpu.query.measure_exec",
        "banyandb_tpu.models.trace -> banyandb_tpu.query.measure_exec",
    }
)
