"""Whole-program analyses: the cross-module invariants bdlint's per-file
rules cannot see.

Four analyzers, all surfaced through ``python -m banyandb_tpu.lint``
(``--check`` runs them; ``--whole-program`` runs them report-only):

- ``layering``        import-graph enforcement of the SURVEY.md §1
                      L0-L6 layer map (layer_config.py is the checked-in
                      policy; pre-existing violations ride a ratcheted
                      baseline that only shrinks)
- ``wp-sync-in-jit``  interprocedural "performs host sync / blocks"
                      facts: a function transitively reaching
                      jax.device_get or a blocking call from inside a
                      jit-traced region is flagged across files
- ``wp-lock-blocking``the cross-file extension of lock-across-rpc: a
                      call made while holding a lock whose CALLEE
                      (transitively) blocks
- ``lock-order``      potential deadlock cycles in the
                      acquires-while-holding lock graph
- ``wp-shared-state`` cross-thread race analysis: attributes written
                      from >= 2 discovered thread roots (Thread targets,
                      bus subscribers, gRPC servicer methods, HTTP
                      handlers, executor submissions) with no common
                      lock guard (shared_state.py; the static half of
                      bdsan — banyandb_tpu/sanitize is the runtime half)
- ``plan-audit``      jax.eval_shape abstract trace of every registered
                      measure/stream kernel entry point against a matrix
                      of representative plan shapes: dtype promotion,
                      shape mismatch and retrace hazards, zero device
                      execution
- ``wire-*``          the bdwire wire-contract family (lint/wire):
                      role/topic exhaustiveness, wire-kind taxonomy,
                      envelope producer/consumer matching, fault-site
                      coverage, retryable handling, BYDB_* flag registry
                      and the obs contract (wire_config.py is the
                      checked-in policy)
- ``kernel-*``        the bdjit kernel audit family (lint/kernel):
                      jaxpr walk, stub-device dispatch/transfer counts,
                      CPU lowering facts, and the ratcheted
                      per-signature budget table (kernel_budgets.py)

Findings reuse bdlint's Finding/suppression machinery: a whole-program
finding anchors at a real source line and honors the same
``# bdlint: disable=<rule> -- reason`` comments.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from banyandb_tpu.lint.core import Finding, parse_suppressions

# (name, summary) catalog for --list-rules; checks live in the sibling
# modules, not in per-file rule objects.  The kernel-audit family
# (lint/kernel, "bdjit") rides the same surface.
from banyandb_tpu.lint.kernel import KERNEL_RULES
from banyandb_tpu.lint.wire import WIRE_RULES

WP_RULES = (
    ("layering", "import respects the SURVEY L0-L6 layer map"),
    ("wp-sync-in-jit", "transitive host sync/block inside a jit region"),
    ("wp-lock-blocking", "callee transitively blocks while a lock is held"),
    ("lock-order", "potential deadlock cycle in the lock-order graph"),
    ("wp-shared-state", "attribute written from >=2 thread roots unguarded"),
    ("plan-audit", "eval_shape plan matrix: dtype/shape/retrace hazards"),
) + WIRE_RULES + KERNEL_RULES


def apply_suppressions(
    findings: list[Finding],
) -> tuple[list[Finding], int]:
    """Filter whole-program findings through per-file bdlint suppressions.

    -> (kept findings, suppressed count).  Files are read lazily and only
    when they actually carry findings.
    """
    kept: list[Finding] = []
    suppressed = 0
    cache: dict[str, tuple[dict, frozenset]] = {}
    for f in findings:
        maps = cache.get(f.path)
        if maps is None:
            try:
                lines = Path(f.path).read_text(encoding="utf-8").splitlines()
                maps = parse_suppressions(lines)
            except OSError:
                maps = ({}, frozenset())
            cache[f.path] = maps
        per_line, file_wide = maps
        sup = per_line.get(f.line, frozenset()) | file_wide
        if f.rule in sup or "all" in sup:
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


# --only analyzer families -> the rules each family emits
FAMILIES = {
    "layering": ("layering",),
    "sync": ("wp-sync-in-jit", "wp-lock-blocking"),
    "lock-order": ("lock-order",),
    "shared-state": ("wp-shared-state",),
    "plan-audit": ("plan-audit",),
    "wire": tuple(name for name, _ in WIRE_RULES),
    "kernel": (
        "kernel-jaxpr",
        "kernel-dispatch",
        "kernel-lowering",
        "kernel-budget",
    ),
}


def family_of_rule(rule: str) -> Optional[str]:
    for fam, rules in FAMILIES.items():
        if rule in rules:
            return fam
    return None


def run_whole_program(
    pkg_root: Path,
    plan_audit: bool = True,
    only: Optional[set] = None,
    fast: bool = False,
) -> tuple[list[Finding], dict]:
    """Run the whole-program analyzers against the banyandb_tpu package
    rooted at ``pkg_root`` -> (findings after suppressions, stats).

    ``only`` (family names from FAMILIES) restricts which analyzers run
    — the CLI's ``--only`` so local iteration pays only the pass under
    edit; None = everything.  ``plan_audit=False`` opts out of BOTH
    jax-backed families (plan audit and the kernel audit) — the legacy
    "AST analyses only" switch the meta-tests use.  ``fast`` skips the
    kernel lowering-audit (the XLA-compile half of the kernel family).
    """
    from banyandb_tpu.lint.whole_program import layer_config
    from banyandb_tpu.lint.whole_program.layers import parse_package

    def want(fam: str) -> bool:
        return only is None or fam in only

    findings: list[Finding] = []
    stats = {"wp_functions": 0, "wp_roots": 0}
    need_program = any(
        want(f) for f in ("sync", "lock-order", "shared-state", "wire")
    )
    trees = (
        parse_package(pkg_root, layer_config.PACKAGE)
        if need_program or want("layering")
        else None
    )

    if want("layering"):
        from banyandb_tpu.lint.whole_program.layers import analyze_layers

        findings += analyze_layers(
            pkg_root,
            layer_config.PACKAGE,
            layer_config.CONFIG,
            baseline=layer_config.BASELINE,
            trees=trees,
        )
    if need_program:
        from banyandb_tpu.lint.whole_program.callgraph import (
            Program,
            analyze_lock_blocking,
            analyze_sync_in_jit,
        )

        program = Program.build(pkg_root, layer_config.PACKAGE, trees=trees)
        stats["wp_functions"] = len(program.functions)
        if want("sync"):
            findings += analyze_sync_in_jit(program)
            findings += analyze_lock_blocking(program)
        if want("lock-order"):
            from banyandb_tpu.lint.whole_program.lockorder import (
                analyze_lock_order,
            )

            findings += analyze_lock_order(program)
        if want("shared-state"):
            from banyandb_tpu.lint.whole_program.shared_state import (
                BASELINE as SHARED_STATE_BASELINE,
            )
            from banyandb_tpu.lint.whole_program.shared_state import (
                analyze_shared_state,
                discover_roots,
            )

            roots = discover_roots(program)
            stats["wp_roots"] = len(roots)
            findings += analyze_shared_state(
                program,
                baseline=SHARED_STATE_BASELINE,
                baseline_path=str(
                    pkg_root / "lint" / "whole_program" / "shared_state.py"
                ),
                roots=roots,
            )
        if want("wire"):
            from banyandb_tpu.lint.wire import run_wire

            wire_findings, wire_stats = run_wire(program, trees, pkg_root)
            findings += wire_findings
            stats.update(wire_stats)
    if plan_audit and want("plan-audit"):
        from banyandb_tpu.lint.whole_program.plan_audit import run_plan_audit

        findings += run_plan_audit()
    if plan_audit and want("kernel"):
        from banyandb_tpu.lint.kernel import kernel_stats, run_kernel_audit

        findings += run_kernel_audit(fast=fast)
        stats.update(kernel_stats(fast=fast))
    findings, suppressed = apply_suppressions(findings)
    findings.sort()
    stats.update(
        {"wp_findings": len(findings), "wp_suppressed": suppressed}
    )
    return findings, stats
