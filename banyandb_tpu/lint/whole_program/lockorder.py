"""Lock-order cycle detector over the acquires-while-holding graph.

Nodes are declaration-based lock identities (``module.Class.attr`` —
see callgraph.lock_identity).  An edge ``A -> B`` means: somewhere the
program acquires ``B`` (directly via a nested ``with``, or transitively
through a call chain) while ``A`` is held.  A cycle in this graph is a
*potential* deadlock: two threads taking the locks in opposite orders
can each end up waiting on the other.

"Potential" is load-bearing: identities are per declaration site, not
per instance, so ``node_a.lock -> node_b.lock`` between two instances of
the same class shows up as a self-edge.  Such self-edges are still worth
a look (cross-instance calls under a held lock are how fabric fan-outs
deadlock), but a verified-safe one is suppressed at the acquisition site
with a reason, like any bdlint finding.

Scope: the graph is built package-wide (edges through helper layers
count), and every cycle is reported — the fabric (cluster/, api/) is
where the multi-lock topology actually lives, per SURVEY §1.
"""

from __future__ import annotations

from dataclasses import dataclass

from banyandb_tpu.lint.core import Finding
from banyandb_tpu.lint.whole_program.callgraph import Program

RULE = "lock-order"


@dataclass(frozen=True)
class LockEdge:
    held: str
    acquired: str
    path: str
    line: int
    col: int
    via: str  # "" for a direct nested with, else the callee qualname


def build_lock_graph(program: Program) -> list[LockEdge]:
    """Every held->acquired pair, with the source location that creates
    it (the nested ``with`` or the call that transitively acquires)."""
    acq = program.lock_acquires()
    edges: list[LockEdge] = []
    for info in program.functions.values():
        for region in info.lock_regions:
            for lid, node in region.inner_locks:
                edges.append(
                    LockEdge(
                        held=region.lock_id,
                        acquired=lid,
                        path=info.path,
                        line=node.lineno,
                        col=node.col_offset,
                        via="",
                    )
                )
            for site in region.calls:
                if not site.callee:
                    continue
                for lid in sorted(acq.get(site.callee, ())):
                    edges.append(
                        LockEdge(
                            held=region.lock_id,
                            acquired=lid,
                            path=info.path,
                            line=site.line,
                            col=site.col,
                            via=site.callee,
                        )
                    )
    return edges


def _cycles(adj: dict[str, set[str]]) -> list[tuple[str, ...]]:
    """Elementary cycles, canonicalized (rotation-minimal, deduped).
    Bounded DFS — lock graphs here are tiny (tens of nodes)."""
    out: set[tuple[str, ...]] = set()

    def canon(path: tuple[str, ...]) -> tuple[str, ...]:
        i = path.index(min(path))
        return path[i:] + path[:i]

    def dfs(start: str, node: str, path: tuple[str, ...]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                out.add(canon(path))
            elif nxt not in path and len(path) < 8:
                dfs(start, nxt, path + (nxt,))

    for n in sorted(adj):
        dfs(n, n, (n,))
    return sorted(out)


def analyze_lock_order(program: Program) -> list[Finding]:
    edges = build_lock_graph(program)
    adj: dict[str, set[str]] = {}
    for e in edges:
        if e.held != e.acquired:
            adj.setdefault(e.held, set()).add(e.acquired)
    findings: list[Finding] = []

    # self-edges: re-acquiring the same declaration while held — either a
    # genuine non-reentrant self-deadlock or a cross-instance hold.
    # Declarations assigned threading.RLock() are reentrant by design and
    # exempt (length>=2 cycles still report: lock ORDER across threads
    # matters regardless of reentrancy).
    self_edges = [
        e
        for e in edges
        if e.held == e.acquired and e.held not in program.reentrant_locks
    ]
    for e in self_edges:
        via = f" via `{e.via.split(':', 1)[1]}`" if e.via else ""
        findings.append(
            Finding(
                path=e.path,
                line=e.line,
                col=e.col,
                rule=RULE,
                message=(
                    f"`{e.acquired}` is acquired while already held{via}: "
                    "self-deadlock on a non-reentrant lock (or a "
                    "cross-instance hold chain — verify and suppress with "
                    "the reason)"
                ),
            )
        )

    by_pair: dict[tuple[str, str], LockEdge] = {}
    for e in edges:
        by_pair.setdefault((e.held, e.acquired), e)
    for cycle in _cycles(adj):
        hops = []
        for i, lock in enumerate(cycle):
            nxt = cycle[(i + 1) % len(cycle)]
            e = by_pair[(lock, nxt)]
            via = f" via {e.via.split(':', 1)[1]}" if e.via else ""
            hops.append(f"{lock} -> {nxt} (at {e.path}:{e.line}{via})")
        anchor = by_pair[(cycle[0], cycle[1 % len(cycle)])]
        findings.append(
            Finding(
                path=anchor.path,
                line=anchor.line,
                col=anchor.col,
                rule=RULE,
                message=(
                    "potential deadlock cycle: " + "; ".join(hops) + "; "
                    "pick one global acquisition order and restructure "
                    "the odd one out"
                ),
            )
        )
    return findings
