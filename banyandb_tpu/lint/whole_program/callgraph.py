"""Module-resolution call graph + interprocedural fact propagation.

Builds one symbol table over the whole package AST (functions, methods,
nested kernel builders, import aliases), resolves call sites to
fully-qualified functions, and propagates two facts to a fixed point:

- ``sync``:  the function (transitively) performs a device->host sync
  (``jax.device_get`` / ``.block_until_ready()``);
- ``block``: the function (transitively) blocks the thread (transport
  RPC, ``time.sleep``, ``urlopen``, ``socket.create_connection``,
  ``subprocess`` waits).

Two analyses consume the facts:

- ``analyze_sync_in_jit``: a call *inside a jit-traced function* whose
  callee transitively syncs or blocks is flagged — across files, which
  the per-file host-sync rule cannot see.  Direct (depth-0) calls to the
  sync APIs stay the per-file rule's business; this analysis only
  reports what an intra-file reading would miss.
- ``analyze_lock_blocking``: the cross-file half of lock-across-rpc — a
  call made while holding a lock whose callee transitively blocks.

Resolution is module-level and deliberately conservative: a call that
cannot be resolved to a package function creates no edge (no facts, no
false chains).  ``self.m()`` resolves through the enclosing class and
its in-package bases; aliased module and symbol imports (including
function-local lazy imports) resolve through one merged per-module
import table.

Lock identity is *declaration-based*: ``self._lock`` in class ``C`` of
module ``m`` is ``m.C._lock`` — one id per declaration site, not per
instance (see lockorder.py for the deadlock-graph consequences).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

from banyandb_tpu.lint.core import Finding, dotted_name
from banyandb_tpu.lint.rules_fabric import _attr_chain_ids, _is_transport_call
from banyandb_tpu.lint.rules_jax import _is_jax_jit
from banyandb_tpu.lint.whole_program.layers import (
    parse_package,
    resolve_relative_base,
)

_SYNC_APIS = {"jax.device_get"}
_SYNC_ATTRS = {"block_until_ready"}
_BLOCK_APIS = {
    "time.sleep",
    "_time.sleep",
    "urllib.request.urlopen",
    "request.urlopen",
    "urlopen",
    "socket.create_connection",
    "create_connection",
    "subprocess.run",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.call",
}


@dataclass
class CallSite:
    node: ast.Call
    callee: Optional[str]  # resolved qualname ("mod:Class.fn") or None
    line: int
    col: int


@dataclass(frozen=True)
class Registration:
    """A function handed to a concurrency primitive: the thread-root
    discovery surface for the shared-state analyzer.

    kinds: ``thread`` (threading.Thread target), ``timer``
    (threading.Timer function), ``executor`` (pool.submit fn),
    ``subscriber`` (bus.subscribe handler), ``http`` (a
    ThreadingHTTPServer handler-class ``do_*`` method — each request runs
    it on its own thread)."""

    kind: str
    target: str  # resolved qualname of the function that will run
    name: str  # thread name= constant when present, else ""
    line: int
    col: int


@dataclass
class LockRegion:
    lock_id: str
    node: ast.AST  # the With node
    calls: list[CallSite] = field(default_factory=list)
    inner_locks: list[tuple[str, ast.AST]] = field(default_factory=list)


@dataclass
class FuncInfo:
    qual: str  # "module:fn", "module:Class.fn", "module:fn.inner"
    module: str
    path: str
    node: ast.AST
    cls: Optional[str]
    calls: list[CallSite] = field(default_factory=list)
    lock_regions: list[LockRegion] = field(default_factory=list)
    registrations: list[Registration] = field(default_factory=list)
    direct_sync: Optional[str] = None
    direct_block: Optional[str] = None
    traced: bool = False
    # propagated facts: (base api, witness chain of quals) or None
    sync: Optional[tuple[str, tuple[str, ...]]] = None
    block: Optional[tuple[str, tuple[str, ...]]] = None


def _walk_own(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs (each
    nested def is its own FuncInfo)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def lock_identity(
    expr: ast.AST,
    module: str,
    cls: Optional[str],
    imports: Optional[dict[str, str]] = None,
) -> Optional[str]:
    """Declaration-based lock id for a with-context expression, or None
    when the expression is not lock-shaped (last segment contains
    'lock').  An imported head resolves through the module's import
    table, so ``other.GLOBAL_LOCK`` names the same declaration from
    every module that touches it."""
    if isinstance(expr, ast.Call):  # with self._lock_for(x): style
        expr = expr.func
    ids = _attr_chain_ids(expr)
    if not ids or "lock" not in ids[-1].lower():
        return None
    if ids[0] in ("self", "cls"):
        owner = f"{module}.{cls}" if cls else module
        return ".".join([owner, *ids[1:]])
    if imports and ids[0] in imports:
        return ".".join([imports[ids[0]], *ids[1:]])
    return f"{module}." + ".".join(ids)


class Program:
    """The whole-package call graph with propagated facts."""

    def __init__(self) -> None:
        self.functions: dict[str, FuncInfo] = {}
        self.modules: set[str] = set()
        # lock declarations assigned threading.RLock() — reentrant, so a
        # self re-acquisition is not a self-deadlock
        self.reentrant_locks: set[str] = set()
        # every Lock()/RLock() declaration: (abs file path, lineno of the
        # constructor call) -> declaration-based lock id.  The runtime
        # sanitizer (banyandb_tpu/sanitize/lockwatch.py) maps locks it
        # instruments back to static identities through this table.
        self.lock_sites: dict[tuple[str, int], str] = {}
        # module -> {class name -> {method name -> qual}}
        self._classes: dict[str, dict[str, dict[str, str]]] = {}
        self._bases: dict[tuple[str, str], list[str]] = {}
        # merged per-module import tables (kept for late resolution needs)
        self.tables: dict[str, dict[str, str]] = {}
        # one-hop attribute types: (mod, cls, attr) -> (mod2, cls2) for
        # `self.attr = SomePackageClass(...)` — lets `self.liaison.probe()`
        # resolve to the Liaison method
        self.attr_types: dict[tuple[str, str, str], tuple[str, str]] = {}
        # ctor dotted name per attribute declaration: (mod, cls, attr) ->
        # "threading.Event" etc.; the shared-state analyzer classifies
        # thread-safe primitives from this
        self.attr_ctor: dict[tuple[str, str, str], str] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(
        cls, pkg_root: Path, pkgname: str, trees: Optional[dict] = None
    ) -> "Program":
        """Pass pre-parsed ``trees`` (layers.parse_package) to share one
        parse of the package across analyzers."""
        self = cls()
        if trees is None:
            trees = parse_package(pkg_root, pkgname)
        self.modules = set(trees)
        for mod, (path, tree) in trees.items():
            self._collect_defs(mod, str(path), tree)
        tables = {
            mod: self._import_table(mod, tree, path.name == "__init__.py")
            for mod, (path, tree) in trees.items()
        }
        self.tables = tables
        for mod, (_path, tree) in trees.items():
            self._collect_attr_types(mod, tree, tables[mod])
        for mod, (_path, tree) in trees.items():
            self._resolve_module(mod, tree, tables[mod])
        self._mark_traced(trees, tables)
        self._propagate()
        return self

    def _collect_defs(self, mod: str, path: str, tree: ast.Module) -> None:
        classes: dict[str, dict[str, str]] = {}

        def visit(node: ast.AST, prefix: str, cls_name: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{mod}:{prefix}{child.name}"
                    self.functions[qual] = FuncInfo(
                        qual=qual, module=mod, path=path, node=child, cls=cls_name
                    )
                    if cls_name and not prefix.replace(cls_name + ".", "", 1):
                        classes.setdefault(cls_name, {})[child.name] = qual
                    visit(child, f"{prefix}{child.name}.", cls_name)
                elif isinstance(child, ast.ClassDef):
                    bases = [dotted_name(b) for b in child.bases]
                    self._bases[(mod, child.name)] = [b for b in bases if b]
                    classes.setdefault(child.name, {})
                    visit(child, f"{child.name}.", child.name)

        visit(tree, "", None)
        self._classes[mod] = classes

        # lock declarations: self.X = threading.Lock()/RLock() inside
        # class C -> "mod.C.X"; NAME = threading.Lock() -> "mod.NAME".
        # RLock declarations are additionally reentrant (not a
        # self-deadlock); every declaration records its constructor-call
        # source site for the runtime sanitizer's identity mapping.
        def scan_locks(node: ast.AST, cls_name: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan_locks(child, child.name)
                    continue
                if isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call
                ):
                    ctor = dotted_name(child.value.func)
                    if ctor in (
                        "threading.Lock",
                        "Lock",
                        "threading.RLock",
                        "RLock",
                    ):
                        for t in child.targets:
                            lid = lock_identity(t, mod, cls_name)
                            if lid:
                                if ctor.endswith("RLock"):
                                    self.reentrant_locks.add(lid)
                                self.lock_sites[
                                    (path, child.value.lineno)
                                ] = lid
                scan_locks(child, cls_name)

        scan_locks(tree, None)

    def _import_table(
        self, mod: str, tree: ast.Module, is_pkg: bool
    ) -> dict[str, str]:
        """Merged alias -> dotted-target table (module-level AND
        function-local imports: lazy boundaries still carry facts)."""
        table: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    table[(a.asname or a.name).split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
                    if a.asname:
                        table[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = resolve_relative_base(mod, node, is_pkg)
                for a in node.names:
                    table[a.asname or a.name] = f"{base}.{a.name}"
        return table

    def _find_class(
        self, mod: str, imports: dict[str, str], dotted: str
    ) -> Optional[tuple[str, str]]:
        """Resolve a dotted constructor name to an in-package class ref
        (module, class) — local class, imported symbol, or imported
        module attribute."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if not rest and head in self._classes.get(mod, {}):
            return (mod, head)
        if head in imports:
            dotted = imports[head] + (("." + rest) if rest else "")
        elif rest:
            return None
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            m = ".".join(parts[:cut])
            if m in self.modules:
                r = ".".join(parts[cut:])
                if r and "." not in r and r in self._classes.get(m, {}):
                    return (m, r)
                return None
        return None

    def _collect_attr_types(
        self, mod: str, tree: ast.Module, imports: dict[str, str]
    ) -> None:
        """One-hop attribute typing: `self.attr = Ctor(...)` anywhere in a
        class records the ctor (for primitive classification) and, when
        Ctor is an in-package class, the attribute's type — enabling
        `self.attr.method()` call resolution."""

        def scan(node: ast.AST, cls_name: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan(child, child.name)
                    continue
                if (
                    cls_name
                    and isinstance(child, (ast.Assign, ast.AnnAssign))
                    and isinstance(child.value, ast.Call)
                ):
                    ctor = dotted_name(child.value.func)
                    targets = (
                        child.targets
                        if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    if ctor:
                        for t in targets:
                            ids = _attr_chain_ids(t)
                            if len(ids) == 2 and ids[0] in ("self", "cls"):
                                key = (mod, cls_name, ids[1])
                                self.attr_ctor.setdefault(key, ctor)
                                ref = self._find_class(mod, imports, ctor)
                                if ref is not None:
                                    self.attr_types.setdefault(key, ref)
                scan(child, cls_name)

        scan(tree, None)

    # -- public class-table accessors (shared_state root discovery) --------

    def iter_classes(self):
        """-> (module, class name, {method name -> qual}) triples."""
        for mod, classes in self._classes.items():
            for cls_name, methods in classes.items():
                yield mod, cls_name, methods

    def class_bases(self, mod: str, cls_name: str) -> list[str]:
        """Dotted base names through the in-package inheritance chain
        (the class's own bases plus in-package ancestors' bases)."""
        out: list[str] = []
        seen = set()
        queue = [(mod, cls_name)]
        while queue:
            m, c = queue.pop(0)
            if (m, c) in seen:
                continue
            seen.add((m, c))
            for b in self._bases.get((m, c), []):
                out.append(b)
                if b in self._classes.get(m, {}):
                    queue.append((m, b))
        return out

    def _find_function(self, dotted: str) -> Optional[str]:
        """Fully-dotted path -> qualname, trying module prefixes longest
        first ("pkg.a.b.C.f" -> "pkg.a.b:C.f")."""
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.modules:
                rest = ".".join(parts[cut:])
                if not rest:
                    return None
                qual = f"{mod}:{rest}"
                if qual in self.functions:
                    return qual
                # class instantiation -> __init__
                init = f"{mod}:{rest}.__init__"
                if init in self.functions:
                    return init
                return None
        return None

    def _method_on(self, mod: str, cls_name: str, name: str) -> Optional[str]:
        """Method lookup through the in-package MRO (single inheritance
        chains only — enough for this codebase)."""
        seen = set()
        queue = [(mod, cls_name)]
        while queue:
            m, c = queue.pop(0)
            if (m, c) in seen:
                continue
            seen.add((m, c))
            qual = self._classes.get(m, {}).get(c, {}).get(name)
            if qual:
                return qual
            for b in self._bases.get((m, c), []):
                # base may be local ("Base") or imported — local only here
                if b in self._classes.get(m, {}):
                    queue.append((m, b))
        return None

    def _attr_type_on(
        self, mod: str, cls_name: str, attr: str
    ) -> Optional[tuple[str, str]]:
        """Attribute type lookup through the in-package MRO (declared in
        the class or any in-package ancestor)."""
        seen = set()
        queue = [(mod, cls_name)]
        while queue:
            m, c = queue.pop(0)
            if (m, c) in seen:
                continue
            seen.add((m, c))
            ref = self.attr_types.get((m, c, attr))
            if ref is not None:
                return ref
            for b in self._bases.get((m, c), []):
                if b in self._classes.get(m, {}):
                    queue.append((m, b))
        return None

    def attr_ctor_on(
        self, mod: str, cls_name: str, attr: str
    ) -> Optional[str]:
        """Constructor dotted name an attribute is assigned from, looked
        up through the in-package MRO ("threading.Event", ...)."""
        seen = set()
        queue = [(mod, cls_name)]
        while queue:
            m, c = queue.pop(0)
            if (m, c) in seen:
                continue
            seen.add((m, c))
            ctor = self.attr_ctor.get((m, c, attr))
            if ctor is not None:
                return ctor
            for b in self._bases.get((m, c), []):
                if b in self._classes.get(m, {}):
                    queue.append((m, b))
        return None

    def _resolve_ref(
        self,
        mod: str,
        imports: dict[str, str],
        enclosing: list[str],
        cls_name: Optional[str],
        local_types: dict[str, tuple[str, str]],
        d: str,
    ) -> Optional[str]:
        """Resolve a dotted function reference (call target or a function
        handed to Thread/submit/subscribe) to a qualname, or None."""
        if not d:
            return None
        head, _, rest = d.partition(".")
        if head in ("self", "cls") and cls_name:
            if rest and "." not in rest:
                return self._method_on(mod, cls_name, rest)
            if rest and rest.count(".") == 1:
                # one typed hop: self.liaison.probe -> Liaison.probe
                attr, _, meth = rest.partition(".")
                ref = self._attr_type_on(mod, cls_name, attr)
                if ref is not None:
                    return self._method_on(ref[0], ref[1], meth)
            return None
        if head in local_types and rest and "." not in rest:
            # typed local: srv = StandaloneServer(...); srv.start()
            return self._method_on(*local_types[head], rest)
        if head in imports:
            return self._find_function(
                imports[head] + (("." + rest) if rest else "")
            )
        if not rest:
            # bare name: enclosing nested scopes innermost-first, then
            # module-level function, then local class __init__
            for prefix in reversed(enclosing):
                qual = f"{mod}:{prefix}{head}"
                if qual in self.functions:
                    return qual
            if f"{mod}:{head}" in self.functions:
                return f"{mod}:{head}"
            if head in self._classes.get(mod, {}):
                return self._classes[mod].get(head, {}).get("__init__")
        return None

    def _resolve_call(
        self,
        mod: str,
        imports: dict[str, str],
        enclosing: list[str],
        cls_name: Optional[str],
        node: ast.Call,
        local_types: Optional[dict[str, tuple[str, str]]] = None,
    ) -> Optional[str]:
        return self._resolve_ref(
            mod,
            imports,
            enclosing,
            cls_name,
            local_types or {},
            dotted_name(node.func),
        )

    def _registrations_of(
        self,
        mod: str,
        imports: dict[str, str],
        enclosing: list[str],
        cls_name: Optional[str],
        local_types: dict[str, tuple[str, str]],
        node: ast.Call,
        fn_node: Optional[ast.AST] = None,
    ) -> list[Registration]:
        """Concurrency registrations made by this call, resolved to the
        function(s) that will run on another thread."""

        def resolve(expr: ast.AST) -> Optional[str]:
            return self._resolve_ref(
                mod, imports, enclosing, cls_name, local_types,
                dotted_name(expr),
            )

        def reg(kind: str, expr: ast.AST, name: str = "") -> list[Registration]:
            target = resolve(expr)
            if target is None and isinstance(expr, ast.Name) and fn_node:
                # the loops.py idiom: `for target, name in ((self._a,
                # "a"), (self._b, "b")): Thread(target=target)` — chase
                # the for-loop's literal iterable for every resolvable
                # function reference bound to this name
                return [
                    Registration(
                        kind=kind, target=t, name=n,
                        line=node.lineno, col=node.col_offset,
                    )
                    for t, n in self._loop_bound_targets(
                        fn_node, expr.id, resolve
                    )
                ]
            if target is None:
                return []
            return [
                Registration(
                    kind=kind,
                    target=target,
                    name=name,
                    line=node.lineno,
                    col=node.col_offset,
                )
            ]

        d = dotted_name(node.func)
        if d:
            # normalize the head through the import table, so
            # `import threading as _threading` still matches
            head, _, rest = d.partition(".")
            if head in imports:
                d = imports[head] + (("." + rest) if rest else "")
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if d in ("threading.Thread", "Thread") and "target" in kw:
            name = ""
            if isinstance(kw.get("name"), ast.Constant):
                name = str(kw["name"].value)
            return reg("thread", kw["target"], name)
        if d in ("threading.Timer", "Timer"):
            fn = kw.get("function") or (
                node.args[1] if len(node.args) > 1 else None
            )
            return reg("timer", fn) if fn is not None else []
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "submit" and node.args:
                return reg("executor", node.args[0])
            if node.func.attr == "subscribe" and len(node.args) >= 2:
                return reg("subscriber", node.args[1])
        if d.endswith("ThreadingHTTPServer") and len(node.args) >= 2:
            # each request runs the handler class's do_* method on its
            # own thread: every one is a root
            ref = None
            hd = dotted_name(node.args[1])
            if hd and "." not in hd:
                if hd in self._classes.get(mod, {}):
                    ref = (mod, hd)
                else:
                    ref = self._find_class(mod, imports, hd)
            if ref is None:
                return []
            out = []
            for meth, qual in sorted(self._classes[ref[0]][ref[1]].items()):
                if meth.startswith("do_"):
                    out.append(
                        Registration(
                            kind="http",
                            target=qual,
                            name="",
                            line=node.lineno,
                            col=node.col_offset,
                        )
                    )
            return out
        return []

    @staticmethod
    def _loop_bound_targets(fn_node: ast.AST, var: str, resolve):
        """(target qual, thread name) pairs for a name bound by a for
        loop over a LITERAL tuple/list of (callable, name, ...) tuples —
        the table-driven thread-spawn idiom."""
        out = []
        for forn in ast.walk(fn_node):
            if not isinstance(forn, ast.For):
                continue
            tgt = forn.target
            idx = None
            if isinstance(tgt, ast.Name) and tgt.id == var:
                idx = -1  # bare `for target in (...)`
            elif isinstance(tgt, ast.Tuple):
                for i, el in enumerate(tgt.elts):
                    if isinstance(el, ast.Name) and el.id == var:
                        idx = i
            if idx is None or not isinstance(forn.iter, (ast.Tuple, ast.List)):
                continue
            for row in forn.iter.elts:
                expr = row
                name = ""
                if isinstance(row, (ast.Tuple, ast.List)) and idx >= 0:
                    if idx >= len(row.elts):
                        continue
                    expr = row.elts[idx]
                    consts = [
                        e.value
                        for e in row.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
                    name = consts[0] if consts else ""
                q = resolve(expr)
                if q is not None:
                    out.append((q, name))
        return out

    def _resolve_module(
        self, mod: str, tree: ast.Module, imports: dict[str, str]
    ) -> None:
        def visit_fn(fn_node: ast.AST, qual: str, enclosing: list[str]) -> None:
            info = self.functions[qual]
            # typed locals first: `srv = StandaloneServer(...)` lets the
            # later `srv.start()` resolve (single-assignment idiom only —
            # a rebound name keeps its first type, conservatively)
            local_types: dict[str, tuple[str, str]] = {}
            for node in _walk_own(fn_node):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    ref = self._find_class(
                        mod, imports, dotted_name(node.value.func)
                    )
                    if ref is not None:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local_types.setdefault(t.id, ref)
            for node in _walk_own(fn_node):
                if isinstance(node, ast.Call):
                    callee = self._resolve_call(
                        mod, imports, enclosing, info.cls, node, local_types
                    )
                    site = CallSite(
                        node=node,
                        callee=callee,
                        line=node.lineno,
                        col=node.col_offset,
                    )
                    info.calls.append(site)
                    info.registrations.extend(
                        self._registrations_of(
                            mod,
                            imports,
                            enclosing,
                            info.cls,
                            local_types,
                            node,
                            fn_node,
                        )
                    )
                    d = dotted_name(node.func)
                    if d in _SYNC_APIS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_ATTRS
                    ):
                        info.direct_sync = d or node.func.attr
                    if d in _BLOCK_APIS or _is_transport_call(node):
                        info.direct_block = d or "transport.call"
            # lock regions: with-items whose context is lock-shaped
            for node in _walk_own(fn_node):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                for item in node.items:
                    lock_id = lock_identity(
                        item.context_expr, mod, info.cls, imports
                    )
                    if lock_id is None:
                        continue
                    region = LockRegion(lock_id=lock_id, node=node)
                    for inner in _walk_own(node):
                        if isinstance(inner, ast.Call):
                            region.calls.append(
                                CallSite(
                                    node=inner,
                                    callee=self._resolve_call(
                                        mod,
                                        imports,
                                        enclosing,
                                        info.cls,
                                        inner,
                                        local_types,
                                    ),
                                    line=inner.lineno,
                                    col=inner.col_offset,
                                )
                            )
                        elif isinstance(inner, (ast.With, ast.AsyncWith)):
                            for it in inner.items:
                                lid = lock_identity(
                                    it.context_expr, mod, info.cls, imports
                                )
                                if lid is not None:
                                    region.inner_locks.append((lid, inner))
                    info.lock_regions.append(region)

        def descend(node: ast.AST, prefix: str, enclosing: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{mod}:{prefix}{child.name}"
                    if qual in self.functions:
                        # a function's own prefix is in scope for its own
                        # body: `outer` calling its nested `h` resolves to
                        # "mod:outer.h", not the non-existent "mod:h"
                        inner = enclosing + [f"{prefix}{child.name}."]
                        visit_fn(child, qual, inner)
                        descend(child, f"{prefix}{child.name}.", inner)
                elif isinstance(child, ast.ClassDef):
                    descend(child, f"{child.name}.", enclosing)

        descend(tree, "", [])

    def _mark_traced(self, trees: dict, tables: dict) -> None:
        """jit regions: @jax.jit-decorated defs plus any function whose
        name (or dotted path) is passed to jax.jit(...) anywhere."""
        for mod, (_path, tree) in trees.items():
            imports = tables[mod]
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if any(_is_jax_jit(d) for d in node.decorator_list):
                        for qual, info in self.functions.items():
                            if info.node is node:
                                info.traced = True
                elif isinstance(node, ast.Call) and _is_jax_jit(node.func):
                    if not node.args:
                        continue
                    target = node.args[0]
                    d = dotted_name(target)
                    if not d:
                        continue
                    qual = None
                    if "." not in d:
                        # bare name: any (possibly nested) def in this module
                        cands = [
                            q
                            for q in self.functions
                            if q.startswith(f"{mod}:")
                            and q.rsplit(".", 1)[-1].split(":")[-1] == d
                        ]
                        qual = cands[0] if len(cands) == 1 else (
                            f"{mod}:{d}" if f"{mod}:{d}" in self.functions else None
                        )
                        if qual is None and cands:
                            for q in cands:
                                self.functions[q].traced = True
                    else:
                        head, _, rest = d.partition(".")
                        if head in imports:
                            qual = self._find_function(f"{imports[head]}.{rest}")
                    if qual and qual in self.functions:
                        self.functions[qual].traced = True

    # -- fact propagation --------------------------------------------------

    def _propagate(self) -> None:
        callers: dict[str, list[str]] = {}
        for qual, info in self.functions.items():
            for site in info.calls:
                if site.callee:
                    callers.setdefault(site.callee, []).append(qual)
            if info.direct_sync:
                info.sync = (info.direct_sync, ())
            if info.direct_block:
                info.block = (info.direct_block, ())
        work = [q for q, i in self.functions.items() if i.sync or i.block]
        while work:
            q = work.pop()
            info = self.functions[q]
            for caller in callers.get(q, ()):  # propagate up one edge
                ci = self.functions[caller]
                changed = False
                if info.sync and ci.sync is None:
                    ci.sync = (info.sync[0], (q, *info.sync[1]))
                    changed = True
                if info.block and ci.block is None:
                    ci.block = (info.block[0], (q, *info.block[1]))
                    changed = True
                if changed:
                    work.append(caller)

    def lock_acquires(self) -> dict[str, set[str]]:
        """qual -> set of lock ids the function may (transitively)
        acquire.  Fixed point over the call graph."""
        acq: dict[str, set[str]] = {
            q: {r.lock_id for r in i.lock_regions}
            for q, i in self.functions.items()
        }
        changed = True
        while changed:
            changed = False
            for q, info in self.functions.items():
                for site in info.calls:
                    if site.callee and site.callee in acq:
                        extra = acq[site.callee] - acq[q]
                        if extra:
                            acq[q] |= extra
                            changed = True
        return acq


def _chain(start: str, fact: tuple[str, tuple[str, ...]]) -> str:
    api, path = fact
    hops = " -> ".join(
        q.split(":", 1)[1] + f" ({q.split(':', 1)[0].split('.')[-1]})"
        for q in path
    )
    return f"{start} -> {hops} -> {api}" if hops else f"{start} -> {api}"


def analyze_sync_in_jit(program: Program) -> list[Finding]:
    """Calls inside jit-traced functions whose callee transitively syncs
    or blocks.  Depth-0 (direct API) calls are the per-file rule's job —
    only the cross-function reach is reported here."""
    findings: list[Finding] = []
    for info in program.functions.values():
        if not info.traced:
            continue
        for site in info.calls:
            if not site.callee:
                continue
            callee = program.functions.get(site.callee)
            if callee is None:
                continue
            short = site.callee.split(":", 1)[1]
            if callee.sync:
                findings.append(
                    Finding(
                        path=info.path,
                        line=site.line,
                        col=site.col,
                        rule="wp-sync-in-jit",
                        message=(
                            f"jit-traced `{info.qual.split(':', 1)[1]}` "
                            f"calls `{short}` which transitively performs "
                            f"a host sync: {_chain(short, callee.sync)}; "
                            "syncs belong at the result boundary, outside "
                            "the traced region"
                        ),
                    )
                )
            elif callee.block:
                findings.append(
                    Finding(
                        path=info.path,
                        line=site.line,
                        col=site.col,
                        rule="wp-sync-in-jit",
                        message=(
                            f"jit-traced `{info.qual.split(':', 1)[1]}` "
                            f"calls `{short}` which transitively blocks: "
                            f"{_chain(short, callee.block)}; a traced "
                            "function must stay pure device work"
                        ),
                    )
                )
    return findings


def analyze_lock_blocking(program: Program) -> list[Finding]:
    """The interprocedural extension of lock-across-rpc: a call made
    while holding a lock whose callee (transitively) blocks.  Direct
    blocking calls in the region are the per-file rule's findings and
    are not duplicated here."""
    findings: list[Finding] = []
    for info in program.functions.values():
        for region in info.lock_regions:
            for site in region.calls:
                if not site.callee:
                    continue
                callee = program.functions.get(site.callee)
                if callee is None or not callee.block:
                    continue
                if callee.qual == info.qual:
                    continue
                short = site.callee.split(":", 1)[1]
                findings.append(
                    Finding(
                        path=info.path,
                        line=site.line,
                        col=site.col,
                        rule="wp-lock-blocking",
                        message=(
                            f"`{short}` transitively blocks "
                            f"({_chain(short, callee.block)}) while "
                            f"`{region.lock_id}` is held; snapshot under "
                            "the lock, call outside it"
                        ),
                    )
                )
    return findings
