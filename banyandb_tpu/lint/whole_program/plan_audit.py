"""eval_shape plan auditor: abstract-trace every registered kernel entry
point against a matrix of representative plan shapes.

``jax.eval_shape`` runs the full trace — shape/dtype inference, XLA-less
— so this audit catches, with **zero device execution**:

- **shape mismatch**: a plan whose kernel no longer traces (broadcast
  error, bad segment count, wrong pytree) fails here, not on the first
  production query with that plan shape;
- **dtype promotion**: the precision contract (f32 device partials, i32
  keys/timestamps, f64 only on the host merge) is pinned as an explicit
  expectation table per entry; any drift — an accidental f64 constant, a
  weak-type widening, an int64 key — is a finding;
- **avoidable retrace**: the jit cache key objects (PlanSpec/_MaskSpec)
  are audited for deep immutability, by-value equality, and stable
  hashing (an identity-hashing or array-carrying key defeats the kernel
  cache and recompiles per query), and the row-bucket functions are
  audited to produce a finite power-of-two shape set (raw-n shapes mean
  one compile per distinct row count).

The matrix mirrors the dashboard plan population: flat count, grouped
eq+LUT predicates with scan-order tracking, percentile histogram at a
scan-chunk bucket, and an OR expression tree — plus the stream mask
kernel and the shared ops reduction entries that every plan lowers onto.

tests/test_whole_program.py drives ``audit_kernel`` with a seeded
dtype-promoting kernel to prove the detection; ``run_plan_audit()`` is
the tree audit the CLI runs.
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from typing import Callable, Optional

from banyandb_tpu.lint.core import Finding

RULE = "plan-audit"

_IMMUTABLE_SCALARS = (str, bytes, int, float, bool, type(None))


def _rel_path(path) -> str:
    """Repo-relative anchor path for a module's source file, matching
    the CLI-relative paths every other analyzer emits (stable SARIF
    URIs across machines).  Falls back to the absolute path when the
    package is installed outside a repo checkout."""
    from pathlib import Path

    import banyandb_tpu

    root = Path(banyandb_tpu.__file__).resolve().parent.parent
    p = Path(path).resolve()
    try:
        return str(p.relative_to(root))
    except ValueError:
        return str(p)


@dataclass
class KernelAudit:
    """One entry of the audit matrix."""

    name: str
    path: str  # finding anchor: the file that owns the kernel builder
    line: int
    fn: Callable  # the (jitted or plain) kernel to eval_shape
    args: tuple  # pytrees of jax.ShapeDtypeStruct / static scalars
    kwargs: dict = field(default_factory=dict)
    # flattened output key-path -> (dtype name, shape); the checked-in
    # precision/shape contract for this plan shape
    expect: Optional[dict[str, tuple[str, tuple]]] = None
    cache_key: object = None  # jit-cache key object to audit, if any


def _mutable_parts(obj, prefix: str = "") -> list[str]:
    """Paths inside a cache-key object that are not deeply immutable."""
    if isinstance(obj, _IMMUTABLE_SCALARS):
        return []
    if isinstance(obj, tuple):
        return [
            p
            for i, v in enumerate(obj)
            for p in _mutable_parts(v, f"{prefix}[{i}]")
        ]
    if isinstance(obj, frozenset):
        return [p for v in obj for p in _mutable_parts(v, prefix + "{}")]
    if dataclasses.is_dataclass(obj) and obj.__dataclass_params__.frozen:
        return [
            p
            for f in dataclasses.fields(obj)
            for p in _mutable_parts(
                getattr(obj, f.name), f"{prefix}.{f.name}".lstrip(".")
            )
        ]
    return [prefix or "<root>"]


def _flat_spec(tree) -> dict[str, tuple[str, tuple]]:
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = "".join(str(p) for p in path) or "<out>"
        out[key] = (str(leaf.dtype), tuple(leaf.shape))
    return out


def audit_kernel(entry: KernelAudit) -> list[Finding]:
    """Run one matrix entry -> findings (empty = the plan holds)."""
    import jax

    findings: list[Finding] = []

    def hit(message: str) -> None:
        findings.append(
            Finding(
                path=entry.path,
                line=entry.line,
                col=0,
                rule=RULE,
                message=f"[{entry.name}] {message}",
            )
        )

    if entry.cache_key is not None:
        for p in _mutable_parts(entry.cache_key):
            hit(
                f"jit cache key field `{p}` is not deeply immutable; "
                "an array/list/dict in the key defeats the kernel cache "
                "(retrace per query)"
            )
        try:
            clone = copy.deepcopy(entry.cache_key)
            if bool(clone != entry.cache_key) or hash(clone) != hash(
                entry.cache_key
            ):
                hit(
                    "jit cache key compares/hashes by identity, not "
                    "value: an equal plan rebuilt next query misses the "
                    "cache and recompiles"
                )
        except TypeError as e:
            hit(f"jit cache key is unhashable: {e}")
        except ValueError:
            # e.g. an ndarray in the key makes != ambiguous — already
            # reported above as a non-immutable field
            pass

    try:
        out = jax.eval_shape(entry.fn, *entry.args, **entry.kwargs)
    except Exception as e:  # noqa: BLE001 — the finding IS the report
        hit(
            f"abstract trace failed (shape mismatch / trace error): "
            f"{type(e).__name__}: {e}"
        )
        return findings

    got = _flat_spec(out)
    for key, (dtype, _shape) in sorted(got.items()):
        if dtype in ("float64", "int64", "uint64"):
            hit(
                f"output `{key}` is {dtype}: 64-bit dtypes in a device "
                "plan double HBM traffic and break the f32-partials/"
                "f64-host-merge precision contract"
            )
    if entry.expect is not None:
        for key in sorted(set(entry.expect) | set(got)):
            want, have = entry.expect.get(key), got.get(key)
            if want is None:
                hit(f"unexpected output `{key}` {have}; extend the contract "
                    "table if this is deliberate")
            elif have is None:
                hit(f"missing output `{key}` (contract says {want})")
            elif want != have:
                hit(
                    f"output `{key}` is dtype={have[0]} shape={have[1]}, "
                    f"contract says dtype={want[0]} shape={want[1]}"
                )
    return findings


def _bucket_findings() -> list[Finding]:
    """The retrace-bound audit: row-bucket functions must emit a finite
    power-of-two shape set."""
    import inspect

    from banyandb_tpu.query import measure_exec, stream_exec

    findings: list[Finding] = []
    for mod, fn_name, fn, hi in (
        (measure_exec, "_scan_bucket", measure_exec._scan_bucket, measure_exec.SCAN_CHUNK),
        (stream_exec, "_pad_bucket", stream_exec._pad_bucket, 1 << 24),
    ):
        path = _rel_path(inspect.getsourcefile(mod))
        line = inspect.getsourcelines(fn)[1]
        buckets = {fn(n) for n in (1, 2, 63, 64, 65, 1000, 8192, 100_000, hi)}
        bad = [b for b in buckets if b & (b - 1) or b > max(hi, 1)]
        if bad:
            findings.append(
                Finding(
                    path=path,
                    line=line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"{fn_name} emitted non-power-of-two/unbounded row "
                        f"buckets {sorted(bad)}: every distinct bucket is "
                        "one XLA compile; the shape set must stay "
                        "O(log max_rows)"
                    ),
                )
            )
    return findings


def default_entries() -> list[KernelAudit]:
    """The checked-in plan matrix for the banyandb_tpu query layer.

    The measure/stream kernel signatures come from the precompile
    registry's builtin matrix (query/precompile.builtin_plans/_masks) —
    ONE list feeds both warming and auditing, and the agreement is
    pinned by a meta-test (tests/test_cold_path.py), so a signature the
    server precompiles is exactly a signature this audit contracts."""
    import inspect

    import jax
    import jax.numpy as jnp

    from banyandb_tpu import ops
    from banyandb_tpu.query import measure_exec, precompile, stream_exec
    from banyandb_tpu.query.measure_exec import PlanSpec

    S = jax.ShapeDtypeStruct
    f32, i32, b8 = jnp.float32, jnp.int32, jnp.bool_

    mpath = _rel_path(inspect.getsourcefile(measure_exec))
    mline = inspect.getsourcelines(measure_exec._build_kernel)[1]
    spath = _rel_path(inspect.getsourcefile(stream_exec))
    sline = inspect.getsourcelines(stream_exec._build_kernel)[1]

    def measure_entry(
        name: str, spec: PlanSpec, expect: dict[str, tuple[str, tuple]]
    ) -> KernelAudit:
        return KernelAudit(
            name=name,
            path=str(mpath),
            line=mline,
            fn=measure_exec._build_kernel(spec),
            args=(
                precompile.chunk_struct(spec),
                precompile.pred_struct(spec),
                S((), f32),
                S((), f32),
            ),
            expect=expect,
            cache_key=spec,
        )

    def base_expect(spec: PlanSpec) -> dict[str, tuple[str, tuple]]:
        g = (spec.num_groups,)
        out = {"['count']": ("float32", g)}
        for f in spec.fields:
            out[f"['sums']['{f}']"] = ("float32", g)
            if spec.want_minmax:  # min/max arrays exist only when asked
                out[f"['mins']['{f}']"] = ("float32", g)
                out[f"['maxs']['{f}']"] = ("float32", g)
        if spec.hist_field:
            out["['hist']"] = ("float32", (spec.num_groups, 512))
        if spec.want_rep:
            out["['rep_ts']"] = ("int32", g)
            out["['rep_row']"] = ("int32", g)
        return out

    entries: list[KernelAudit] = []

    for name, spec in precompile.builtin_plans():
        entries.append(measure_entry(name, spec, base_expect(spec)))

    for name, mspec in precompile.builtin_masks():
        entries.append(
            KernelAudit(
                name=name,
                path=str(spath),
                line=sline,
                fn=stream_exec._build_kernel(mspec),
                args=precompile.mask_structs(mspec),
                expect={"<out>": ("bool", (mspec.nrows,))},
                cache_key=mspec,
            )
        )

    # the fused whole-plan twins: same contract per chunk, stacked
    # [num_chunks, ...] outputs (one dispatch/one get per part-batch is
    # the kernel-dispatch half; here the shape/dtype contract is pinned)
    from banyandb_tpu.query import fused_exec

    fpath = _rel_path(inspect.getsourcefile(fused_exec))
    fline = inspect.getsourcelines(fused_exec._build_kernel)[1]
    for name, fspec in precompile.builtin_fused():
        fexpect = {
            key: (dtype, (fspec.num_chunks,) + shape)
            for key, (dtype, shape) in base_expect(fspec.plan).items()
        }
        entries.append(
            KernelAudit(
                name=name,
                path=str(fpath),
                line=fline,
                fn=fused_exec._build_kernel(fspec),
                args=(
                    precompile.fused_chunk_struct(fspec),
                    precompile.pred_struct(fspec.plan),
                    S((), f32),
                    S((), f32),
                ),
                expect=fexpect,
                cache_key=fspec,
            )
        )

    # the device-decode twins (ROADMAP item 3): SAME fused program, the
    # COMPRESSED chunk ship form (narrow codes + remap LUTs + narrow int
    # fields) — the in-program decode stage must keep the output
    # contract identical and introduce no 64-bit dtypes, and the
    # lowering audit pins the bytes-accessed class the compression buys
    for name, fspec in precompile.builtin_fused_decode():
        fexpect = {
            key: (dtype, (fspec.num_chunks,) + shape)
            for key, (dtype, shape) in base_expect(fspec.plan).items()
        }
        entries.append(
            KernelAudit(
                name=name,
                path=str(fpath),
                line=fline,
                fn=fused_exec._build_kernel(fspec),
                args=(
                    precompile.fused_decode_chunk_struct(fspec),
                    precompile.pred_struct(fspec.plan),
                    S((), f32),
                    S((), f32),
                ),
                expect=fexpect,
                cache_key=fspec,
            )
        )

    # 6. the shared ops reductions every plan lowers onto, at a
    # representative grouped shape (method dispatch goes through "auto")
    opath = _rel_path(inspect.getsourcefile(ops.groupby))
    oline = inspect.getsourcelines(ops.group_reduce)[1]
    n, G = 8192, 128
    entries.append(
        KernelAudit(
            name="ops/group_reduce",
            path=str(opath),
            line=oline,
            fn=lambda key, valid, f: ops.group_reduce(key, valid, {"v": f}, G),
            args=(S((n,), i32), S((n,), b8), S((n,), f32)),
            expect={
                ".count": ("float32", (G,)),
                ".sums['v']": ("float32", (G,)),
                ".mins['v']": ("float32", (G,)),
                ".maxs['v']": ("float32", (G,)),
            },
        )
    )
    hpath = _rel_path(inspect.getsourcefile(ops.percentile))
    hline = inspect.getsourcelines(ops.group_histogram)[1]
    entries.append(
        KernelAudit(
            name="ops/group_histogram",
            path=str(hpath),
            line=hline,
            fn=lambda key, valid, vals, lo, span: ops.group_histogram(
                key, valid, vals, G, lo, span, 512
            ),
            args=(
                S((n,), i32),
                S((n,), b8),
                S((n,), f32),
                S((), f32),
                S((), f32),
            ),
            expect={"<out>": ("float32", (G, 512))},
        )
    )
    return entries


def run_plan_audit() -> list[Finding]:
    findings: list[Finding] = []
    for entry in default_entries():
        findings.extend(audit_kernel(entry))
    findings.extend(_bucket_findings())
    return findings
