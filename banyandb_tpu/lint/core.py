"""bdlint engine: file discovery, suppressions, rule running, rendering.

Rules live in rules_jax.py (hot-path invariants) and rules_fabric.py
(cluster-fabric + resource invariants).  Each rule is an object with

- ``name``     the greppable id used in ``# bdlint: disable=<name>``
- ``summary``  one line for ``--list-rules``
- ``scope``    tuple of package-relative path prefixes it applies to
               (``""`` = the whole package)
- ``check(ctx) -> Iterable[Finding]``

Scopes are matched against the file's path relative to the
``banyandb_tpu`` package root, so the hot-path rules fire only in the
modules where a stray host sync actually costs money (query/, ops/,
parallel/, index/) while fabric rules cover the whole tree.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Optional

_SUPPRESS_RE = re.compile(
    r"#\s*bdlint:\s*(disable|disable-file)=([A-Za-z0-9_,\- ]+)"
)
_GENERATED_DIRS = {"pb", "__pycache__"}


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at a source location."""

    path: str  # as given to the linter (display path)
    line: int  # 1-based
    col: int  # 0-based
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class FileContext:
    """Parsed source + shared per-file analyses handed to every rule."""

    def __init__(self, source: str, path: str, rel: str):
        self.source = source
        self.path = path
        self.rel = rel  # package-relative, "/"-separated (scope matching)
        self.tree = ast.parse(source)
        self.lines = source.splitlines()
        self._parents: Optional[dict] = None
        self._facts = None

    @property
    def parents(self) -> dict:
        """child ast node -> parent node map (built on first use)."""
        if self._parents is None:
            p: dict = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    p[child] = node
            self._parents = p
        return self._parents

    @property
    def jax_facts(self):
        """Module-level jit analysis shared by the hot-path rules."""
        if self._facts is None:
            from banyandb_tpu.lint.rules_jax import ModuleJaxFacts

            self._facts = ModuleJaxFacts(self.tree)
        return self._facts

    def finding(self, node: ast.AST, rule: str, message: str) -> Finding:
        return Finding(
            path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            rule=rule,
            message=message,
        )


def dotted_name(node: ast.AST) -> str:
    """``jax.lax.psum`` for nested Attribute/Name chains, else ""."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def parse_suppressions(lines: list[str]) -> tuple[dict[int, frozenset], frozenset]:
    """-> ({1-based line: suppressed rule names}, file-wide suppressions).

    A suppression on a comment-only line applies to the next code line, so
    long reasons don't have to fight the line-length limit.
    """
    per_line: dict[int, set] = {}
    file_wide: set = set()
    pending: set = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        stripped = text.strip()
        names: set = set()
        if m:
            # the "-- reason" text must go before splitting on commas,
            # or a reason like "-- not a leak, host-sync" would widen
            # the suppression to the named rule
            spec = m.group(2).split("--", 1)[0]
            names = {n.strip() for n in spec.split(",") if n.strip()}
            if m.group(1) == "disable-file":
                file_wide |= names
                names = set()
        if stripped.startswith("#") or not stripped:
            # comment-only or blank line: keep deferring to the next
            # code line (a reflow that inserts a blank line must not
            # silently detach an audited suppression)
            pending |= names
            continue
        here = names | pending
        pending = set()
        if here:
            per_line[i] = per_line.get(i, set()) | here
    return (
        {k: frozenset(v) for k, v in per_line.items()},
        frozenset(file_wide),
    )


# -- ratchet-baseline mechanics ----------------------------------------------
# Shared by every baselined analyzer (layering, wp-shared-state, the
# kernel budget table): a baselined violation that still exists is
# tolerated, a new violation fails, and a baselined entry whose
# violation disappeared fails as STALE — so the checked-in list only
# ever shrinks/tightens, never silently rots.


def stale_entry_finding(
    key: str,
    *,
    rule: str,
    path: str,
    what: str = "the violation",
    line: int = 1,
) -> Finding:
    """The stale half of the ratchet, one message shape for every
    baselined analyzer (tests grep for "stale baseline")."""
    return Finding(
        path=path,
        line=line,
        col=0,
        rule=rule,
        message=(
            f"stale baseline entry `{key}`: {what} no longer exists — "
            "delete it so the ratchet only tightens"
        ),
    )


def apply_ratchet(
    violations: list[tuple[str, Finding]],
    baseline: frozenset,
    *,
    rule: str,
    baseline_path: str,
    what: str = "the violation",
) -> list[Finding]:
    """Set-membership ratchet over ``(key, finding)`` live violations:
    baselined keys are tolerated, unknown keys pass through as findings,
    and baseline entries with no live violation fail as stale."""
    findings = [f for key, f in violations if key not in baseline]
    seen = {key for key, _ in violations if key in baseline}
    for key in sorted(baseline - seen):
        findings.append(
            stale_entry_finding(
                key, rule=rule, path=baseline_path, what=what
            )
        )
    return findings


def ratchet_value(
    key: str,
    column: str,
    measured: float,
    budget: float,
    *,
    rule: str,
    path: str,
    line: int = 1,
    budget_path: str = "",
    regression_hint: str = "",
) -> list[Finding]:
    """Numeric-budget ratchet: measured above budget is a regression,
    measured below budget is a stale (too-loose) entry that must be
    tightened, equal is clean.  The kernel budget table's contract."""
    if measured == budget:
        return []
    if measured > budget:
        msg = (
            f"[{key}] {column} regression: measured {measured:g} exceeds "
            f"the budgeted {budget:g}"
        )
        if regression_hint:
            msg += f"; {regression_hint}"
        return [Finding(path=path, line=line, col=0, rule=rule, message=msg)]
    return [
        Finding(
            path=budget_path or path,
            line=1 if budget_path else line,
            col=0,
            rule=rule,
            message=(
                f"[{key}] stale budget entry: {column} measured "
                f"{measured:g} is below the budgeted {budget:g} — tighten "
                "the entry so the ratchet keeps the improvement"
            ),
        )
    ]


def _package_rel(path: Path) -> Optional[str]:
    """Path inside the banyandb_tpu package -> package-relative posix
    path; None for files outside the package (bdlint is project-native
    and has nothing to say about them)."""
    parts = list(path.parts)
    if "banyandb_tpu" not in parts:
        return None
    idx = len(parts) - 1 - parts[::-1].index("banyandb_tpu")
    rel = parts[idx + 1 :]
    if any(d in _GENERATED_DIRS for d in rel[:-1]):
        return None  # generated code (api/pb) is out of audit scope
    return "/".join(rel)


def all_rules() -> list:
    from banyandb_tpu.lint import rules_fabric, rules_jax

    return list(rules_jax.RULES) + list(rules_fabric.RULES)


ALL_RULES = all_rules


def lint_source(
    source: str,
    rel: str = "",
    path: str = "<memory>",
    rules: Optional[list] = None,
) -> tuple[list[Finding], int]:
    """Lint one source string as if it lived at package-relative `rel`.

    -> (findings, suppressed_count).  The test suite's entry point.
    """
    ctx = FileContext(source, path=path, rel=rel)
    per_line, file_wide = parse_suppressions(ctx.lines)
    findings: list[Finding] = []
    suppressed = 0
    for rule in rules if rules is not None else all_rules():
        if rule.scope and not any(rel.startswith(s) for s in rule.scope):
            continue
        for f in rule.check(ctx):
            sup = per_line.get(f.line, frozenset()) | file_wide
            if f.rule in sup or "all" in sup:
                suppressed += 1
            else:
                findings.append(f)
    findings.sort()
    return findings, suppressed


def lint_file(
    path: Path, rules: Optional[list] = None
) -> tuple[list[Finding], int, bool]:
    """-> (findings, suppressed, was_linted)."""
    rel = _package_rel(path)
    if rel is None:
        return [], 0, False
    source = path.read_text(encoding="utf-8")
    try:
        findings, suppressed = lint_source(
            source, rel=rel, path=str(path), rules=rules
        )
    except SyntaxError as e:
        return (
            [
                Finding(
                    path=str(path),
                    line=e.lineno or 1,
                    col=e.offset or 0,
                    rule="parse-error",
                    message=f"file does not parse: {e.msg}",
                )
            ],
            0,
            True,
        )
    return findings, suppressed, True


def lint_paths(
    paths: Iterable[str], rules: Optional[list] = None
) -> tuple[list[Finding], dict]:
    """Walk files/dirs -> (sorted findings, summary stats dict)."""
    files: list[Path] = []
    for p in paths:
        pth = Path(p)
        if pth.is_dir():
            files.extend(sorted(pth.rglob("*.py")))
        elif pth.suffix == ".py":
            files.append(pth)
    findings: list[Finding] = []
    suppressed = 0
    linted = 0
    for f in files:
        got, sup, used = lint_file(f, rules=rules)
        findings.extend(got)
        suppressed += sup
        linted += int(used)
    findings.sort()
    return findings, {
        "files": linted,
        "findings": len(findings),
        "suppressed": suppressed,
    }


def render_text(findings: list[Finding], summary: dict) -> str:
    out = [f.render() for f in findings]
    tail = (
        "bdlint: {files} files, {findings} findings, "
        "{suppressed} suppressed".format(**summary)
    )
    if "wp_functions" in summary:
        tail += (
            "; whole-program: {wp_findings} findings, "
            "{wp_suppressed} suppressed over {wp_functions} "
            "functions".format(**summary)
        )
    out.append(tail)
    return "\n".join(out)


_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _rule_catalog() -> list[tuple[str, str]]:
    """(id, summary) for every rule bdlint can emit, stable order:
    per-file rules, whole-program analyses, then the parse sentinel."""
    cat = [(r.name, r.summary) for r in all_rules()]
    from banyandb_tpu.lint.whole_program import WP_RULES

    cat += list(WP_RULES)
    cat.append(("parse-error", "file does not parse"))
    return cat


def render_json(findings: list[Finding], summary: dict) -> str:
    """Real SARIF 2.1.0 (editors and code-scanning UIs ingest it):
    tool.driver rule metadata, results[].locations, run-level summary
    under properties.  Deterministic: sorted findings, sorted keys."""
    catalog = _rule_catalog()
    rule_index = {name: i for i, (name, _) in enumerate(catalog)}
    results = []
    for f in findings:
        results.append(
            {
                "ruleId": f.rule,
                "ruleIndex": rule_index.get(f.rule, -1),
                "level": "error",
                "message": {"text": f.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {
                                "startLine": f.line,
                                # bdlint columns are 0-based; SARIF's are 1-based
                                "startColumn": f.col + 1,
                            },
                        }
                    }
                ],
            }
        )
    doc = {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        # informationUri omitted: SARIF §3.19.2 requires an
                        # absolute URI and this repo has no canonical URL;
                        # docs/linting.md is the human entry point
                        "name": "bdlint",
                        "rules": [
                            {
                                "id": name,
                                "shortDescription": {"text": text},
                            }
                            for name, text in catalog
                        ],
                    }
                },
                "results": results,
                "properties": summary,
            }
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
