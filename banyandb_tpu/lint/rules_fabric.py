"""Cluster-fabric and resource hygiene rules.

The fabric invariants keep a degraded cluster degraded instead of dead:
every RPC must be able to time out, no lock may be held across a
blocking network call (one slow peer would serialize the process), and
retry loops must back off instead of hammering a struggling node.
Resource hygiene keeps long-running nodes from leaking fds across
flush/merge/restart cycles.
"""

from __future__ import annotations

import ast
from typing import Iterable

from banyandb_tpu.lint.core import FileContext, Finding, dotted_name

# Callees that block on the network (or deliberately stall the thread).
_BLOCKING_SLEEPS = {"time.sleep", "_time.sleep", "sleep"}
_URLOPEN = {"urlopen", "urllib.request.urlopen", "request.urlopen"}
_SOCKET_CONNECT = {"socket.create_connection", "create_connection"}


def _attr_chain_ids(node: ast.AST) -> list[str]:
    """['self', 'transport', 'call'] for self.transport.call."""
    out: list[str] = []
    while isinstance(node, ast.Attribute):
        out.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        out.append(node.id)
    return list(reversed(out))


def _is_transport_call(node: ast.Call) -> bool:
    """A bus/transport RPC: ``<...>.transport.call(...)`` or a bare
    ``transport.call(...)`` — the project's one fabric call surface
    (cluster/rpc.py LocalTransport/GrpcTransport)."""
    if not isinstance(node.func, ast.Attribute) or node.func.attr != "call":
        return False
    chain = _attr_chain_ids(node.func)
    return any("transport" in part for part in chain[:-1])


def _is_blocking(node: ast.Call) -> bool:
    d = dotted_name(node.func)
    if d in _BLOCKING_SLEEPS | _URLOPEN | _SOCKET_CONNECT:
        return True
    return _is_transport_call(node)


class RpcTimeoutRule:
    """rpc-timeout: fabric calls that can block a thread forever.

    Transport defaults exist, but an explicit timeout at every call site
    is the contract: the right bound depends on the call (health probes
    want 5s, chunked sync wants 120s) and an inherited default is how
    30s stalls hide in gossip loops."""

    name = "rpc-timeout"
    summary = "network call without an explicit timeout"
    scope = ("",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            kw = {k.arg for k in node.keywords}
            if _is_transport_call(node) and "timeout" not in kw:
                yield ctx.finding(
                    node,
                    self.name,
                    "transport.call without explicit timeout=; pick the "
                    "bound this call actually tolerates",
                )
            d = dotted_name(node.func)
            if d in _URLOPEN | _SOCKET_CONNECT and "timeout" not in kw:
                yield ctx.finding(
                    node,
                    self.name,
                    f"{d}() without timeout= can hang the fabric thread",
                )


class LockAcrossRpcRule:
    """lock-across-rpc: a mutex held across a blocking network call.

    One unreachable peer then serializes every thread that touches the
    lock — the exact failure the per-node health/handoff machinery
    exists to avoid.  Move the call out of the critical section (snapshot
    under the lock, call after)."""

    name = "lock-across-rpc"
    summary = "lock held across a blocking RPC/sleep"
    scope = ("",)

    @staticmethod
    def _is_lock_ctx(expr: ast.AST) -> bool:
        ids = _attr_chain_ids(expr)
        return bool(ids) and "lock" in ids[-1].lower()

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(
                self._is_lock_ctx(item.context_expr) for item in node.items
            ):
                continue
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) and _is_blocking(inner):
                    yield ctx.finding(
                        inner,
                        self.name,
                        "blocking call while holding a lock; snapshot "
                        "under the lock, call outside it",
                    )


class RetryBackoffRule:
    """retry-backoff: a retry loop with no sleep between attempts.

    A ``while`` loop that swallows exceptions and immediately re-tries
    turns one struggling peer into a busy-loop DoS from every client.
    The blessed shape is schema_plane's watcher: exponential backoff,
    reset on a healthy pass."""

    name = "retry-backoff"
    summary = "retry loop without backoff/sleep"
    scope = ("",)

    @staticmethod
    def _has_pause(loop: ast.While) -> bool:
        for node in ast.walk(loop):
            if isinstance(node, ast.Call):
                d = dotted_name(node.func)
                last = d.rsplit(".", 1)[-1]
                if last in ("sleep", "wait") or "backoff" in d:
                    return True
                # a bounded blocking call (q.get(timeout=...)) paces the
                # loop just as well as an explicit sleep — but a NETWORK
                # call's own timeout does not: against a down peer,
                # connection-refused returns in microseconds and the loop
                # still hammers (the timeout only bounds the slow case)
                if (
                    any(k.arg == "timeout" for k in node.keywords)
                    and not _is_blocking(node)
                ):
                    return True
        return False

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        """True when the handler neither re-raises nor leaves the loop —
        i.e. the loop will immediately try again."""
        escapes = (ast.Raise, ast.Return, ast.Break)
        return not any(
            isinstance(n, escapes) for n in ast.walk(handler)
        )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While):
                continue
            retries = [
                t
                for t in ast.walk(node)
                if isinstance(t, ast.Try)
                and any(self._swallows(h) for h in t.handlers)
            ]
            if retries and not self._has_pause(node):
                yield ctx.finding(
                    retries[0],
                    self.name,
                    "loop swallows errors and retries without sleeping; "
                    "add (exponential) backoff",
                )


class ResourceHygieneRule:
    """resource-hygiene: files/sockets opened outside context managers.

    Nodes run for weeks across flush/merge/restart cycles; a handle that
    relies on GC is a handle that leaks under load.  Deliberate
    long-lived handles (caches, access logs) carry a suppression naming
    who closes them."""

    name = "resource-hygiene"
    summary = "open()/socket() outside a context manager"
    scope = ("",)

    _OPENERS = {"open", "socket.socket", "socket.create_connection"}

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d not in self._OPENERS:
                continue
            parent = ctx.parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            yield ctx.finding(
                node,
                self.name,
                f"{d}() outside a with-block; use a context manager or "
                "suppress naming the owner that closes it",
            )


RULES = (
    RpcTimeoutRule(),
    LockAcrossRpcRule(),
    RetryBackoffRule(),
    ResourceHygieneRule(),
)
