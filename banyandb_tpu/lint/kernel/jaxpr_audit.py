"""kernel-jaxpr: walk each audited kernel's closed jaxpr.

What the eval_shape plan audit cannot see — it only checks the *output*
pytree — this pass checks the program text in between, with zero device
execution (``jax.make_jaxpr`` is a pure trace):

- **host callbacks**: ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` inside a device plan force a host round-trip per
  invocation; a kernel on the query hot path must never carry one.
- **64-bit dtypes**: any equation producing f64/i64/u64 doubles HBM
  traffic and breaks the f32-partials / f64-host-merge precision
  contract *internally*, even if the outputs stay 32-bit (the exact
  failure the exact-integer-aggregation work, ROADMAP item 5c, must not
  reintroduce by accident).
- **narrowing conversions**: ``convert_element_type`` from f32 down to
  f16/bf16 silently truncates an accumulator's mantissa — the
  per-group sums would drift beyond the pinned 1e-5 bound.
- **non-donated aliasing buffers**: an output whose aval exactly matches
  a large input's and is not donated costs a second HBM allocation per
  dispatch; the jit should mark the input in ``donate_argnums``.

``audit_entry`` also reports the widest dtype itemsize seen anywhere in
the jaxpr — the measurement the kernel budget table's ``widest`` column
ratchets (kernel_budgets.py).
"""

from __future__ import annotations

from typing import Iterable

from banyandb_tpu.lint.core import Finding

RULE = "kernel-jaxpr"

_HOST_CALLBACK_PRIMS = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "debug_print",
}

# f32 -> any of these narrows an accumulator's mantissa
_NARROW_FLOATS = {"float16", "bfloat16", "float8_e4m3fn", "float8_e5m2"}

# an output aliasing an input at or above this many bytes should be
# donated (below it the copy is noise)
_DONATE_BYTES = 1 << 16


def iter_eqns(jaxpr) -> Iterable[tuple[int, object]]:
    """Depth-first (index, eqn) over a jaxpr and every sub-jaxpr carried
    in its equation params (pjit bodies, scan/while/cond branches)."""
    idx = 0
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            yield idx, eqn
            idx += 1
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    stack.append(sub)


def _sub_jaxprs(v):
    if hasattr(v, "eqns"):
        return [v]
    if hasattr(v, "jaxpr"):  # ClosedJaxpr
        return [v.jaxpr]
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            out.extend(_sub_jaxprs(x))
        return out
    return []


def _aval_dtype(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def make_entry_jaxpr(entry):
    """Closed jaxpr of one audit-matrix entry (pure trace, no device)."""
    import jax

    return jax.make_jaxpr(entry.fn)(*entry.args, **entry.kwargs)


def audit_entry(entry) -> tuple[list[Finding], int]:
    """-> (findings, widest dtype itemsize seen in the jaxpr)."""
    findings: list[Finding] = []

    def hit(message: str) -> None:
        findings.append(
            Finding(
                path=entry.path,
                line=entry.line,
                col=0,
                rule=RULE,
                message=f"[{entry.name}] {message}",
            )
        )

    try:
        closed = make_entry_jaxpr(entry)
    except Exception as e:  # noqa: BLE001 — plan-audit reports trace errors
        hit(f"jaxpr trace failed: {type(e).__name__}: {e}")
        return findings, 0

    widest = 1
    wide_hits: set[str] = set()
    for idx, eqn in iter_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        if prim in _HOST_CALLBACK_PRIMS:
            hit(
                f"host callback `{prim}` at jaxpr eqn #{idx}: a device "
                "plan must not round-trip to the host per invocation; "
                "lift the callback out of the kernel"
            )
        if prim == "convert_element_type":
            src = _aval_dtype(eqn.invars[0])
            dst = eqn.params.get("new_dtype")
            if (
                src is not None
                and dst is not None
                and str(src) == "float32"
                and str(dst) in _NARROW_FLOATS
            ):
                hit(
                    f"accumulator narrowed at jaxpr eqn #{idx}: "
                    f"convert_element_type float32 -> {dst} truncates "
                    "the mantissa; partial sums must stay f32 on device"
                )
        for ov in eqn.outvars:
            dt = _aval_dtype(ov)
            if dt is None:
                continue
            widest = max(widest, dt.itemsize)
            if dt.itemsize >= 8 and str(dt) not in wide_hits:
                wide_hits.add(str(dt))
                hit(
                    f"64-bit dtype `{dt}` produced at jaxpr eqn #{idx} "
                    f"(`{prim}`): 64-bit values double HBM traffic and "
                    "break the f32-partials/f64-host-merge precision "
                    "contract; keep device math 32-bit"
                )

    findings += _donation_findings(entry, closed)
    return findings, widest


def _donation_findings(entry, closed) -> list[Finding]:
    """Large output aliasing an input aval without donation.

    The alias candidate test is structural (same shape+dtype, >= the
    donate threshold); only when a candidate exists do we pay a lowering
    to read the authoritative donated flags from ``args_info``.
    """
    import jax
    import numpy as np

    in_avals = [v.aval for v in closed.jaxpr.invars]
    out_avals = [v.aval for v in closed.jaxpr.outvars]

    def nbytes(aval) -> int:
        if not hasattr(aval, "dtype"):
            return 0
        return int(np.prod(aval.shape or (1,))) * aval.dtype.itemsize

    candidates = []
    in_keys = {
        (tuple(a.shape), str(a.dtype))
        for a in in_avals
        if hasattr(a, "dtype") and nbytes(a) >= _DONATE_BYTES
    }
    for a in out_avals:
        if not hasattr(a, "dtype") or nbytes(a) < _DONATE_BYTES:
            continue
        if (tuple(a.shape), str(a.dtype)) in in_keys:
            candidates.append(a)
    if not candidates:
        return []

    fn = entry.fn if hasattr(entry.fn, "lower") else jax.jit(entry.fn)
    try:
        lowered = fn.lower(*entry.args, **entry.kwargs)
        args_info = jax.tree_util.tree_leaves(lowered.args_info)
        any_donated = any(getattr(i, "donated", False) for i in args_info)
    except Exception:  # noqa: BLE001 — lowering trouble is not a donation bug
        return []
    if any_donated:
        return []
    return [
        Finding(
            path=entry.path,
            line=entry.line,
            col=0,
            rule=RULE,
            message=(
                f"[{entry.name}] output {tuple(candidates[0].shape)}"
                f"/{candidates[0].dtype} aliases an input buffer of "
                f">= {_DONATE_BYTES} bytes but no argument is donated; "
                "pass donate_argnums so XLA reuses the input allocation "
                "instead of doubling HBM for the output"
            ),
        )
    ]
