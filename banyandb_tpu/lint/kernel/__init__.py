"""bdjit: whole-program kernel audit — the third analysis family on the
bdlint engine (docs/linting.md "Kernel audit").

The fused whole-plan executor and device-side decode (ROADMAP items 2-3)
are ratcheted against *countable, compile-time* properties of our
kernels: how many jitted dispatches a plan costs, what crosses the
PCIe/ICI bus, and which dtypes ride the device.  Four analyzers state
those properties statically — everything runs through ``jax.make_jaxpr``
and ``jit(...).lower()`` on the CPU backend with **zero device kernel
execution**:

- ``kernel-jaxpr``     walk every audited kernel's closed jaxpr: host
                       callbacks (``pure_callback``/``io_callback``/
                       ``debug_print``), 64-bit dtypes anywhere inside a
                       device plan, accumulator-narrowing conversions
                       (f32 -> f16/bf16), and large output buffers that
                       alias an input without ``donate_argnums``
- ``kernel-dispatch``  drive the real executor entry paths
                       (measure_exec.compute_partials,
                       stream_exec.device_tag_mask, ql_exec trace/
                       property) under an instrumented stub device and
                       count jitted dispatches + device_get/device_put
                       transfers per builtin plan signature — also
                       proving the executor resolves EXACTLY the
                       signature the precompile registry warms
- ``kernel-lowering``  ``lower(...).compile()`` per signature on CPU:
                       fused-computation count, bytes-accessed estimate
                       (cost_analysis) and collective count — including
                       the shard_map mesh variant from parallel/dist_exec
- ``kernel-budget``    the checked-in per-signature budget table
                       (kernel_budgets.BUDGETS) enforced with the same
                       ratchet discipline as the layering baseline:
                       regressions fail, improvements fail the now-stale
                       entry until it is tightened

Findings carry witness chains (signature -> jaxpr eqn / HLO measure) and
anchor at the kernel builder's source line, so they flow through the
bdlint suppression and SARIF machinery unchanged.
"""

from __future__ import annotations

from typing import Optional

# (name, summary) catalog for --list-rules / the SARIF driver rules.
KERNEL_RULES = (
    ("kernel-jaxpr", "host callback / 64-bit dtype / narrowing inside a kernel"),
    ("kernel-dispatch", "dispatch+transfer count per plan signature (stub device)"),
    ("kernel-lowering", "HLO fusion/bytes/collective audit per signature"),
    ("kernel-budget", "ratcheted per-signature dispatch/transfer/dtype budgets"),
)


def kernel_entries():
    """The audited kernel matrix: the plan_audit entries (ONE list feeds
    eval_shape contracts, precompile warming and this audit) plus the
    shard_map mesh-variant step from parallel/dist_exec."""
    from banyandb_tpu.lint.whole_program.plan_audit import default_entries

    from banyandb_tpu.lint.kernel.lowering import fused_mesh_entry, mesh_entry

    return list(default_entries()) + [mesh_entry(), fused_mesh_entry()]


def stored_entries(registry=None, limit: int = 16):
    """Audit entries for the top stored/recorded plan signatures — the
    live population the precompile registry warms beyond the builtin
    matrix.  Empty in a fresh lint process (no store bound); in an
    embedded run (server, bench) the hottest production signatures get
    the same jaxpr audit the builtins do.  These are *dynamic*: they
    carry no checked-in budget rows, so they are jaxpr-audited only."""
    import inspect

    import jax
    import jax.numpy as jnp

    from banyandb_tpu.lint.whole_program.plan_audit import (
        KernelAudit,
        _rel_path,
    )
    from banyandb_tpu.query import (
        fused_exec,
        measure_exec,
        precompile,
        stream_exec,
    )

    if registry is None:
        registry = precompile.default_registry()
    S = jax.ShapeDtypeStruct
    entries = []
    for i, (kind, spec) in enumerate(registry.signatures()[:limit]):
        try:
            if kind == "measure":
                mod = measure_exec
                fn = measure_exec._build_kernel(spec)
                args = (
                    precompile.chunk_struct(spec),
                    precompile.pred_struct(spec),
                    S((), jnp.float32),
                    S((), jnp.float32),
                )
                anchor = measure_exec._build_kernel
            elif kind == "fused":
                mod = fused_exec
                fn = fused_exec._build_kernel(spec)
                args = (
                    precompile.fused_chunk_struct(spec),
                    precompile.pred_struct(spec.plan),
                    S((), jnp.float32),
                    S((), jnp.float32),
                )
                anchor = fused_exec._build_kernel
            elif kind == "stream_mask":
                mod = stream_exec
                fn = stream_exec._build_kernel(spec)
                args = precompile.mask_structs(spec)
                anchor = stream_exec._build_kernel
            else:
                continue
        except Exception:  # noqa: BLE001 — a stale stored signature is
            continue  # skipped here exactly like warming skips it
        entries.append(
            KernelAudit(
                name=f"stored/{kind}#{i}",
                path=_rel_path(inspect.getsourcefile(mod)),
                line=inspect.getsourcelines(anchor)[1],
                fn=fn,
                args=args,
                cache_key=spec,
            )
        )
    return entries


def run_kernel_audit(fast: bool = False) -> list:
    """Run the kernel analyzers -> findings (empty = budgets hold).

    ``fast=True`` skips the lowering-audit (XLA compiles dominate the
    runtime; jaxpr + dispatch + their budget columns still run).
    """
    from banyandb_tpu.lint.kernel import dispatch, jaxpr_audit, kernel_budgets

    entries = kernel_entries()
    findings = []
    anchors = {e.name: (e.path, e.line) for e in entries}
    # signatures whose measurement itself failed: they already carry a
    # failure finding and must NOT be judged against the budget table (a
    # widest=0 / absent row would cascade into misleading "tighten" /
    # "stale" guidance)
    failed: set[str] = set()
    measured_widest: dict[str, int] = {}
    for entry in entries:
        fs, widest = jaxpr_audit.audit_entry(entry)
        findings += fs
        if widest > 0:
            measured_widest[entry.name] = widest
        else:
            failed.add(entry.name)
    for entry in stored_entries():
        # dynamic (recorded) signatures: jaxpr invariants only — no
        # checked-in budget row to ratchet against
        fs, _widest = jaxpr_audit.audit_entry(entry)
        findings += fs
    traces = dispatch.audit_dispatch()
    findings += dispatch.dispatch_findings(traces)
    failed |= {t.name for t in traces.values() if t.error}
    anchors.update(
        {t.name: (t.path, t.line) for t in traces.values() if t.path}
    )
    lowered = None
    if not fast:
        from banyandb_tpu.lint.kernel import lowering

        lowered = {}
        for entry in entries:
            fs, meas = lowering.audit_entry(entry)
            findings += fs
            lowered[entry.name] = meas
            if meas is None:
                failed.add(entry.name)
    findings += kernel_budgets.audit_budgets(
        widest=measured_widest,
        traces=traces,
        lowered=lowered,
        anchors=anchors,
        failed=failed,
    )
    return findings


def kernel_stats(fast: bool = False) -> dict:
    """Summary keys folded into the CLI run stats."""
    from banyandb_tpu.lint.kernel.kernel_budgets import BUDGETS

    return {
        "kernel_signatures": len(BUDGETS),
        "kernel_lowering": not fast,
    }
