"""kernel-budget: the checked-in per-signature kernel budget table.

This file IS the performance contract the kernel audit enforces — the
same role layer_config.py plays for the import graph.  One row per
audited signature; every column is a *measured* compile-time fact
(dispatch.py / jaxpr_audit.py / lowering.py) ratcheted with the shared
lint/core mechanics:

- measured **above** budget  -> regression finding, CI fails;
- measured **below** budget  -> the entry is stale (too loose) and fails
  until tightened, so an improvement — e.g. the fused whole-plan
  executor driving dispatches toward 1 per part-batch, or device-side
  decode shrinking bytes_class — is locked in the moment it lands.

Columns (None = not measured for that row's kind):

- ``dispatches``/``gets``/``puts``: jitted dispatches, batched
  device_get transfers, and host->device array ships per scenario run
  (dispatch.py's stub device; measure/stream scenarios are one
  part-batch = one scan chunk).  The ql rows pin the trace/property
  executors to ZERO device work.
- ``widest``: widest dtype itemsize anywhere in the jaxpr (4 = the
  32-bit device contract; 8 would mean a 64-bit leak).
- ``bytes_class``/``fusion_class``: power-of-two class
  (``int.bit_length``) of the compiled HLO bytes-accessed estimate and
  fused-computation count — classes absorb XLA point-release noise,
  real regressions land in the next class.
- ``collectives``: collective ops in the lowered module; single-device
  plan kernels carry none, the parallel/dist-step mesh variant carries
  exactly its psum(count/sums) + pmin/pmax set.

Legitimately changing a row: land the kernel change, run
``python -m banyandb_tpu.lint --check`` (or scripts/kernel_smoke.py),
and copy the measured value the failure reports into the row — tighter
is always allowed, looser must be argued in review like any baseline
growth (docs/linting.md "Kernel audit").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from banyandb_tpu.lint.core import (
    Finding,
    ratchet_value,
    stale_entry_finding,
)

RULE = "kernel-budget"


@dataclass(frozen=True)
class KernelBudget:
    """One signature's budget row (None = column not measured)."""

    dispatches: Optional[int] = None
    gets: Optional[int] = None
    puts: Optional[int] = None
    widest: Optional[int] = None
    bytes_class: Optional[int] = None
    fusion_class: Optional[int] = None
    collectives: Optional[int] = None


def _b(dispatches=None, gets=None, puts=None, widest=None,
       bytes_class=None, fusion_class=None, collectives=None):
    return KernelBudget(dispatches, gets, puts, widest,
                        bytes_class, fusion_class, collectives)


# fmt: off
BUDGETS: dict[str, KernelBudget] = {
    # the builtin measure plan matrix: 1 dispatch + 1 batched get per
    # scan chunk; puts = padded chunk columns + traced predicate arrays.
    # columns: (dispatches, gets, puts, widest, bytes_class,
    #           fusion_class, collectives)
    "measure/flat-count":      _b(1, 1, 5, 4, 19, 3, 0),
    "measure/group-eq-lut":    _b(1, 1, 8, 4, 22, 4, 0),
    "measure/percentile-hist": _b(1, 1, 6, 4, 24, 4, 0),
    "measure/or-expr":         _b(1, 1, 7, 4, 20, 3, 0),
    "measure/topn-dashboard":  _b(1, 1, 7, 4, 22, 4, 0),
    # fused whole-plan twins (query/fused_exec): ONE dispatch + ONE
    # batched get per part-batch regardless of chunk count — the
    # executor's raison d'être, ratcheted so staging can never creep
    # back; puts stay the staged column count (stacked ships).
    "fused/flat-count":        _b(1, 1, 5, 4, 19, 4, 0),
    "fused/group-eq-lut":      _b(1, 1, 8, 4, 22, 5, 0),
    "fused/percentile-hist":   _b(1, 1, 6, 4, 24, 4, 0),
    "fused/or-expr":           _b(1, 1, 7, 4, 20, 3, 0),
    "fused/topn-dashboard":    _b(1, 1, 7, 4, 23, 5, 0),
    # the staging tripwire: a 2-chunk part-batch, still 1 dispatch/get
    # (dispatch columns only: the bucket is synthesized per run, so it
    # has no standing jaxpr/lowering entry)
    "fused/multi-chunk":       _b(1, 1, 5),
    # device-side decode twins (BYDB_DEVICE_DECODE=1, ROADMAP item 3):
    # the compressed ship form — narrow local codes + [S, L] remap LUTs
    # + src-ordinals + narrow int fields — STILL costs exactly one
    # dispatch and one batched get (decode fuses into the plan program);
    # puts grow by the LUT/ordinal ships, bytes_class is pinned so the
    # in-program decode can never double the traffic class, and
    # widest=4 proves the i8->i32 widen never leaks 64-bit
    "fused+decode/flat-count":      _b(1, 1, 5, 4, 19, 4, 0),
    "fused+decode/group-eq-lut":    _b(1, 1, 11, 4, 22, 5, 0),
    "fused+decode/percentile-hist": _b(1, 1, 8, 4, 24, 4, 0),
    "fused+decode/or-expr":         _b(1, 1, 9, 4, 20, 3, 0),
    "fused+decode/topn-dashboard":  _b(1, 1, 10, 4, 23, 5, 0),
    # compressed multi-chunk tripwire: staging AND decode-stage
    # de-fusion both show up here first
    "fused+decode/multi-chunk":     _b(1, 1, 5),
    # fused chunked-scan mesh step: the whole distributed scan as one
    # collective program, SAME psum(count/sums)+pmin+pmax set
    "fused/dist-step":         _b(widest=4, bytes_class=16, fusion_class=4, collectives=4),
    # stream retrieval mask: whole bool mask in one get
    "stream/mask-eq-in":       _b(1, 1, 3, 4, 19, 1, 0),
    # the narrow-ship twin (i8 source codes widened on device): same
    # dispatch/transfer shape — an extra put here means the stream
    # decode stage de-fused (dispatch columns only: the narrow form has
    # no standing jaxpr/lowering entry)
    "stream+decode/mask-eq-in": _b(1, 1, 3),
    # shared ops reductions every plan lowers onto (no executor path of
    # their own: jaxpr + lowering columns only)
    "ops/group_reduce":        _b(widest=4, bytes_class=24, fusion_class=3, collectives=0),
    "ops/group_histogram":     _b(widest=4, bytes_class=20, fusion_class=2, collectives=0),
    # shard_map mesh step: psum(count)+psum(sums)+pmin+pmax = 4
    # collectives (the hist/topn outputs reduce over already-combined
    # vectors)
    "parallel/dist-step":      _b(widest=4, bytes_class=16, fusion_class=4, collectives=4),
    # ql trace/property executors are host-only BY CONTRACT: zero
    # dispatches, zero transfers — a device leg appearing here is a bug
    "ql/trace":                _b(0, 0, 0),
    "ql/property":             _b(0, 0, 0),
}
# fmt: on


def budget_path() -> str:
    from banyandb_tpu.lint.whole_program.plan_audit import _rel_path

    return _rel_path(__file__)


def audit_budgets(
    widest: Optional[dict] = None,
    traces: Optional[dict] = None,
    lowered: Optional[dict] = None,
    budgets: Optional[dict] = None,
    anchors: Optional[dict] = None,
    failed: Optional[set] = None,
) -> list[Finding]:
    """Compare measured columns against the budget table.

    Any argument left None means that analyzer did not run (``--fast``
    skips lowering) and its columns are not judged.  Row-set agreement
    is judged from the measurements that DID run: a measured signature
    with no row fails (new kernels ship with a budget), a row no
    measurement covers fails as stale.  ``failed`` names signatures
    whose measurement itself errored — they already carry a failure
    finding and are excluded from both the column ratchet and the
    stale-row check (a failed measurement is not an improvement).
    """
    from banyandb_tpu.lint.kernel.dispatch import measured_columns

    budgets = BUDGETS if budgets is None else budgets
    bpath = budget_path()
    anchors = anchors or {}
    failed = failed or set()

    measured: dict[str, dict] = {}
    for name, w in (widest or {}).items():
        measured.setdefault(name, {})["widest"] = w
    for name, t in (traces or {}).items():
        if not t.error:
            measured.setdefault(name, {}).update(measured_columns(t))
    for name, cols in (lowered or {}).items():
        if cols is not None:
            measured.setdefault(name, {}).update(cols)
    for name in failed:
        measured.pop(name, None)

    findings: list[Finding] = []
    for name in sorted(set(measured) - set(budgets)):
        findings.append(
            Finding(
                path=anchors.get(name, (bpath, 1))[0],
                line=anchors.get(name, (bpath, 1))[1],
                col=0,
                rule=RULE,
                message=(
                    f"[{name}] audited signature has no budget row; add "
                    "one to lint/kernel/kernel_budgets.py with the "
                    "measured values (the table is total)"
                ),
            )
        )
    for key in sorted(set(budgets) - set(measured) - failed):
        findings.append(
            stale_entry_finding(
                key, rule=RULE, path=bpath, what="the audited signature"
            )
        )

    for name in sorted(measured):
        row = budgets.get(name)
        if row is None:
            continue  # already reported above
        cols = measured[name]
        path, line = anchors.get(name, (bpath, 1))
        for column, value in sorted(cols.items()):
            budget = getattr(row, column)
            if budget is None:
                continue
            findings += ratchet_value(
                name,
                column,
                value,
                budget,
                rule=RULE,
                path=path,
                line=line,
                budget_path=bpath,
                regression_hint=_HINTS.get(column, ""),
            )
    return findings


_HINTS = {
    "dispatches": (
        "every extra dispatch is a host round-trip per chunk; the fused "
        "executor (ROADMAP item 2) must drive this DOWN, never up"
    ),
    "gets": "result transfers must stay batched (one device_get per chunk)",
    "puts": (
        "extra host->device ships grow the pad/ship stage device-side "
        "decode (ROADMAP item 3) is meant to shrink"
    ),
    "widest": "64-bit values double HBM traffic; keep device math 32-bit",
    "bytes_class": (
        "the scan is decode-throughput-bound: bytes moved per query "
        "doubled a class"
    ),
    "fusion_class": (
        "XLA stopped fusing a stage — new materialized temporaries"
    ),
    "collectives": "the cross-shard combine plan changed",
}


# -- obs-plane export --------------------------------------------------------


def publish_to_meter(meter=None) -> int:
    """Export the static dispatch budgets as gauges
    (``kernel_dispatch_budget{signature=...}``) so the obs plane can be
    cross-checked against the prediction (scripts/obs_smoke.py asserts
    observed device_execute spans per query <= this budget).  -> rows
    published."""
    if meter is None:
        from banyandb_tpu.obs import global_meter

        meter = global_meter()
    n = 0
    for name, row in sorted(BUDGETS.items()):
        if row.dispatches is None:
            continue
        meter.gauge_set(
            "kernel_dispatch_budget",
            float(row.dispatches),
            labels={"signature": name},
        )
        n += 1
    return n


def dispatch_budget(kind: str = "measure") -> int:
    """The static per-part-batch dispatch budget for a signature family
    (max over its rows): the bound runtime ``device_execute`` span
    counts are asserted against."""
    vals = [
        row.dispatches
        for name, row in BUDGETS.items()
        if name.startswith(kind + "/") and row.dispatches is not None
    ]
    if not vals:
        raise KeyError(f"no dispatch budgets for kind {kind!r}")
    return max(vals)
