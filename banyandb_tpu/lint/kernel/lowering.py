"""kernel-lowering: what XLA actually makes of each plan signature.

``jit(fn).lower(...)`` + ``.compile()`` on the CPU backend — still zero
device kernel execution (nothing is dispatched) — yields three countable
facts per signature:

- **fusions**: fused computations in the optimized HLO.  A fusion-count
  jump means the compiler stopped fusing a stage (new materialized
  temporaries, more HBM round-trips on a real chip).
- **bytes accessed**: the compiler's traffic estimate
  (``cost_analysis()``).  The decode-throughput law (PAPERS.md
  2606.22423) says scans are bound by exactly this number; device-side
  decode (ROADMAP item 3) must shrink it by the compression ratio.
- **collectives**: all-reduce/all-gather/… ops in the lowered module.
  Single-device plan kernels must carry none; the shard_map mesh step
  (parallel/dist_exec, the SNIPPETS.md sharding pattern) carries exactly
  its psum/pmin/pmax set, and a change means the cross-shard combine
  plan changed.

Bytes and fusion counts ride the budget table as power-of-two *classes*
(``int.bit_length``) so an XLA point release moving an estimate a few
percent does not churn the ratchet, while a real regression — 2x the
traffic, a lost fusion pass — lands in the next class and fails.
"""

from __future__ import annotations

from typing import Optional

from banyandb_tpu.lint.core import Finding

RULE = "kernel-lowering"

_COLLECTIVE_TOKENS = (
    "all_reduce",
    "all-reduce",
    "all_gather",
    "all-gather",
    "all_to_all",
    "all-to-all",
    "collective_permute",
    "collective-permute",
    "reduce_scatter",
    "reduce-scatter",
)


def mesh_entry():
    """The shard_map mesh-variant audit entry: one representative
    distributed step (grouped sum/min/max + top-N over a ('shard','seg')
    mesh) lowered over a single CPU device — the collective *structure*
    (psum/pmin/pmax per output) is identical at any mesh size."""
    import inspect
    from functools import partial

    import jax
    import jax.numpy as jnp

    from banyandb_tpu.lint.whole_program.plan_audit import (
        KernelAudit,
        _rel_path,
    )
    from banyandb_tpu.parallel import dist_exec
    from banyandb_tpu.parallel import mesh as pmesh

    plan = dist_exec.DistPlan(
        tags_code=("svc",),
        fields=("v",),
        group_tags=("svc",),
        radices=(16,),
        num_groups=16,
        topn=4,
    )
    mesh = pmesh.make_mesh(1)
    if hasattr(jax, "shard_map"):
        _shard_map = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as _shard_map
    from jax.sharding import PartitionSpec as P

    data_spec = P(("shard", "seg"))
    step = _shard_map(
        partial(dist_exec._step, plan),
        mesh=mesh,
        in_specs=(
            {
                "valid": data_spec,
                "tags": {"svc": data_spec},
                "fields": {"v": data_spec},
            },
            {},
            P(),
            P(),
        ),
        out_specs=dist_exec._out_specs(plan),
    )
    S = jax.ShapeDtypeStruct
    n = 1024
    return KernelAudit(
        name="parallel/dist-step",
        path=_rel_path(inspect.getsourcefile(dist_exec)),
        line=inspect.getsourcelines(dist_exec._step)[1],
        fn=jax.jit(step),
        args=(
            {
                "valid": S((1, n), jnp.bool_),
                "tags": {"svc": S((1, n), jnp.int32)},
                "fields": {"v": S((1, n), jnp.float32)},
            },
            {},
            S((), jnp.float32),
            S((), jnp.float32),
        ),
    )


def fused_mesh_entry():
    """The fused chunked-scan collective program
    (query/fused_exec.build_fused_dist_step) at a 2-chunk bucket,
    lowered over a single CPU device: the whole distributed scan is ONE
    program carrying exactly the staged mesh step's psum/pmin/pmax set —
    a collective-count change here means the fused path altered the
    cross-shard combine plan."""
    import inspect
    from functools import partial

    import jax
    import jax.numpy as jnp

    from banyandb_tpu.lint.whole_program.plan_audit import (
        KernelAudit,
        _rel_path,
    )
    from banyandb_tpu.parallel import dist_exec
    from banyandb_tpu.parallel import mesh as pmesh
    from banyandb_tpu.query import fused_exec

    plan = dist_exec.DistPlan(
        tags_code=("svc",),
        fields=("v",),
        group_tags=("svc",),
        radices=(16,),
        num_groups=16,
        topn=4,
    )
    num_chunks = 2
    mesh = pmesh.make_mesh(1)
    if hasattr(jax, "shard_map"):
        _shard_map = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as _shard_map
    from jax.sharding import PartitionSpec as P

    data_spec = P(("shard", "seg"))
    step = _shard_map(
        partial(fused_exec._fused_dist_step, plan, num_chunks),
        mesh=mesh,
        in_specs=(
            {
                "valid": data_spec,
                "tags": {"svc": data_spec},
                "fields": {"v": data_spec},
            },
            {},
            P(),
            P(),
        ),
        out_specs=dist_exec._out_specs(plan),
    )
    S = jax.ShapeDtypeStruct
    n = num_chunks * 1024
    return KernelAudit(
        name="fused/dist-step",
        path=_rel_path(inspect.getsourcefile(fused_exec)),
        line=inspect.getsourcelines(fused_exec._fused_dist_step)[1],
        fn=jax.jit(step),
        args=(
            {
                "valid": S((1, n), jnp.bool_),
                "tags": {"svc": S((1, n), jnp.int32)},
                "fields": {"v": S((1, n), jnp.float32)},
            },
            {},
            S((), jnp.float32),
            S((), jnp.float32),
        ),
    )


def lower_entry(entry):
    """-> (lowered, compiled) for one audit entry, CPU backend."""
    import jax

    fn = entry.fn if hasattr(entry.fn, "lower") else jax.jit(entry.fn)
    lowered = fn.lower(*entry.args, **entry.kwargs)
    return lowered, lowered.compile()


def audit_entry(entry) -> tuple[list[Finding], Optional[dict]]:
    """-> (findings, measured columns) for one signature.

    Measured columns: ``collectives`` (lowered module), ``fusion_class``
    and ``bytes_class`` (compiled module / cost analysis) — ratcheted by
    kernel_budgets.BUDGETS.
    """
    findings: list[Finding] = []
    try:
        lowered, compiled = lower_entry(entry)
        lowered_text = lowered.as_text()
        compiled_text = compiled.as_text()
        cost = compiled.cost_analysis()
    except Exception as e:  # noqa: BLE001 — the finding IS the report
        findings.append(
            Finding(
                path=entry.path,
                line=entry.line,
                col=0,
                rule=RULE,
                message=(
                    f"[{entry.name}] lowering/compile failed on the CPU "
                    f"backend: {type(e).__name__}: {e}"
                ),
            )
        )
        return findings, None

    collectives = sum(lowered_text.count(t) for t in _COLLECTIVE_TOKENS)
    fusions = compiled_text.count("fusion(")
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    bytes_accessed = int(cost.get("bytes accessed", 0.0)) if cost else 0
    return findings, {
        "collectives": collectives,
        "fusion_class": fusions.bit_length(),
        "bytes_class": bytes_accessed.bit_length(),
    }
