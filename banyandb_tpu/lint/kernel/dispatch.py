"""kernel-dispatch: count dispatches and transfers per plan signature by
driving the REAL executor entry paths under an instrumented stub device.

Zero device kernel execution: the per-signature kernel builders
(``measure_exec._build_kernel`` / ``stream_exec._build_kernel``) are
swapped for stubs that count the dispatch, derive the output pytree with
``jax.eval_shape`` (a pure trace) and return host zeros; ``jax.device_get``
and ``jnp.asarray`` are wrapped with counting pass-throughs.  Everything
else — gather, dedup, plan-signature resolution, the chunk loop, the
prefetch pipeline — is the production code path, so the measured counts
are the counts a real query pays:

- **dispatches**  jitted kernel invocations (the fused executor's
                  ROADMAP done-bar drives this to 1 per part-batch)
- **gets**        ``jax.device_get`` transfers (result boundaries)
- **puts**        ``jnp.asarray`` host->device array ships (pad/ship)

Each measure/stream scenario is synthesized so the executor resolves
EXACTLY the builtin precompile signature (dict sizes pin the radices,
row counts pin the scan bucket) — signature drift between what
production queries compile and what the registry warms/audits is itself
a finding.  The ql trace/property executors are host-only by design:
their budget is zero dispatches, zero transfers.

The per-scenario counts are ratcheted by kernel_budgets.BUDGETS.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from dataclasses import dataclass
from types import SimpleNamespace
from typing import Callable, Optional

import numpy as np

from banyandb_tpu.lint.core import Finding

RULE = "kernel-dispatch"

T0 = 1_700_000_000_000


class Counters:
    """Event sinks for the stub device (list appends are GIL-atomic: the
    prefetch worker ships chunks while the main thread dispatches).
    Counting can be suspended per-thread while the stub eval_shapes the
    real kernel (tracing must not count as transfer work)."""

    def __init__(self):
        self.dispatches: list[tuple[str, object]] = []  # (kind, spec)
        self.gets: list[int] = []
        self.puts: list[int] = []
        self._local = threading.local()

    def active(self) -> bool:
        return not getattr(self._local, "off", False)

    @contextlib.contextmanager
    def suspended(self):
        self._local.off = True
        try:
            yield
        finally:
            self._local.off = False


@dataclass(frozen=True)
class DispatchTrace:
    """Measured dispatch/transfer profile of one scenario."""

    name: str
    kind: str  # measure | stream | ql
    dispatches: int
    gets: int
    puts: int
    specs: tuple  # plan signatures the executor actually resolved
    builtin: object = None  # the precompile-registry signature expected
    path: str = ""
    line: int = 1
    error: str = ""


def _stub_builder(real_build: Callable, counters: Counters, kind: str):
    """A kernel builder whose kernels count dispatches and return host
    zeros shaped by eval_shape of the real kernel (no XLA compile)."""

    def build(spec):
        real = real_build(spec)
        state: dict = {}

        def stub(*args):
            import jax

            counters.dispatches.append((kind, spec))
            if "out" not in state:
                with counters.suspended():
                    state["out"] = jax.eval_shape(real, *args)
            return jax.tree_util.tree_map(
                lambda s: np.zeros(s.shape, s.dtype), state["out"]
            )

        return stub

    return build


@contextlib.contextmanager
def stub_device():
    """Patch the executors onto the stub device; yields the Counters.

    Scoped and restoring: kernel caches and the precompile registry are
    swapped for throwaways so the audit never pollutes process state,
    and the jax-level wrappers are counting pass-throughs (behavior
    preserved for any concurrent user).
    """
    import jax
    import jax.numpy as jnp

    from banyandb_tpu.query import (
        fused_exec,
        measure_exec,
        precompile,
        stream_exec,
    )

    counters = Counters()
    real_get = jax.device_get
    real_asarray = jnp.asarray

    def counting_get(x):
        if counters.active():
            counters.gets.append(1)
        return real_get(x)

    def counting_asarray(a, *args, **kwargs):
        if counters.active():
            counters.puts.append(1)
        return real_asarray(a, *args, **kwargs)

    saved = (
        measure_exec._KERNEL_CACHE,
        measure_exec._build_kernel,
        stream_exec._KERNEL_CACHE,
        stream_exec._build_kernel,
        fused_exec._KERNEL_CACHE,
        fused_exec._build_kernel,
        precompile.default_registry,
    )
    throwaway = precompile.PrecompileRegistry()
    try:
        measure_exec._KERNEL_CACHE = {}
        measure_exec._build_kernel = _stub_builder(
            saved[1], counters, "measure"
        )
        stream_exec._KERNEL_CACHE = {}
        stream_exec._build_kernel = _stub_builder(
            saved[3], counters, "stream_mask"
        )
        fused_exec._KERNEL_CACHE = {}
        fused_exec._build_kernel = _stub_builder(
            saved[5], counters, "fused"
        )
        precompile.default_registry = lambda: throwaway
        jax.device_get = counting_get
        jnp.asarray = counting_asarray
        yield counters
    finally:
        jax.device_get = real_get
        jnp.asarray = real_asarray
        (
            measure_exec._KERNEL_CACHE,
            measure_exec._build_kernel,
            stream_exec._KERNEL_CACHE,
            stream_exec._build_kernel,
            fused_exec._KERNEL_CACHE,
            fused_exec._build_kernel,
            precompile.default_registry,
        ) = saved


# -- scenario synthesis ------------------------------------------------------


def _int_bytes(i: int) -> bytes:
    return i.to_bytes(8, "little", signed=True)


def _source(n: int, step: int, tags: dict, fields: dict):
    """One synthetic ColumnData: distinct (series, ts) per row so version
    dedup keeps every row, dictionaries sized exactly to pin radices."""
    from banyandb_tpu.storage.part import ColumnData

    return ColumnData(
        ts=T0 + np.arange(n, dtype=np.int64) * step,
        series=np.arange(n, dtype=np.int64) % 64,
        version=np.ones(n, dtype=np.int64),
        tags={t: codes for t, (_vals, codes) in tags.items()},
        fields={f: a for f, a in fields.items()},
        dicts={t: vals for t, (vals, _codes) in tags.items()},
    )


def _measure_schema(tags, fields):
    from banyandb_tpu.api.schema import (
        Entity,
        FieldSpec,
        Measure,
        TagSpec,
    )

    return Measure(
        group="g",
        name="m",
        tags=tuple(TagSpec(n, t) for n, t in tags),
        fields=tuple(FieldSpec(n, t) for n, t in fields),
        entity=Entity((tags[0][0],)),
    )


def _measure_scenarios():
    """(name, builtin PlanSpec, runner) per builtin measure plan.  Each
    runner drives compute_partials so the resolved PlanSpec must equal
    the precompile registry's builtin signature."""
    from banyandb_tpu.api.model import (
        Aggregation,
        Condition,
        GroupBy,
        LogicalExpression,
        QueryRequest,
        TimeRange,
        Top,
    )
    from banyandb_tpu.api.schema import FieldType, TagType
    from banyandb_tpu.query import precompile
    from banyandb_tpu.query.measure_exec import compute_partials

    builtins = dict(precompile.builtin_plans())
    rng = np.random.default_rng(7)

    def svc_dict(k: int):
        vals = [b"s%04d" % i for i in range(k)]
        return vals

    def run_flat():
        n = 8192
        m = _measure_schema(
            [("svc", TagType.STRING)], [("v", FieldType.INT)]
        )
        src = _source(
            n,
            1,
            {"svc": (svc_dict(4), rng.integers(0, 4, n).astype(np.int32))},
            {"v": rng.integers(0, 100, n).astype(np.float64)},
        )
        req = QueryRequest(
            ("g",), "m", TimeRange(T0, T0 + n), field_projection=("v",)
        )
        compute_partials(m, req, [src])

    def run_grouped():
        n = 8192
        m = _measure_schema(
            [("svc", TagType.STRING), ("region", TagType.INT)],
            [("v", FieldType.INT)],
        )
        src = _source(
            n,
            1,
            {
                "svc": (svc_dict(8), rng.integers(0, 8, n).astype(np.int32)),
                "region": (
                    [_int_bytes(i) for i in range(4)],
                    rng.integers(0, 4, n).astype(np.int32),
                ),
            },
            {"v": rng.integers(0, 100, n).astype(np.float64)},
        )
        req = QueryRequest(
            ("g",),
            "m",
            TimeRange(T0, T0 + n),
            criteria=LogicalExpression(
                "and",
                Condition("svc", "eq", "s0003"),
                Condition("region", "le", 2),
            ),
            group_by=GroupBy(("svc", "region")),
            field_projection=("v",),
        )
        compute_partials(m, req, [src])

    def run_pct():
        n = 65536
        # ts span > 2^31 ms: long-range percentile dashboards run with
        # scan-order tracking off (int32 offsets would wrap), which is
        # exactly the builtin percentile-hist signature shape
        step = 32769
        m = _measure_schema(
            [("svc", TagType.STRING)], [("lat", FieldType.FLOAT)]
        )
        src = _source(
            n,
            step,
            {"svc": (svc_dict(16), rng.integers(0, 16, n).astype(np.int32))},
            {"lat": rng.random(n).astype(np.float64) * 100},
        )
        req = QueryRequest(
            ("g",),
            "m",
            TimeRange(T0, T0 + n * step + 1),
            group_by=GroupBy(("svc",)),
            agg=Aggregation("percentile", "lat", quantiles=(0.5,)),
        )
        compute_partials(m, req, [src])

    def run_or():
        n = 8192
        m = _measure_schema(
            [("svc", TagType.STRING)], [("v", FieldType.INT)]
        )
        src = _source(
            n,
            1,
            {"svc": (svc_dict(8), rng.integers(0, 8, n).astype(np.int32))},
            {"v": rng.integers(0, 100, n).astype(np.float64)},
        )
        req = QueryRequest(
            ("g",),
            "m",
            TimeRange(T0, T0 + n),
            criteria=LogicalExpression(
                "or",
                Condition(
                    "svc", "in", ("s0000", "s0001", "s0002", "s0003")
                ),
                Condition("svc", "eq", "s0000"),
            ),
            agg=Aggregation("sum", "v"),
        )
        compute_partials(m, req, [src])

    def run_topn():
        n = 65536
        m = _measure_schema(
            [("svc", TagType.STRING), ("region", TagType.STRING)],
            [("value", FieldType.INT)],
        )
        src = _source(
            n,
            1,
            {
                "svc": (
                    svc_dict(1024),
                    rng.integers(0, 1024, n).astype(np.int32),
                ),
                "region": (
                    [b"r%d" % i for i in range(8)],
                    rng.integers(0, 8, n).astype(np.int32),
                ),
            },
            {"value": rng.integers(0, 100, n).astype(np.float64)},
        )
        req = QueryRequest(
            ("g",),
            "m",
            TimeRange(T0, T0 + n),
            criteria=Condition("region", "ne", "r0"),
            group_by=GroupBy(("svc",)),
            top=Top(10, "value"),
        )
        compute_partials(m, req, [src])

    return [
        ("measure/flat-count", builtins["measure/flat-count"], run_flat),
        ("measure/group-eq-lut", builtins["measure/group-eq-lut"], run_grouped),
        ("measure/percentile-hist", builtins["measure/percentile-hist"], run_pct),
        ("measure/or-expr", builtins["measure/or-expr"], run_or),
        ("measure/topn-dashboard", builtins["measure/topn-dashboard"], run_topn),
    ]


def _multichunk_scenario():
    """fused/multi-chunk: a part-batch spanning SEVERAL scan chunks must
    still cost exactly ONE dispatch and ONE batched get on the fused
    path — the tripwire that fails CI the moment per-chunk staging
    creeps back into the fused executor."""
    from banyandb_tpu.api.model import QueryRequest, TimeRange
    from banyandb_tpu.api.schema import FieldType, TagType

    def run():
        from banyandb_tpu.query import measure_exec
        from banyandb_tpu.query.measure_exec import compute_partials

        n = 8192
        rng = np.random.default_rng(5)
        m = _measure_schema(
            [("svc", TagType.STRING)], [("v", FieldType.INT)]
        )
        src = _source(
            n,
            1,
            {
                "svc": (
                    [b"s%04d" % i for i in range(4)],
                    rng.integers(0, 4, n).astype(np.int32),
                )
            },
            {"v": rng.integers(0, 100, n).astype(np.float64)},
        )
        req = QueryRequest(
            ("g",), "m", TimeRange(T0, T0 + n), field_projection=("v",)
        )
        saved = measure_exec.SCAN_CHUNK
        measure_exec.SCAN_CHUNK = 4096  # n=8192 -> a 2-chunk part-batch
        try:
            compute_partials(m, req, [src])
        finally:
            measure_exec.SCAN_CHUNK = saved

    return run


def _stream_scenario(narrow: bool = False):
    """The stream retrieval-mask scenario; ``narrow=True`` feeds the
    source at stored i8 code width (the device-decode read path,
    models/stream narrow_codes) so the widen-on-device ship form is
    budget-audited alongside the dense one."""
    from banyandb_tpu.api.model import Condition
    from banyandb_tpu.query import precompile, stream_exec

    builtin = dict(precompile.builtin_masks())["stream/mask-eq-in"]
    code_dtype = np.int8 if narrow else np.int32

    def run():
        n = 32768
        rng = np.random.default_rng(9)
        src = _source(
            n,
            1,
            {
                "svc": (
                    [b"a", b"b"],
                    rng.integers(0, 2, n).astype(code_dtype),
                ),
                "region": (
                    [b"r0", b"r1", b"r2", b"r3"],
                    rng.integers(0, 4, n).astype(code_dtype),
                ),
            },
            {},
        )
        conds = [
            Condition("svc", "eq", "a"),
            Condition("region", "in", ("r0", "r1", "r2", "r3")),
        ]
        mask = stream_exec.device_tag_mask(src, conds)
        assert mask is not None and mask.shape == (n,)

    name = "stream+decode/mask-eq-in" if narrow else "stream/mask-eq-in"
    return (name, builtin, run)


def _ql_scenarios():
    from banyandb_tpu.api.model import Condition, QueryRequest, TimeRange
    from banyandb_tpu.query import ql_exec

    def run_trace():
        from banyandb_tpu.api.model import QueryResult

        def q(req, tracer=None):
            res = QueryResult()
            res.data_points = [
                {
                    "trace_id": "t-1",
                    "timestamp": T0,
                    "tags": {"svc": "a", "trace_id": "t-1"},
                    "span": b"",
                }
            ]
            return res

        eng = SimpleNamespace(query=q)
        req = QueryRequest(
            ("g",),
            "t",
            TimeRange(T0, T0 + 1000),
            criteria=Condition("trace_id", "eq", "t-1"),
        )
        ql_exec.execute_trace_ql(eng, req)

    def run_property():
        eng = SimpleNamespace(
            query=lambda g, n, tag_filters=None, ids=None, limit=100: [
                SimpleNamespace(id="p1", tags={"k": "v"}, mod_revision=1)
            ]
        )
        req = QueryRequest(
            ("g",),
            "p",
            TimeRange(T0, T0 + 1000),
            criteria=Condition("id", "eq", "p1"),
        )
        ql_exec.execute_property_ql(eng, req)

    return [("ql/trace", None, run_trace), ("ql/property", None, run_property)]


def _anchor(kind: str) -> tuple[str, int]:
    import inspect

    from banyandb_tpu.lint.whole_program.plan_audit import _rel_path
    from banyandb_tpu.query import measure_exec, ql_exec, stream_exec

    mod, fn = {
        "measure": (measure_exec, measure_exec.compute_partials),
        "stream_mask": (stream_exec, stream_exec.device_tag_mask),
        "ql": (ql_exec, ql_exec.execute_trace_ql),
    }[kind]
    return _rel_path(inspect.getsourcefile(mod)), inspect.getsourcelines(fn)[1]


@contextlib.contextmanager
def _env(overrides: Optional[dict]):
    """Scoped os.environ overrides ({} / None = ambient values)."""
    import os

    overrides = overrides or {}
    saved = {name: os.environ.get(name) for name in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for name, old in saved.items():
            if old is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = old


def audit_dispatch() -> dict[str, DispatchTrace]:
    """Run every scenario under the stub device -> measured traces.

    Each measure scenario runs THREE times: with ``BYDB_FUSED=0`` (the
    staged per-chunk loop, the ``measure/*`` rows), with the fused
    whole-plan executor on (the ``fused/*`` rows, pinned to the
    precompile registry's builtin FusedSpecs at dispatches=1/gets=1),
    and with fused + ``BYDB_DEVICE_DECODE=1`` (the ``fused+decode/*``
    rows: the compressed ship form must STILL cost exactly one dispatch
    and one batched get — the decode stage fuses into the plan program
    or the whole point is lost), plus the multi-chunk staging tripwire.
    The measure/fused rows pin ``BYDB_DEVICE_DECODE=0`` explicitly so
    their put counts stay the dense-ship baseline regardless of the
    ambient default."""
    from banyandb_tpu.query import precompile

    staged_env = {"BYDB_FUSED": "0", "BYDB_DEVICE_DECODE": "0"}
    fused_env = {"BYDB_FUSED": "1", "BYDB_DEVICE_DECODE": "0"}
    decode_env = {"BYDB_FUSED": "1", "BYDB_DEVICE_DECODE": "1"}
    scenarios = [
        (name, "measure", builtin, run, staged_env)
        for name, builtin, run in _measure_scenarios()
    ]
    fused_builtins = dict(precompile.builtin_fused())
    for name, _builtin, run in _measure_scenarios():
        fname = name.replace("measure/", "fused/")
        scenarios.append((fname, "measure", fused_builtins[fname], run, fused_env))
    for name, _builtin, run in _measure_scenarios():
        dname = name.replace("measure/", "fused+decode/")
        # same builtin FusedSpec: the ship form changes the chunk
        # pytree, never the plan signature
        scenarios.append(
            (dname, "measure", fused_builtins[name.replace("measure/", "fused/")], run, decode_env)
        )
    scenarios.append(
        ("fused/multi-chunk", "measure", None, _multichunk_scenario(), fused_env)
    )
    scenarios.append(
        (
            "fused+decode/multi-chunk",
            "measure",
            None,
            _multichunk_scenario(),
            decode_env,
        )
    )
    s_name, s_builtin, s_run = _stream_scenario()
    scenarios.append((s_name, "stream_mask", s_builtin, s_run, {"BYDB_DEVICE_DECODE": "0"}))
    d_name, d_builtin, d_run = _stream_scenario(narrow=True)
    scenarios.append((d_name, "stream_mask", d_builtin, d_run, {"BYDB_DEVICE_DECODE": "1"}))
    scenarios += [
        (name, "ql", builtin, run, None)
        for name, builtin, run in _ql_scenarios()
    ]

    out: dict[str, DispatchTrace] = {}
    for name, kind, builtin, run, env in scenarios:
        path, line = _anchor(kind)
        with stub_device() as counters, _env(env):
            error = ""
            try:
                run()
            except Exception as e:  # noqa: BLE001 — the finding IS the report
                error = f"{type(e).__name__}: {e}"
        out[name] = DispatchTrace(
            name=name,
            kind=kind,
            dispatches=len(counters.dispatches),
            gets=len(counters.gets),
            puts=len(counters.puts),
            specs=tuple(spec for _k, spec in counters.dispatches),
            builtin=builtin,
            path=path,
            line=line,
            error=error,
        )
    return out


def _spec_diff(got, want) -> str:
    parts = []
    for f in dataclasses.fields(want):
        g, w = getattr(got, f.name), getattr(want, f.name)
        if g != w:
            parts.append(f"{f.name}: resolved {g!r} != builtin {w!r}")
    return "; ".join(parts) or f"resolved {got!r} != builtin {want!r}"


def dispatch_findings(traces: dict[str, DispatchTrace]) -> list[Finding]:
    """Scenario failures and signature drift (budget columns are checked
    by kernel_budgets.audit_budgets on the same traces)."""
    findings: list[Finding] = []
    for name in sorted(traces):
        t = traces[name]
        if t.error:
            findings.append(
                Finding(
                    path=t.path,
                    line=t.line,
                    col=0,
                    rule=RULE,
                    message=f"[{name}] scenario failed under the stub "
                    f"device: {t.error}",
                )
            )
            continue
        if t.builtin is None:
            continue
        resolved = tuple(dict.fromkeys(t.specs))
        if resolved != (t.builtin,):
            detail = (
                _spec_diff(resolved[0], t.builtin)
                if len(resolved) == 1
                and dataclasses.is_dataclass(resolved[0])
                else f"resolved {len(resolved)} distinct signatures"
            )
            findings.append(
                Finding(
                    path=t.path,
                    line=t.line,
                    col=0,
                    rule=RULE,
                    message=(
                        f"[{name}] plan signature drift: the executor did "
                        "not resolve the precompile-registry builtin "
                        f"signature ({detail}); the registry would warm a "
                        "kernel production queries never hit"
                    ),
                )
            )
    return findings


def measured_columns(t: DispatchTrace) -> dict[str, Optional[int]]:
    """The budget-table columns this analyzer measures."""
    return {"dispatches": t.dispatches, "gets": t.gets, "puts": t.puts}
