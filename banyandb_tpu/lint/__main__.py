"""CLI: ``python -m banyandb_tpu.lint [--check] [--format json] PATH...``

Exit codes: without ``--check`` the run is report-only (exit 0 even
with findings — the editor/exploration mode); ``--check`` is the CI
gate (exit 1 on any finding); 2 on usage error.
"""

from __future__ import annotations

import argparse
import sys

from banyandb_tpu.lint.core import (
    all_rules,
    lint_paths,
    render_json,
    render_text,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bdlint",
        description="banyandb-tpu project-native static analysis",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["banyandb_tpu"],
        help="files or directories (default: banyandb_tpu)",
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="CI mode: exit 1 on any finding (default: report-only)",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (json is SARIF-lite, stable ordering)",
    )
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated rule names to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            scope = ",".join(r.scope) or "(package)"
            print(f"{r.name:18s} [{scope}] {r.summary}")
        return 0
    if args.rules:
        wanted = {n.strip() for n in args.rules.split(",") if n.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"bdlint: unknown rule(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    findings, summary = lint_paths(args.paths, rules=rules)
    if args.format == "json":
        print(render_json(findings, summary))
    else:
        print(render_text(findings, summary))
    return 1 if (findings and args.check) else 0


if __name__ == "__main__":
    sys.exit(main())
