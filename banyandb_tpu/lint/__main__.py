"""CLI: ``python -m banyandb_tpu.lint [--check] [--format json] PATH...``

Exit codes: without ``--check`` the run is report-only (exit 0 even
with findings — the editor/exploration mode); ``--check`` is the CI
gate (exit 1 on any finding); 2 on usage error.

``--check`` also runs the whole-program analyses (layering, call-graph
sync/lock propagation, lock-order cycles, eval_shape plan audit, the
bdjit kernel audit) when a target path is — or contains — the real
``banyandb_tpu`` package; ``--whole-program`` runs them report-only
without the gate.  ``--only=FAMILY,...`` restricts the run to named
analyzer families (``rules`` = the per-file rules, plus ``kernel``,
``layering``, ``shared-state``, ``lock-order``, ``plan-audit``,
``sync``) so local iteration does not pay the full whole-program pass;
``--fast`` skips the kernel lowering-audit (the XLA-compile half).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional

from banyandb_tpu.lint.core import (
    all_rules,
    lint_paths,
    render_json,
    render_text,
)


def _find_pkg_root(paths: list[str]) -> Optional[Path]:
    """The banyandb_tpu package dir among the CLI targets, if any.
    Whole-program analyses need the whole package, so a single-file or
    out-of-package target runs the per-file rules only."""
    for p in paths:
        pth = Path(p)
        if pth.name == "banyandb_tpu" and (pth / "__init__.py").is_file():
            return pth
        cand = pth / "banyandb_tpu"
        if pth.is_dir() and (cand / "__init__.py").is_file():
            return cand
    return None


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bdlint",
        description="banyandb-tpu project-native static analysis",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["banyandb_tpu"],
        help="files or directories (default: banyandb_tpu)",
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="CI mode: exit 1 on any finding; includes the whole-program "
        "analyses (default: report-only)",
    )
    ap.add_argument(
        "--whole-program",
        action="store_true",
        help="run the whole-program analyses (layering, call-graph facts, "
        "lock-order, plan audit, kernel audit) report-only",
    )
    ap.add_argument(
        "--only",
        default="",
        help="comma-separated analyzer families to run: rules, kernel, "
        "layering, shared-state, lock-order, plan-audit, sync "
        "(default: all; implies running the named whole-program "
        "analyses)",
    )
    ap.add_argument(
        "--fast",
        action="store_true",
        help="skip the kernel lowering-audit (XLA compiles; jaxpr + "
        "dispatch budgets still run)",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (json is SARIF 2.1.0, deterministic)",
    )
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated rule names to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)

    from banyandb_tpu.lint.whole_program import (
        FAMILIES,
        WP_RULES,
        family_of_rule,
    )

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            scope = ",".join(r.scope) or "(package)"
            print(f"{r.name:18s} [{scope}] {r.summary}")
        for name, summary in WP_RULES:
            print(f"{name:18s} [whole-program] {summary}")
        return 0

    only: Optional[set] = None
    if args.only:
        only = {n.strip() for n in args.only.split(",") if n.strip()}
        known_families = set(FAMILIES) | {"rules"}
        unknown = only - known_families
        if unknown:
            print(
                f"bdlint: unknown --only famil{'y' if len(unknown) == 1 else 'ies'}:"
                f" {sorted(unknown)} (choose from {sorted(known_families)})",
                file=sys.stderr,
            )
            return 2

    wanted = None
    if args.rules:
        wanted = {n.strip() for n in args.rules.split(",") if n.strip()}
        known = {r.name for r in rules} | {n for n, _ in WP_RULES}
        unknown = wanted - known
        if unknown:
            print(f"bdlint: unknown rule(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    run_file_rules = only is None or "rules" in only
    if run_file_rules:
        findings, summary = lint_paths(args.paths, rules=rules)
    else:
        findings, summary = [], {"files": 0, "findings": 0, "suppressed": 0}

    wp_root = _find_pkg_root(args.paths)
    wp_names = {n for n, _ in WP_RULES}
    # naming a whole-program rule via --rules (or a family via --only)
    # implies running those analyses even without --check/--whole-program
    # — an analysis the user asked for by name must never silently not run
    wp_only: Optional[set] = None
    if only is not None:
        wp_only = only & set(FAMILIES)
    if wanted is not None:
        from_rules = {
            fam
            for fam in (family_of_rule(n) for n in wanted)
            if fam is not None
        }
        wp_only = from_rules if wp_only is None else (wp_only & from_rules)
    run_wp = (
        args.check
        or args.whole_program
        or (wanted is not None and bool(wanted & wp_names))
        or (only is not None and bool(only & set(FAMILIES)))
    ) and wp_root is not None
    if wp_only is not None and not wp_only:
        run_wp = False
    # a selection that excludes EVERY analyzer is a usage error, not a
    # green gate: --check must never exit 0 having checked nothing
    # (e.g. --only=kernel --rules=host-sync, or --only=rules
    # --rules=layering)
    file_rules_vacuous = not run_file_rules or (
        wanted is not None and not rules
    )
    if (args.rules or args.only) and file_rules_vacuous and not run_wp:
        print(
            "bdlint: the --only/--rules selection excludes every analyzer "
            "(nothing would run); drop one flag or align them",
            file=sys.stderr,
        )
        return 2
    if run_wp:
        from banyandb_tpu.lint.whole_program import run_whole_program

        wp_findings, wp_stats = run_whole_program(
            wp_root,
            only=wp_only,
            fast=args.fast,
        )
        if wanted is not None:
            wp_findings = [f for f in wp_findings if f.rule in wanted]
            wp_stats["wp_findings"] = len(wp_findings)
        findings = sorted(findings + wp_findings)
        summary["findings"] += len(wp_findings)
        summary["suppressed"] += wp_stats["wp_suppressed"]
        summary.update(wp_stats)

    if args.format == "json":
        print(render_json(findings, summary))
    else:
        print(render_text(findings, summary))
    return 1 if (findings and args.check) else 0


if __name__ == "__main__":
    sys.exit(main())
