"""bdlint — project-native static analysis for banyandb-tpu.

Machine-checks the invariants that keep the TPU hot path hot and the
cluster fabric live — the failure classes code review keeps missing
(docs/linting.md has the full rule catalog):

- ``host-sync``        accidental device->host round-trips in hot modules
- ``recompile-hazard`` per-call jit wrapper churn / trace-time formatting
- ``rpc-timeout``      fabric calls that can block forever
- ``lock-across-rpc``  locks held across blocking network calls
- ``retry-backoff``    retry loops that hammer without sleeping
- ``resource-hygiene`` files/sockets opened outside context managers
- ``precision-drift``  implicit float64 promotion in kernel paths

Usage::

    python -m banyandb_tpu.lint --check banyandb_tpu
    python -m banyandb_tpu.lint --format json path/to/file.py

Per-line suppression (same line or the comment line directly above)::

    x = np.asarray(out)  # bdlint: disable=host-sync -- boundary transfer
"""

from banyandb_tpu.lint.core import (  # noqa: F401
    ALL_RULES,
    Finding,
    lint_file,
    lint_paths,
    lint_source,
    render_json,
    render_text,
)
