"""FODC proxy: cluster-wide first-occurrence data capture.

Analog of the reference's fodc proxy tier (/root/reference/fodc/proxy —
the aggregation layer above per-node fodc agents): the proxy polls
every cluster node's diagnostics topic, assembles one timestamped
bundle per capture, persists bundles to disk with a retention cap, and
can run trigger rules (capture automatically when a node reports a
pressure signal).  The per-node agent half is admin/diagnostics.py.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Optional

from banyandb_tpu.cluster.rpc import TransportError

from banyandb_tpu.admin.diagnostics import DIAG_TOPIC  # noqa: E402


class FodcProxy:
    def __init__(
        self,
        transport,
        nodes,  # list[NodeInfo]
        bundle_root: str | Path,
        *,
        max_bundles: int = 16,
    ):
        self.transport = transport
        self.nodes = list(nodes)
        self.root = Path(bundle_root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_bundles = max_bundles
        self._lock = threading.Lock()
        self._active: set[Path] = set()  # bundles mid-write: retention-immune
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.triggered = 0

    def _poll_node(self, n, include_threads: bool) -> tuple[dict, str]:
        try:
            return (
                self.transport.call(
                    n.addr,
                    DIAG_TOPIC,
                    {"include_threads": include_threads},
                    timeout=10,
                ),
                "ok",
            )
        except TransportError as e:
            return {"error": str(e)}, "unreachable"
        except Exception as e:  # noqa: BLE001 - a faulty collector on one
            # node must not abort the whole bundle (incidents are exactly
            # when collectors fail)
            return {"error": f"{type(e).__name__}: {e}"}, "collector-error"

    # -- capture -------------------------------------------------------------
    def capture(
        self,
        reason: str = "manual",
        include_threads: bool = False,
        preset: Optional[dict] = None,
    ) -> Path:
        """Collect diagnostics from every node into one bundle dir.

        Nodes poll IN PARALLEL (serial 10s timeouts on a degraded
        cluster would block the capture for minutes — exactly when it
        must be fast).  `preset` supplies already-collected diagnostics
        per node name (the trigger path reuses its probe responses)."""
        import uuid
        from concurrent.futures import ThreadPoolExecutor

        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        # uuid suffix: two captures in the same wall-clock second (manual
        # + trigger racing) must not overwrite each other's evidence
        bundle = self.root / f"fodc-{stamp}-{reason}-{uuid.uuid4().hex[:8]}"
        bundle.mkdir(parents=True, exist_ok=False)
        with self._lock:
            self._active.add(bundle)
        try:
            summary = {"reason": reason, "captured_at": stamp, "nodes": {}}
            preset = preset or {}
            to_poll = [n for n in self.nodes if n.name not in preset]
            results = {name: (diag, "ok") for name, diag in preset.items()}
            if to_poll:
                with ThreadPoolExecutor(max_workers=min(8, len(to_poll))) as ex:
                    for n, res in zip(
                        to_poll,
                        ex.map(lambda n: self._poll_node(n, include_threads), to_poll),
                    ):
                        results[n.name] = res
            for n in self.nodes:
                diag, status = results[n.name]
                (bundle / f"{n.name}.json").write_text(
                    json.dumps(diag, indent=1, default=str)
                )
                summary["nodes"][n.name] = status
            (bundle / "summary.json").write_text(json.dumps(summary, indent=1))
        finally:
            with self._lock:
                self._active.discard(bundle)
        self._enforce_retention()
        return bundle

    def _enforce_retention(self) -> None:
        import shutil

        with self._lock:
            bundles = sorted(
                d
                for d in self.root.iterdir()
                if d.is_dir()
                and d.name.startswith("fodc-")
                and d not in self._active  # never GC a bundle mid-write
            )
            for old in bundles[: max(0, len(bundles) - self.max_bundles)]:
                shutil.rmtree(old, ignore_errors=True)

    def list_bundles(self) -> list[str]:
        return sorted(
            d.name
            for d in self.root.iterdir()
            if d.is_dir() and d.name.startswith("fodc-")
        )

    # -- trigger rules --------------------------------------------------------
    def check_triggers(
        self,
        *,
        rss_limit_bytes: Optional[int] = None,
        min_interval_s: float = 300.0,
    ) -> Optional[Path]:
        """One trigger evaluation: capture when any node reports RSS over
        the limit (the first-OCCURRENCE contract: one bundle per episode,
        rate-limited by min_interval_s).  With no rule configured this is
        a no-op — no wasted per-node diagnostics RPCs."""
        if rss_limit_bytes is None:
            return None
        now = time.monotonic()
        last = getattr(self, "_last_trigger", -1e18)
        if now - last < min_interval_s:
            return None
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(8, len(self.nodes) or 1)) as ex:
            probes = dict(
                zip(
                    (n.name for n in self.nodes),
                    ex.map(lambda n: self._poll_node(n, False), self.nodes),
                )
            )
        for n in self.nodes:
            diag, status = probes[n.name]
            if status != "ok":
                continue
            rss = (diag.get("process") or {}).get("rss_bytes", 0)
            if rss > rss_limit_bytes:
                self._last_trigger = now
                self.triggered += 1
                # no preset here: the probes were collected WITHOUT thread
                # dumps, and an RSS bundle without stacks is useless —
                # re-poll (in parallel) with include_threads=True
                return self.capture(
                    reason=f"rss-{n.name}", include_threads=True
                )
        return None

    # -- background loop ------------------------------------------------------
    def start(self, interval_s: float = 30.0, **trigger_kw) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.check_triggers(**trigger_kw)
                except Exception:  # noqa: BLE001 - the watchdog survives
                    pass

        self._thread = threading.Thread(target=loop, daemon=True, name="fodc-proxy")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
