"""Hot -> cold segment migration (banyand/backup/lifecycle analog).

The reference's lifecycle agent moves expired-from-hot segments between
node tiers with resumable progress (lifecycle/service.go, progress.go).
This single-node form migrates whole segment dirs into an archive root
with copy -> verify -> swap semantics and a JSON progress file so an
interrupted run resumes instead of restarting:

    migrate(db, archive_root, older_than_millis)
    restore_segment(archive_root, db, segment_name)

Multi-node tier routing (stage-aware node selectors,
banyand/queue/pub/stage.go) composes on top: the archive root of a hot
node is the data root of a warm/cold node shipped via chunked sync.
"""

from __future__ import annotations

import shutil
from pathlib import Path

from banyandb_tpu.storage.tsdb import TSDB
from banyandb_tpu.utils import fs


def _dir_signature(root: Path) -> list[tuple[str, int]]:
    return sorted(
        (str(p.relative_to(root)), p.stat().st_size)
        for p in root.rglob("*")
        if p.is_file()
    )


def migrate(
    db: TSDB, archive_root: str | Path, older_than_millis: int
) -> list[str]:
    """Move segments whose window ended before the cutoff. Returns names."""
    archive_root = Path(archive_root)
    progress_path = archive_root / ".migration-progress.json"
    done: dict = (
        fs.read_json(progress_path) if progress_path.exists() else {"copied": []}
    )
    moved = []
    for seg in db.segments:
        if seg.end > older_than_millis:
            continue
        # Seal the segment first: memtable rows must reach disk before the
        # directory is copied, or they'd exist in neither tier.
        for shard in seg.shards:
            shard.flush()
        seg.persist_index()
        name = seg.root.name
        dest = archive_root / name
        # A "copied" marker from a previous run is only trusted if the
        # archived copy still matches the (possibly since-written) hot dir;
        # any divergence re-runs the copy before the destructive swap.
        up_to_date = (
            name in done["copied"]
            and dest.exists()
            and _dir_signature(dest) == _dir_signature(seg.root)
        )
        if not up_to_date:
            if dest.exists():
                shutil.rmtree(dest)
            shutil.copytree(seg.root, dest)
            if _dir_signature(dest) != _dir_signature(seg.root):
                raise IOError(f"verification failed migrating {name}")
            if name not in done["copied"]:
                done["copied"].append(name)
            fs.atomic_write_json(progress_path, done)
        # swap phase: drop from the hot tier only after a verified copy
        with db._lock:
            start = seg.start
            if start in db._segments:
                del db._segments[start]
        shutil.rmtree(seg.root, ignore_errors=True)
        moved.append(name)
    live = {seg.root.name for seg in db.segments}
    done["copied"] = [n for n in done["copied"] if n in live]
    fs.atomic_write_json(progress_path, done)
    return moved


def list_archived(archive_root: str | Path) -> list[str]:
    return sorted(
        p.name for p in Path(archive_root).glob("seg-*") if p.is_dir()
    )


def restore_segment(
    archive_root: str | Path, db: TSDB, segment_name: str
) -> None:
    """Bring an archived segment back into the hot tier.

    Only the one segment is attached (under the db lock) — a full
    _reopen would replace every live Segment object and drop their
    unflushed memtables.
    """
    import datetime as dt

    from banyandb_tpu.storage.tsdb import Segment

    src = Path(archive_root) / segment_name
    dest = db.root / segment_name
    if dest.exists():
        raise FileExistsError(f"segment {segment_name} already live")
    shutil.copytree(src, dest)
    stamp = segment_name[4:]
    iv = db.opts.segment_interval
    fmt = "%Y%m%d%H" if iv.unit == "hour" else "%Y%m%d"
    t = dt.datetime.strptime(stamp, fmt).replace(tzinfo=dt.timezone.utc)
    start = int(t.timestamp() * 1000)
    with db._lock:
        if start in db._segments:
            raise FileExistsError(f"segment {segment_name} already attached")
        db._segments[start] = Segment(
            dest, start, iv.millis, db.opts.shard_num, db.mem_factory
        )
