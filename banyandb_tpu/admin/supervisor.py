"""Panic supervisor: crash capture + clean cancellation.

Analog of the reference's panic supervisor
(/root/reference/pkg/cmdsetup/supervisor.go: recovered panics write
diagnostics and cancel the run group instead of half-dying).  The
Python twins of "recovered panic" are (a) an uncaught exception on ANY
thread (threading.excepthook) and (b) an uncaught exception on the main
thread (sys.excepthook).  Both paths write a crash artifact via the
diagnostics collector and trigger the run group's stop so teardown is
orderly rather than a stuck half-alive process.
"""

from __future__ import annotations

import logging
import sys
import threading
from pathlib import Path
from typing import Callable, Optional

log = logging.getLogger("banyandb.supervisor")


class Supervisor:
    def __init__(
        self,
        root: str | Path,
        on_crash: Optional[Callable[[], None]] = None,
    ):
        """on_crash: e.g. group.trigger_stop — called once per process
        after the first captured crash."""
        self.root = Path(root)
        self.on_crash = on_crash
        self.crashes = 0
        self._lock = threading.Lock()
        self._prev_threading_hook = None
        self._prev_sys_hook = None

    def _capture(self, reason: str, exc: BaseException) -> None:
        from banyandb_tpu.admin.diagnostics import DiagnosticsCollector

        with self._lock:
            self.crashes += 1
            first = self.crashes == 1
        try:
            artifact = DiagnosticsCollector(self.root).write_crash_artifact(
                f"{reason}: {type(exc).__name__}: {exc}"
            )
            log.error("crash captured -> %s", artifact)
        except Exception:  # noqa: BLE001 - capture must not crash the hook
            log.exception("crash artifact write failed")
        if first and self.on_crash is not None:
            try:
                self.on_crash()
            except Exception:  # noqa: BLE001
                log.exception("on_crash callback failed")

    def install(self) -> "Supervisor":
        self._prev_threading_hook = threading.excepthook
        self._prev_sys_hook = sys.excepthook

        def thread_hook(args):
            if args.exc_type is SystemExit:
                return
            self._capture(
                f"thread {getattr(args.thread, 'name', '?')}", args.exc_value
            )
            self._prev_threading_hook(args)

        def main_hook(exc_type, exc, tb):
            if exc_type is not SystemExit:
                self._capture("main thread", exc)
            self._prev_sys_hook(exc_type, exc, tb)

        threading.excepthook = thread_hook
        sys.excepthook = main_hook
        return self

    def uninstall(self) -> None:
        if self._prev_threading_hook is not None:
            threading.excepthook = self._prev_threading_hook
        if self._prev_sys_hook is not None:
            sys.excepthook = self._prev_sys_hook
