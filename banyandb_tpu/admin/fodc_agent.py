"""FODC per-node agent: watchdog, flight recorder, pressure profiler.

Analog of the reference's fodc agent internals
(/root/reference/fodc/agent/internal/watchdog/watchdog.go,
fodc/agent/internal/flightrecorder, fodc/agent/internal/pressureprofiler
+ fodc/internal/pprofcapture): the watchdog polls local metric sources on
an interval with bounded retry/backoff and forwards each cycle to the
flight recorder (a windowed in-memory ring the proxy can query); the
pressure profiler rides the watchdog as a post-poll hook and captures
profile artifacts to disk when RSS crosses a cgroup-derived threshold.

Re-scoped for this runtime: the reference scrapes Prometheus HTTP
endpoints and shells out to pprof; here metric sources are in-process
callables (the admin.metrics.Meter, process stats) and a "profile" is
the profiling module's thread/heap/runtime text artifacts — the eBPF
kernel telemetry is host-specific and intentionally out of scope.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from pathlib import Path
from typing import Callable, Optional

# fodc/v1 MetricType enum values (api/proto/banyandb/fodc/v1/rpc.proto)
GAUGE = "gauge"
COUNTER = "counter"
HISTOGRAM = "histogram"

PPROF_TOPIC = "fodc-pprof"  # on-demand capture over the cluster bus


@dataclasses.dataclass(frozen=True)
class RawMetric:
    """One sample: the fodc/v1 Metric message shape, host-side."""

    name: str
    labels: tuple  # sorted (k, v) pairs
    value: float
    type: str = GAUGE
    ts_millis: int = 0


def meter_source(meter) -> Callable[[], list[RawMetric]]:
    """Adapt an admin.metrics.Meter into a watchdog metric source."""

    def poll() -> list[RawMetric]:
        now = int(time.time() * 1000)
        snap = meter.snapshot()
        pfx = (meter.scope + "_") if meter.scope else ""
        out = [
            RawMetric(pfx + n + "_total", lbls, v, COUNTER, now)
            for (n, lbls), v in snap["counters"].items()
        ]
        out += [
            RawMetric(pfx + n, lbls, v, GAUGE, now)
            for (n, lbls), v in snap["gauges"].items()
        ]
        for (n, lbls), (count, total) in snap["histograms"].items():
            out.append(RawMetric(pfx + n + "_count", lbls, count, HISTOGRAM, now))
            out.append(RawMetric(pfx + n + "_sum", lbls, total, HISTOGRAM, now))
        return out

    return poll


def process_source() -> list[RawMetric]:
    """RSS / thread-count gauges (fodc watchdog's runtime params poll)."""
    from banyandb_tpu.admin.protector import process_rss

    now = int(time.time() * 1000)
    return [
        RawMetric("process_resident_memory_bytes", (), float(process_rss()), GAUGE, now),
        RawMetric("process_threads", (), float(threading.active_count()), GAUGE, now),
    ]


def io_source() -> Callable[[], list[RawMetric]]:
    """Host IO telemetry: the ktm eBPF io-monitor re-scoped to /proc
    (fodc/agent/internal/ktm/iomonitor, loader.go:54 — kernel BPF IO
    latency probes become /proc/diskstats + /proc/self/io delta rates).

    Stateful: each poll reports rates/averages over the interval since
    the previous poll.  Per physical device (partitions and loop/ram
    devices skipped): iops, bytes/s, average await ms, utilization.
    Process-level: read/write bytes/s of this node process.
    """
    from banyandb_tpu.admin.diagnostics import read_self_io

    state: dict = {"ts": None, "disk": {}, "proc": None}

    def whole_devices() -> Optional[set]:
        """Whole block devices per the kernel (/sys/block lists exactly
        those — partitions live underneath).  A name heuristic would
        misclassify nvme0n1/mmcblk0/dm-0 as partitions."""
        try:
            import os as _os

            return set(_os.listdir("/sys/block"))
        except OSError:
            return None

    def read_diskstats() -> dict:
        out = {}
        whole = whole_devices()
        try:
            with open("/proc/diskstats") as f:
                for line in f:
                    p = line.split()
                    if len(p) < 14:
                        continue
                    name = p[2]
                    if name.startswith(("loop", "ram", "zram")):
                        continue
                    if whole is not None:
                        if name not in whole:
                            continue  # partition
                    elif name[-1].isdigit() and not name.startswith(
                        ("nvme", "mmcblk", "dm-", "md")
                    ):
                        continue  # fallback heuristic without /sys/block
                    # fields: 4=reads 6=sectors_read 7=ms_reading
                    #         8=writes 10=sectors_written 11=ms_writing
                    #         13=ms_doing_io
                    out[name] = (
                        int(p[3]) + int(p[7]),           # ios completed
                        (int(p[5]) + int(p[9])) * 512,   # bytes
                        int(p[6]) + int(p[10]),          # ms waiting
                        int(p[12]),                      # ms device busy
                    )
        except OSError:
            pass
        return out


    def poll() -> list[RawMetric]:
        now_s = time.time()
        now = int(now_s * 1000)
        disk = read_diskstats()
        proc = read_self_io()
        prev_ts = state["ts"]
        out: list[RawMetric] = []
        if prev_ts is not None and now_s > prev_ts:
            dt = now_s - prev_ts
            for name, cur in disk.items():
                prev = state["disk"].get(name)
                if prev is None:
                    continue
                d_ios = cur[0] - prev[0]
                d_bytes = cur[1] - prev[1]
                d_wait = cur[2] - prev[2]
                d_busy = cur[3] - prev[3]
                if min(d_ios, d_bytes, d_wait, d_busy) < 0:
                    # counter wrap (io_ticks wraps ~49 busy-days) or a
                    # device reset under the same name: skip the interval
                    # rather than publish negative rates
                    continue
                lbl = (("device", name),)
                out.append(RawMetric("disk_iops", lbl, d_ios / dt, GAUGE, now))
                out.append(RawMetric("disk_bytes_per_s", lbl, d_bytes / dt, GAUGE, now))
                out.append(RawMetric(
                    "disk_await_ms", lbl,
                    (d_wait / d_ios) if d_ios else 0.0, GAUGE, now,
                ))
                out.append(RawMetric(
                    "disk_util", lbl, min(1.0, d_busy / (dt * 1000.0)), GAUGE, now,
                ))
            if (
                proc is not None
                and state["proc"] is not None
                and proc[0] >= state["proc"][0]
                and proc[1] >= state["proc"][1]
            ):
                out.append(RawMetric(
                    "process_read_bytes_per_s", (),
                    (proc[0] - state["proc"][0]) / dt, GAUGE, now,
                ))
                out.append(RawMetric(
                    "process_write_bytes_per_s", (),
                    (proc[1] - state["proc"][1]) / dt, GAUGE, now,
                ))
        state["ts"], state["disk"], state["proc"] = now_s, disk, proc
        return out

    return poll


class FlightRecorder:
    """Windowed ring of metric cycles (fodc flight recorder analog).

    Keeps up to `window_s` seconds of polled cycles; `latest()` answers
    the proxy's live scrape, `window(start, end)` its historical query.
    """

    def __init__(self, window_s: float = 900.0, max_cycles: int = 512):
        self.window_s = window_s
        self.max_cycles = max_cycles
        self._lock = threading.Lock()
        self._cycles: list[tuple[float, list[RawMetric]]] = []

    def update(self, metrics: list[RawMetric]) -> None:
        now = time.time()
        with self._lock:
            self._cycles.append((now, list(metrics)))
            cutoff = now - self.window_s
            while self._cycles and (
                self._cycles[0][0] < cutoff or len(self._cycles) > self.max_cycles
            ):
                self._cycles.pop(0)

    def latest(self) -> list[RawMetric]:
        with self._lock:
            return list(self._cycles[-1][1]) if self._cycles else []

    def window(self, start_s: float, end_s: float) -> list[tuple[float, list[RawMetric]]]:
        with self._lock:
            return [
                (ts, list(ms)) for ts, ms in self._cycles if start_s <= ts <= end_s
            ]


class Watchdog:
    """Polls metric sources on an interval; feeds the flight recorder.

    Mirrors watchdog.go's contract: per-source retry (3 attempts,
    100ms->5s exponential backoff), a live node-identity provider whose
    first resolved answer "sticks" (a provider regressing to unresolved
    must not fork a ghost series), a resolve grace period before the
    first recording, and post-poll hooks run in registration order.
    """

    MAX_RETRIES = 3
    INITIAL_BACKOFF_S = 0.1
    MAX_BACKOFF_S = 5.0

    def __init__(
        self,
        recorder: FlightRecorder,
        sources: list[Callable[[], list[RawMetric]]],
        *,
        interval_s: float = 5.0,
        node_role: str = "",
        resolve_grace_s: float = 300.0,
    ):
        self.recorder = recorder
        self.sources = list(sources)
        self.interval_s = interval_s
        self._node_info: Optional[Callable[[], tuple[str, dict]]] = None
        self._resolved: Optional[tuple[str, dict]] = None
        self._static_role = node_role
        self._resolve_grace_s = resolve_grace_s
        self._start_time = time.monotonic()
        self._hooks: list[Callable[[], None]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.poll_count = 0
        self.error_count = 0

    def set_node_info_provider(self, fn: Callable[[], tuple[str, dict]]) -> None:
        with self._lock:
            self._node_info = fn

    def add_post_poll_hook(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._hooks.append(fn)

    def _resolve_identity(self) -> tuple[str, dict]:
        with self._lock:
            provider, cached = self._node_info, self._resolved
        role, labels = (provider() if provider else (self._static_role, {}))
        if role and role != "unspecified":
            resolved = (role, dict(labels))
            with self._lock:
                self._resolved = resolved
            return resolved
        if cached is not None:  # sticky: never regress to unresolved
            return cached
        return (self._static_role, {})

    def _poll_source(self, src) -> list[RawMetric]:
        backoff = self.INITIAL_BACKOFF_S
        for attempt in range(self.MAX_RETRIES):
            try:
                return src()
            except Exception:  # noqa: BLE001 - retried, then surfaced as a count
                if attempt == self.MAX_RETRIES - 1:
                    self.error_count += 1
                    return []
                time.sleep(backoff)
                backoff = min(backoff * 2, self.MAX_BACKOFF_S)
        return []

    def poll_once(self) -> list[RawMetric]:
        """One full cycle: poll every source, stamp identity, record, hooks."""
        role, labels = self._resolve_identity()
        if (
            not role
            and time.monotonic() - self._start_time < self._resolve_grace_s
        ):
            # defer recording while unresolved (ghost-series guard); after
            # the grace period record anyway so a never-resolving node is
            # still observable
            return []
        stamp = tuple(sorted({"node_role": role or "unknown", **labels}.items()))
        cycle: list[RawMetric] = []
        for src in self.sources:
            for m in self._poll_source(src):
                cycle.append(
                    dataclasses.replace(m, labels=tuple(sorted((*m.labels, *stamp))))
                )
        self.recorder.update(cycle)
        self.poll_count += 1
        with self._lock:
            hooks = list(self._hooks)
        for h in hooks:
            try:
                h()
            except Exception:  # noqa: BLE001 - a hook must not kill the poll loop
                pass
        return cycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001
                    self.error_count += 1

        self._thread = threading.Thread(target=loop, daemon=True, name="fodc-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class PressureProfiler:
    """Capture profile artifacts when memory pressure crosses a threshold.

    fodc pressureprofiler + pprofcapture analog: each capture event is a
    directory named by its UTC-ns timestamp holding `threads.txt`,
    `heap.txt` (tracemalloc top), and `runtime.txt`, plus a `record.json`
    matching the fodc/v1 PressureProfileRecord fields. Ride a Watchdog
    via `hook()`; serve the proxy's list/fetch commands via
    `list_records()` / `read_profile()` (path-validated to this dir).
    """

    PROFILE_FILES = ("threads", "heap", "runtime")

    def __init__(
        self,
        root: str | Path,
        *,
        limit_bytes: int,
        trigger_percent: int = 75,
        min_interval_s: float = 300.0,
        max_events: int = 8,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.limit_bytes = int(limit_bytes)
        self.trigger_percent = int(trigger_percent)
        self.threshold_bytes = self.limit_bytes * self.trigger_percent // 100
        self.min_interval_s = min_interval_s
        self.max_events = max_events
        self._last_capture = -1e18
        self._lock = threading.Lock()
        self.captured = 0

    def hook(self) -> None:
        """Watchdog post-poll hook: check pressure, maybe capture."""
        from banyandb_tpu.admin.protector import process_rss

        self.maybe_capture(process_rss())

    def maybe_capture(self, rss_bytes: int) -> Optional[Path]:
        if self.threshold_bytes <= 0 or rss_bytes < self.threshold_bytes:
            return None
        now = time.monotonic()
        with self._lock:
            if now - self._last_capture < self.min_interval_s:
                return None
            self._last_capture = now
        return self.capture(rss_bytes)

    def capture(self, rss_bytes: int) -> Path:
        import json

        from banyandb_tpu.admin.profiling import (
            _threads_text,
            _tracemalloc_text,
            _vars_text,
        )

        profile_id = f"{time.time_ns()}"
        event = self.root / profile_id
        event.mkdir(parents=True, exist_ok=True)
        contents = {
            "threads": _threads_text(),
            "heap": _tracemalloc_text(25),
            "runtime": _vars_text(),
        }
        profiles = []
        for kind in self.PROFILE_FILES:
            p = event / f"{kind}.txt"
            p.write_text(contents[kind])
            profiles.append(
                {
                    "type": kind,
                    "filename": p.name,
                    "filepath": str(p),
                    "format": "text",
                    "size_bytes": p.stat().st_size,
                }
            )
        record = {
            "profile_id": profile_id,
            "captured_at_millis": int(time.time() * 1000),
            "rss_bytes": rss_bytes,
            "cgroup_limit_bytes": self.limit_bytes,
            "trigger_percent": self.trigger_percent,
            "threshold_bytes": self.threshold_bytes,
            "profiles": profiles,
        }
        (event / "record.json").write_text(json.dumps(record, indent=1))
        self.captured += 1
        self._enforce_retention()
        return event

    def _enforce_retention(self) -> None:
        import shutil

        events = sorted(d for d in self.root.iterdir() if d.is_dir())
        for old in events[: max(0, len(events) - self.max_events)]:
            shutil.rmtree(old, ignore_errors=True)

    def list_records(self) -> list[dict]:
        import json

        out = []
        for d in sorted(p for p in self.root.iterdir() if p.is_dir()):
            rec = d / "record.json"
            if rec.exists():
                try:
                    out.append(json.loads(rec.read_text()))
                except ValueError:
                    pass
        return out

    def read_profile(self, profile_id: str, kind: str) -> bytes:
        """Serve one profile's bytes; the path is validated to live under
        this profiler's root (the agent-side check FetchPressureProfile
        documents — a proxy-supplied path must not escape the dir)."""
        p = (self.root / profile_id / f"{kind}.txt").resolve()
        if not str(p).startswith(str(self.root.resolve()) + "/"):
            raise PermissionError(f"profile path escapes profiler dir: {p}")
        if not p.exists():
            raise FileNotFoundError(f"{profile_id}/{kind}")
        return p.read_bytes()


def pprof_capture_handler(payload: dict) -> dict:
    """Bus handler for on-demand profile capture (fodc pprofcapture RPC
    analog; register under PPROF_TOPIC on every node).

    payload: {"kinds": ["threads","heap","runtime","cpu"], "seconds": N}
    -> {"profiles": {kind: text}}
    """
    from banyandb_tpu.admin import profiling

    kinds = payload.get("kinds") or ["threads", "runtime"]
    out = {}
    for kind in kinds:
        if kind == "threads":
            out[kind] = profiling._threads_text()
        elif kind == "heap":
            out[kind] = profiling._tracemalloc_text(int(payload.get("top", 25)))
        elif kind == "runtime":
            out[kind] = profiling._vars_text()
        elif kind == "cpu":
            out[kind] = profiling._profile_text(
                float(payload.get("seconds", 2.0))
            )
        else:
            out[kind] = f"unknown profile kind {kind!r}"
    return {"profiles": out}
