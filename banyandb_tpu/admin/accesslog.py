"""Access + slow-query logging (pkg/accesslog analog).

JSON-lines access records for writes and queries, with a separate
slow-query threshold mirroring the reference's slow-query capture
(banyand/dquery/measure.go:169-174).  Files rotate by size.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Optional


class AccessLog:
    # one default for every surface that marks queries slow (access log,
    # flight recorder); overridable per instance, via the server config
    # flag --slow-query-ms, or the BYDB_SLOW_QUERY_MS env
    DEFAULT_SLOW_QUERY_MS = 500.0

    def __init__(
        self,
        path: str | Path,
        *,
        slow_query_ms: Optional[float] = None,
        max_bytes: int = 64 << 20,
    ):
        from banyandb_tpu.utils.envflag import env_float

        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if slow_query_ms is None:
            slow_query_ms = env_float(
                "BYDB_SLOW_QUERY_MS", self.DEFAULT_SLOW_QUERY_MS
            )
        self.slow_query_ms = slow_query_ms
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        # bdlint: disable=resource-hygiene -- log handle lives as long as
        # the AccessLog; closed by close() and across rotation in _emit
        self._f = open(self.path, "a", buffering=1)

    def _emit(self, record: dict) -> None:
        record["ts"] = int(time.time() * 1000)
        with self._lock:
            if self._f.tell() > self.max_bytes:
                # single-generation rotation: access.log -> access.log.1
                self._f.close()
                rotated = self.path.with_name(self.path.name + ".1")
                self.path.replace(rotated)
                # bdlint: disable=resource-hygiene -- rotation replaces
                # the owned handle just closed above
                self._f = open(self.path, "a", buffering=1)
            self._f.write(json.dumps(record) + "\n")

    def log_write(
        self, group: str, name: str, points: int, duration_ms: float,
        *, tenant: str = "",
    ) -> None:
        self._emit(
            {"kind": "write", "group": group, "name": name,
             "tenant": tenant or self._tenant(group),
             "points": points, "ms": round(duration_ms, 3)}
        )

    @staticmethod
    def _tenant(group: str) -> str:
        from banyandb_tpu.qos.tenancy import tenant_of_group

        return tenant_of_group(group)

    def log_query(
        self,
        group: str,
        name: str,
        duration_ms: float,
        *,
        ql: Optional[str] = None,
        rows: int = 0,
        tenant: str = "",
    ) -> None:
        rec = {
            "kind": "query", "group": group, "name": name,
            "tenant": tenant or self._tenant(group),
            "ms": round(duration_ms, 3), "rows": rows,
        }
        if ql:
            rec["ql"] = ql
        if duration_ms >= self.slow_query_ms:
            rec["slow"] = True
        self._emit(rec)

    def close(self) -> None:
        with self._lock:
            self._f.close()
