"""Operational subsystems: backup/restore, admission control, metrics
(the reference's banyand/backup, banyand/protector, pkg/meter +
banyand/observability analogs)."""
