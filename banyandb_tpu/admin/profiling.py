"""In-process profiling endpoints (pprof-server analog).

The reference mounts Go's pprof handlers
(/root/reference/banyand/observability/pprof.go:40); the Python twin
serves the equivalent diagnostics over a tiny HTTP listener:

    GET /debug/threads            all thread stacks (goroutine profile)
    GET /debug/tracemalloc?top=N  top allocation sites (heap profile);
                                  first call starts tracing
    GET /debug/profile?seconds=N  statistical sampler over ALL threads
                                  for N seconds (cpu profile); top
                                  frames by sample count
    GET /debug/vars               runtime counters (gc, threads, rss)

Plain text responses — curl-able under incident pressure, no tooling
required.
"""

from __future__ import annotations

import collections
import gc
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


def _threads_text() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def _tracemalloc_text(top: int) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return "tracemalloc started; call again for a snapshot\n"
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    total = sum(s.size for s in snap.statistics("filename"))
    lines = [f"total traced: {total / 1e6:.1f} MB; top {top} by line:"]
    lines += [str(s) for s in stats]
    return "\n".join(lines) + "\n"


def _profile_text(seconds: float, hz: float = 100.0) -> str:
    """Statistical wall-clock sampler over ALL threads (cProfile hooks
    only the calling thread, which here would just be sleeping): sample
    sys._current_frames() at `hz`, aggregate leaf frames and full
    stacks by count — the py-spy/pprof-CPU-profile shape, curl-able."""
    me = threading.get_ident()
    deadline = time.monotonic() + min(seconds, 30.0)
    interval = 1.0 / hz
    leaf: collections.Counter = collections.Counter()
    stacks: collections.Counter = collections.Counter()
    samples = 0
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            samples += 1
            f = frame
            leaf[f"{f.f_code.co_filename}:{f.f_lineno} {f.f_code.co_name}"] += 1
            parts = []
            while f is not None and len(parts) < 12:
                parts.append(f.f_code.co_name)
                f = f.f_back
            stacks[" < ".join(parts)] += 1
        time.sleep(interval)
    out = [f"{samples} samples over {seconds}s at {hz:.0f}Hz (all threads)"]
    out.append("\n--- top leaf frames ---")
    for frame_id, n in leaf.most_common(25):
        out.append(f"{n:6d}  {frame_id}")
    out.append("\n--- top stacks ---")
    for stack, n in stacks.most_common(15):
        out.append(f"{n:6d}  {stack}")
    return "\n".join(out) + "\n"


def _vars_text() -> str:
    from banyandb_tpu.admin.protector import process_rss

    return (
        f"threads: {threading.active_count()}\n"
        f"gc_counts: {gc.get_count()}\n"
        f"gc_objects: {len(gc.get_objects())}\n"
        f"rss_bytes: {process_rss()}\n"
    )


class ProfilingServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    if u.path == "/debug/threads":
                        body = _threads_text()
                    elif u.path == "/debug/tracemalloc":
                        body = _tracemalloc_text(int(q.get("top", ["20"])[0]))
                    elif u.path == "/debug/profile":
                        body = _profile_text(float(q.get("seconds", ["5"])[0]))
                    elif u.path == "/debug/vars":
                        body = _vars_text()
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # noqa: BLE001
                    self.send_error(500, str(e))
                    return
                raw = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="profiling"
        )
        self._thread.start()
        return self

    def stop(self):
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()
