"""On-disk inspection tools (banyand/cmd/dump + bydbctl analyze analog).

Read-only walkers over a server root: groups -> segments -> shards ->
parts with block stats, plus column-level detail for one part.
"""

from __future__ import annotations

from pathlib import Path

from banyandb_tpu.storage.part import Part


def inspect_root(root: str | Path) -> dict:
    """Summarize every engine tree under <root>/data."""
    root = Path(root)
    out: dict = {"engines": {}}
    for engine_dir in sorted((root / "data").glob("*")):
        if not engine_dir.is_dir():
            continue
        groups = {}
        for group_dir in sorted(engine_dir.glob("*")):
            if not group_dir.is_dir():
                continue
            segments = {}
            for seg_dir in sorted(group_dir.glob("seg-*")):
                shards = {}
                for shard_dir in sorted(seg_dir.glob("shard-*")):
                    parts = []
                    for part_dir in sorted(shard_dir.glob("part-*")):
                        try:
                            p = Part(part_dir)
                        except Exception:
                            parts.append({"name": part_dir.name, "error": "unreadable"})
                            continue
                        parts.append(
                            {
                                "name": p.name,
                                "rows": p.total_count,
                                "blocks": len(p.blocks),
                                "min_ts": p.min_ts,
                                "max_ts": p.max_ts,
                                "resource": p.meta.get("measure")
                                or p.meta.get("stream")
                                or p.meta.get("trace"),
                                "bytes": sum(
                                    f.stat().st_size for f in part_dir.iterdir()
                                ),
                            }
                        )
                    shards[shard_dir.name] = {
                        "parts": parts,
                        "rows": sum(x.get("rows", 0) for x in parts),
                    }
                segments[seg_dir.name] = shards
            groups[group_dir.name] = segments
        out["engines"][engine_dir.name] = groups
    return out


def inspect_property_index(idx_dir: str | Path) -> dict:
    """Segment-level stats for one property shard index
    (``<root>/data/property/<group>/shard-N.idx`` — cmd/dump property
    analog).  Read-only: manifest + per-segment headers and tombstone
    counts, never a doc materialization."""
    from banyandb_tpu.index.segment import Segment
    from banyandb_tpu.utils import fs

    idx_dir = Path(idx_dir)
    man_path = idx_dir / "manifest.json"
    if not man_path.exists():
        raise ValueError(
            f"dump: {idx_dir} has no manifest.json — not a property "
            "shard index (expected <root>/data/property/<group>/"
            "shard-N.idx)"
        )
    man = fs.read_json(man_path)
    segments = []
    for ent in man.get("segments", []):
        name, gen = ent["name"], ent.get("tomb_gen", 0)
        tomb = idx_dir / f"{name}.tomb-{gen}" if gen else None
        seg = Segment(idx_dir / f"{name}.seg", tomb_path=tomb)
        try:
            segments.append(
                {
                    "name": name,
                    "docs": seg.n,
                    "alive": seg.alive_count,
                    "tomb_gen": gen,
                    "keyword_fields": list(seg.kw_fields),
                    "numeric_fields": list(seg.num_fields),
                    "bytes": (idx_dir / f"{name}.seg").stat().st_size,
                }
            )
        finally:
            seg.close()
    return {
        "manifest": man,
        "segments": segments,
        "docs": sum(s["docs"] for s in segments),
        "alive": sum(s["alive"] for s in segments),
    }


def inspect_part(part_dir: str | Path) -> dict:
    """Column-level stats for one part (cmd/dump measure analog).

    ``zone_maps`` reports whether every block carries the per-column
    zone maps the planner skips on (parts written before the zone-map
    format upgrade load and scan fine, they just never skip — this is
    how an operator tells the two apart)."""
    p = Part(part_dir)
    part_dir = Path(part_dir)
    cols = {}
    for f in sorted(part_dir.iterdir()):
        cols[f.name] = f.stat().st_size
    return {
        "meta": p.meta,
        "files": cols,
        "zone_maps": p.has_zone_maps(),
        "blocks": [
            {
                "count": b["count"],
                "ts": [b["min_ts"], b["max_ts"]],
                "series": [b["min_series"], b["max_series"]],
                **(
                    {"zones": sorted(b["zones"])}
                    if "zones" in b
                    else {}
                ),
            }
            for b in p.blocks
        ],
    }
