"""FODCService on the wire: proxy-side servicer + agent-side client.

Implements banyandb.fodc.v1.FODCService (the reference's agent<->proxy
plane, /root/reference/api/proto/banyandb/fodc/v1/rpc.proto:29 served by
fodc/proxy/internal/grpc/service.go) on the generated protos: six bidi
streams, all agent-initiated.  Agents dial the proxy, register with an
identity, then push metrics/topology/lifecycle/crash data; the
pressure-profiles stream is proxy-driven (list/fetch commands down,
records/chunks up, correlated by request_id).

Correlation between streams of one agent uses gRPC metadata
('fodc-agent-id', assigned at registration) — equivalent to the
reference's per-connection AgentIdentity registry.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Optional

from banyandb_tpu.admin.fodc_agent import RawMetric

SERVICE = "banyandb.fodc.v1.FODCService"
AGENT_ID_MD = "fodc-agent-id"
HEARTBEAT_S = 30
CHUNK_BYTES = 1 << 20


def _now_ts():
    from google.protobuf import timestamp_pb2

    ts = timestamp_pb2.Timestamp()
    ts.GetCurrentTime()
    return ts


class AgentState:
    """Everything the proxy knows about one registered agent."""

    def __init__(self, agent_id: str, identity: dict):
        self.agent_id = agent_id
        self.identity = identity  # node_role, labels, pod_name, containers
        self.last_seen = time.time()
        self.metrics: list[RawMetric] = []
        self.metric_history: list[tuple[float, list[RawMetric]]] = []
        self.topology: Optional[dict] = None
        self.lifecycle: Optional[dict] = None
        self.crashes: list[dict] = []
        # pressure-profile command plane: proxy pushes commands, the
        # stream handler routes replies to the issuing waiter by request_id
        self.pp_commands: "queue.Queue" = queue.Queue()
        self.pp_waiters: dict[str, "queue.Queue"] = {}
        self.pp_connected = False


class FodcProxyState:
    """Shared registry behind the servicer and the REST API."""

    HISTORY_CYCLES = 256

    def __init__(self):
        self._lock = threading.Lock()
        self.agents: dict[str, AgentState] = {}

    def register(self, identity: dict) -> AgentState:
        agent_id = uuid.uuid4().hex[:12]
        st = AgentState(agent_id, identity)
        with self._lock:
            self.agents[agent_id] = st
        return st

    def get(self, agent_id: str) -> Optional[AgentState]:
        with self._lock:
            return self.agents.get(agent_id)

    def by_pod(self, pod_name: str) -> Optional[AgentState]:
        with self._lock:
            for st in self.agents.values():
                if st.identity.get("pod_name") == pod_name:
                    return st
        return None

    def all_agents(self) -> list[AgentState]:
        with self._lock:
            return list(self.agents.values())

    def record_metrics(self, st: AgentState, metrics: list[RawMetric]) -> None:
        now = time.time()
        with self._lock:
            st.metrics = metrics
            st.last_seen = now
            st.metric_history.append((now, metrics))
            if len(st.metric_history) > self.HISTORY_CYCLES:
                st.metric_history.pop(0)


def _agent_from_context(state: FodcProxyState, context) -> Optional[AgentState]:
    for k, v in context.invocation_metadata():
        if k == AGENT_ID_MD:
            return state.get(v)
    return None


def _metric_to_raw(m) -> RawMetric:
    from banyandb_tpu.api import pb

    f = pb.fodc_rpc_pb2
    type_name = {
        f.METRIC_TYPE_GAUGE: "gauge",
        f.METRIC_TYPE_COUNTER: "counter",
        f.METRIC_TYPE_HISTOGRAM: "histogram",
        f.METRIC_TYPE_SUMMARY: "summary",
    }.get(m.type, "untyped")
    return RawMetric(
        name=m.name,
        labels=tuple(sorted(m.labels.items())),
        value=m.value,
        type=type_name,
        ts_millis=m.timestamp.ToMilliseconds() if m.HasField("timestamp") else 0,
    )


def _raw_to_metric(m: RawMetric):
    from banyandb_tpu.api import pb

    f = pb.fodc_rpc_pb2
    type_enum = {
        "gauge": f.METRIC_TYPE_GAUGE,
        "counter": f.METRIC_TYPE_COUNTER,
        "histogram": f.METRIC_TYPE_HISTOGRAM,
        "summary": f.METRIC_TYPE_SUMMARY,
    }.get(m.type, f.METRIC_TYPE_UNTYPED)
    out = f.Metric(name=m.name, value=m.value, type=type_enum)
    for k, v in m.labels:
        out.labels[str(k)] = str(v)
    if m.ts_millis:
        out.timestamp.FromMilliseconds(m.ts_millis)
    return out


def generic_handler(state: FodcProxyState):
    """Build the FODCService generic handler for a grpc server
    (co-hosted on the proxy's GrpcBusServer via extra_handlers)."""
    import grpc

    from banyandb_tpu.api import pb

    f = pb.fodc_rpc_pb2

    def register_agent(req_iter, context):
        first = next(req_iter, None)
        if first is None:
            return
        st = state.register(
            {
                "node_role": first.node_role,
                "labels": dict(first.labels),
                "pod_name": first.pod_name,
                "container_names": list(first.container_names),
            }
        )
        yield f.RegisterAgentResponse(
            success=True,
            message="registered",
            heartbeat_interval_seconds=HEARTBEAT_S,
            agent_id=st.agent_id,
        )
        for _hb in req_iter:  # subsequent requests are heartbeats
            st.last_seen = time.time()
            yield f.RegisterAgentResponse(success=True, agent_id=st.agent_id)

    def stream_metrics(req_iter, context):
        st = _agent_from_context(state, context)
        if st is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "agent not registered")
        for req in req_iter:
            state.record_metrics(st, [_metric_to_raw(m) for m in req.metrics])
        return
        yield  # pragma: no cover - makes this a generator

    def stream_topology(req_iter, context):
        st = _agent_from_context(state, context)
        if st is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "agent not registered")
        # prompt once, then consume pushes
        yield f.StreamClusterTopologyResponse(request_topology=True)
        for req in req_iter:
            st.topology = {
                "nodes": [
                    {"name": n.metadata.name, "roles": list(n.roles)}
                    for n in req.topology.nodes
                ],
                "calls": [
                    {"id": c.id, "source": c.source, "target": c.target}
                    for c in req.topology.calls
                ],
            }
            st.last_seen = time.time()

    def stream_lifecycle(req_iter, context):
        st = _agent_from_context(state, context)
        if st is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "agent not registered")
        for req in req_iter:
            st.lifecycle = {
                "pod_name": req.pod_name,
                "groups": [
                    {
                        "name": g.name,
                        "catalog": g.catalog,
                        "errors": list(g.errors),
                        "data_info_count": len(g.data_info),
                    }
                    for g in req.lifecycle_data.groups
                ],
                "reports": [
                    {"filename": r.filename}
                    for r in req.lifecycle_data.reports
                ],
            }
            st.last_seen = time.time()
        return
        yield  # pragma: no cover

    def stream_crash(req_iter, context):
        st = _agent_from_context(state, context)
        if st is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "agent not registered")
        yield f.StreamCrashDiagnosticsResponse(request_diagnostics=True)
        for req in req_iter:
            rec = {
                "artifact_dir": req.artifact_dir,
                "files": list(req.files),
                "component": req.panic_record.component,
                "panic_value": req.panic_record.panic_value,
                "recovered": req.panic_record.recovered,
            }
            st.crashes.append(rec)
            del st.crashes[:-32]  # bounded
            st.last_seen = time.time()

    def stream_pressure(req_iter, context):
        st = _agent_from_context(state, context)
        if st is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "agent not registered")
        st.pp_connected = True

        def reader():
            try:
                for req in req_iter:
                    which = req.WhichOneof("payload")
                    if which == "record":
                        rec = req.record
                        payload = {
                            "profile_id": rec.profile_id,
                            "rss_bytes": rec.rss_bytes,
                            "cgroup_limit_bytes": rec.cgroup_limit_bytes,
                            "trigger_percent": rec.trigger_percent,
                            "threshold_bytes": rec.threshold_bytes,
                            "profiles": [
                                {
                                    "type": p.type,
                                    "filename": p.filename,
                                    "filepath": p.filepath,
                                    "format": p.format,
                                    "size_bytes": p.size_bytes,
                                }
                                for p in rec.profiles
                            ],
                        }
                        for w in list(st.pp_waiters.values()):
                            w.put(("record", payload))
                    elif which == "list_complete":
                        w = st.pp_waiters.get(req.list_complete.request_id)
                        if w is not None:
                            w.put(("done", None))
                    elif which == "chunk":
                        ch = req.chunk
                        w = st.pp_waiters.get(ch.request_id)
                        if w is not None:
                            if ch.error:
                                w.put(("error", ch.error))
                            else:
                                w.put(("chunk", ch.data))
                                if ch.last:
                                    w.put(("done", None))
            except Exception:  # noqa: BLE001 - stream cancel at teardown
                pass
            finally:
                st.pp_connected = False
                st.pp_commands.put(None)  # unblock the writer

        t = threading.Thread(target=reader, daemon=True, name="fodc-pp-reader")
        t.start()
        while True:
            cmd = st.pp_commands.get()
            if cmd is None:
                return
            yield cmd

    def h(fn, req_cls):
        return grpc.stream_stream_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )

    return grpc.method_handlers_generic_handler(
        SERVICE,
        {
            "RegisterAgent": h(register_agent, f.RegisterAgentRequest),
            "StreamMetrics": h(stream_metrics, f.StreamMetricsRequest),
            "StreamClusterTopology": h(stream_topology, f.StreamClusterTopologyRequest),
            "StreamLifecycle": h(stream_lifecycle, f.StreamLifecycleRequest),
            "StreamCrashDiagnostics": h(stream_crash, f.StreamCrashDiagnosticsRequest),
            "StreamPressureProfiles": h(stream_pressure, f.StreamPressureProfilesRequest),
        },
    )


# -- proxy-driven pressure-profile commands (used by the REST API) ----------


def list_pressure_profiles(st: AgentState, timeout: float = 10.0) -> list[dict]:
    """Ask one agent for all capture-event metadata (ListProfiles)."""
    from banyandb_tpu.api import pb

    f = pb.fodc_rpc_pb2
    if not st.pp_connected:
        raise ConnectionError(f"agent {st.agent_id} pressure stream not connected")
    rid = uuid.uuid4().hex
    w: "queue.Queue" = queue.Queue()
    st.pp_waiters[rid] = w
    try:
        st.pp_commands.put(
            f.StreamPressureProfilesResponse(
                list_profiles=f.ListProfiles(request_id=rid)
            )
        )
        records = []
        deadline = time.monotonic() + timeout
        while True:
            kind, payload = w.get(timeout=max(0.0, deadline - time.monotonic()))
            if kind == "done":
                return records
            if kind == "record":
                records.append(payload)
    finally:
        st.pp_waiters.pop(rid, None)


def fetch_pressure_profile(
    st: AgentState, profile_id: str, kind: str, filepath: str = "", timeout: float = 30.0
) -> bytes:
    """Download one profile's bytes from an agent (FetchPressureProfile)."""
    from banyandb_tpu.api import pb

    f = pb.fodc_rpc_pb2
    if not st.pp_connected:
        raise ConnectionError(f"agent {st.agent_id} pressure stream not connected")
    rid = uuid.uuid4().hex
    w: "queue.Queue" = queue.Queue()
    st.pp_waiters[rid] = w
    try:
        st.pp_commands.put(
            f.StreamPressureProfilesResponse(
                fetch_profile=f.FetchPressureProfile(
                    request_id=rid,
                    profile_id=profile_id,
                    type=kind,
                    filepath=filepath,
                )
            )
        )
        buf = bytearray()
        deadline = time.monotonic() + timeout
        while True:
            k, payload = w.get(timeout=max(0.0, deadline - time.monotonic()))
            if k == "done":
                return bytes(buf)
            if k == "chunk":
                buf.extend(payload)
            elif k == "error":
                raise FileNotFoundError(payload)
    finally:
        st.pp_waiters.pop(rid, None)


# -- agent side --------------------------------------------------------------


class FodcAgentClient:
    """Per-node client: registers with the proxy and keeps the push
    streams alive (fodc agent's proxy client analog).

    recorder: FlightRecorder to stream metric cycles from.
    profiler: optional PressureProfiler answering list/fetch commands.
    """

    def __init__(
        self,
        addr: str,
        *,
        node_role: str,
        pod_name: str,
        labels: Optional[dict] = None,
        recorder=None,
        profiler=None,
        push_interval_s: float = 5.0,
    ):
        import grpc

        self.channel = grpc.insecure_channel(addr)
        self.node_role = node_role
        self.pod_name = pod_name
        self.labels = dict(labels or {})
        self.recorder = recorder
        self.profiler = profiler
        self.push_interval_s = push_interval_s
        self.agent_id: Optional[str] = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _method(self, name: str, md: bool = True):
        from banyandb_tpu.api import pb

        f = pb.fodc_rpc_pb2
        resp_cls = {
            "RegisterAgent": f.RegisterAgentResponse,
            "StreamMetrics": f.StreamMetricsResponse,
            "StreamPressureProfiles": f.StreamPressureProfilesResponse,
        }[name]
        kw = {}
        if md and self.agent_id:
            kw["metadata"] = ((AGENT_ID_MD, self.agent_id),)
        mc = self.channel.stream_stream(
            f"/{SERVICE}/{name}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        return mc, kw

    def register(self, timeout: float = 10.0) -> str:
        from banyandb_tpu.api import pb

        f = pb.fodc_rpc_pb2

        def reqs():
            yield f.RegisterAgentRequest(
                node_role=self.node_role,
                pod_name=self.pod_name,
                labels=self.labels,
            )
            # keep the stream open for heartbeats until stopped
            while not self._stop.wait(HEARTBEAT_S):
                yield f.RegisterAgentRequest(node_role=self.node_role)

        mc, kw = self._method("RegisterAgent", md=False)
        resp_iter = mc(reqs(), **kw)
        first = next(iter(resp_iter))
        if not first.success:
            raise ConnectionError(f"registration rejected: {first.message}")
        self.agent_id = first.agent_id

        def drain():
            try:
                for _ in resp_iter:
                    pass
            except Exception:  # noqa: BLE001 - stream teardown
                pass

        t = threading.Thread(target=drain, daemon=True, name="fodc-agent-reg")
        t.start()
        self._threads.append(t)
        return self.agent_id

    def start_metrics_push(self) -> None:
        from banyandb_tpu.api import pb

        f = pb.fodc_rpc_pb2

        def reqs():
            while not self._stop.wait(self.push_interval_s):
                cycle = self.recorder.latest() if self.recorder else []
                req = f.StreamMetricsRequest(
                    metrics=[_raw_to_metric(m) for m in cycle]
                )
                req.timestamp.GetCurrentTime()
                yield req

        def run():
            mc, kw = self._method("StreamMetrics")
            try:
                for _ in mc(reqs(), **kw):
                    pass
            except Exception:  # noqa: BLE001 - push loop dies with the channel
                pass

        t = threading.Thread(target=run, daemon=True, name="fodc-agent-metrics")
        t.start()
        self._threads.append(t)

    def push_metrics_once(self) -> None:
        """Synchronous single push (tests + low-rate deployments)."""
        from banyandb_tpu.api import pb

        f = pb.fodc_rpc_pb2
        cycle = self.recorder.latest() if self.recorder else []
        req = f.StreamMetricsRequest(metrics=[_raw_to_metric(m) for m in cycle])
        req.timestamp.GetCurrentTime()
        mc, kw = self._method("StreamMetrics")
        for _ in mc(iter([req]), **kw):
            pass

    def start_pressure_serving(self) -> None:
        """Answer the proxy's list/fetch commands from the local profiler."""
        from banyandb_tpu.api import pb

        f = pb.fodc_rpc_pb2
        outq: "queue.Queue" = queue.Queue()

        def reqs():
            while True:
                item = outq.get()
                if item is None:
                    return
                yield item

        def serve():
            mc, kw = self._method("StreamPressureProfiles")
            try:
                for cmd in mc(reqs(), **kw):
                    which = cmd.WhichOneof("command")
                    if which == "list_profiles":
                        rid = cmd.list_profiles.request_id
                        for rec in (
                            self.profiler.list_records() if self.profiler else []
                        ):
                            msg = f.StreamPressureProfilesRequest(
                                record=f.PressureProfileRecord(
                                    profile_id=rec["profile_id"],
                                    rss_bytes=int(rec.get("rss_bytes", 0)),
                                    cgroup_limit_bytes=int(
                                        rec.get("cgroup_limit_bytes", 0)
                                    ),
                                    trigger_percent=int(
                                        rec.get("trigger_percent", 0)
                                    ),
                                    threshold_bytes=int(
                                        rec.get("threshold_bytes", 0)
                                    ),
                                    profiles=[
                                        f.PressureProfileInfo(
                                            type=p["type"],
                                            filename=p["filename"],
                                            filepath=p["filepath"],
                                            format=p["format"],
                                            size_bytes=int(p["size_bytes"]),
                                        )
                                        for p in rec.get("profiles", [])
                                    ],
                                )
                            )
                            outq.put(msg)
                        outq.put(
                            f.StreamPressureProfilesRequest(
                                list_complete=f.ListComplete(request_id=rid)
                            )
                        )
                    elif which == "fetch_profile":
                        fp = cmd.fetch_profile
                        try:
                            data = self.profiler.read_profile(
                                fp.profile_id, fp.type
                            )
                        except Exception as e:  # noqa: BLE001 - report over the wire
                            outq.put(
                                f.StreamPressureProfilesRequest(
                                    chunk=f.PressureProfileChunk(
                                        request_id=fp.request_id,
                                        profile_id=fp.profile_id,
                                        type=fp.type,
                                        error=f"{type(e).__name__}: {e}",
                                    )
                                )
                            )
                            continue
                        for off in range(0, max(len(data), 1), CHUNK_BYTES):
                            part = data[off : off + CHUNK_BYTES]
                            outq.put(
                                f.StreamPressureProfilesRequest(
                                    chunk=f.PressureProfileChunk(
                                        request_id=fp.request_id,
                                        profile_id=fp.profile_id,
                                        type=fp.type,
                                        data=part,
                                        last=off + CHUNK_BYTES >= len(data),
                                    )
                                )
                            )
            except Exception:  # noqa: BLE001 - channel teardown ends serving
                pass
            finally:
                outq.put(None)

        t = threading.Thread(target=serve, daemon=True, name="fodc-agent-pp")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        try:
            self.channel.close()
        except Exception:  # noqa: BLE001
            pass
