"""Offline format migration tool (banyand/cmd/migration analyze/plan/
copy/verify + banyand/internal/migration analog).

Four phases over a server root:
  analyze -> inventory of parts + format versions + sizes
  plan    -> which parts a target format version requires rewriting
  copy    -> rewrite planned parts into a NEW root (source untouched)
  verify  -> row-count + column-checksum comparison source vs target

The current on-disk format is version 1; the tool is the harness future
format bumps plug into (rewrite = decode with the old reader, re-encode
with the current writer).
"""

from __future__ import annotations

import hashlib
import shutil
from pathlib import Path

from banyandb_tpu.storage.part import Part, PartWriter

FORMAT_VERSION = 1


def _iter_parts(root: Path):
    for part_dir in sorted((root / "data").glob("*/*/seg-*/shard-*/part-*")):
        yield part_dir


def analyze(root: str | Path) -> dict:
    root = Path(root)
    parts = []
    for pd in _iter_parts(root):
        try:
            p = Part(pd)
            parts.append(
                {
                    "dir": str(pd.relative_to(root)),
                    "rows": p.total_count,
                    "version": p.meta.get("format_version", 1),
                    "bytes": sum(f.stat().st_size for f in pd.iterdir()),
                }
            )
        except Exception as e:  # noqa: BLE001 - analysis must not abort
            parts.append({"dir": str(pd.relative_to(root)), "error": str(e)})
    return {"format_version": FORMAT_VERSION, "parts": parts}


def plan(root: str | Path, target_version: int = FORMAT_VERSION) -> dict:
    info = analyze(root)
    rewrite = [
        p["dir"]
        for p in info["parts"]
        if "error" not in p and p["version"] != target_version
    ]
    return {
        "target_version": target_version,
        "rewrite": rewrite,
        "unreadable": [p["dir"] for p in info["parts"] if "error" in p],
        "unchanged": [
            p["dir"]
            for p in info["parts"]
            if "error" not in p and p["version"] == target_version
        ],
    }


def copy(root: str | Path, dest: str | Path, migration_plan: dict) -> dict:
    """Materialize `dest`: planned parts re-encoded, the rest (and all
    non-part files: schema, snapshots, indexes) copied verbatim."""
    root, dest = Path(root), Path(dest)
    if dest.exists() and any(dest.iterdir()):
        raise FileExistsError(f"copy target {dest} not empty")
    rewrite = set(migration_plan["rewrite"])
    copied = rewritten = 0
    for src in sorted(root.rglob("*")):
        rel = src.relative_to(root)
        out = dest / rel
        if src.is_dir():
            continue
        part_rel = _enclosing_part(rel)
        if part_rel is not None and part_rel in rewrite:
            continue  # handled below, whole-part
        out.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, out)
        copied += 1
    for part_rel in sorted(rewrite):
        p = Part(root / part_rel)
        cols = p.read(
            range(len(p.blocks)),
            tags=p.meta["tags"],
            fields=p.meta["fields"],
            want_payload=bool(p.meta.get("has_payload")),
            cached=False,  # one-shot migration sweep
        )
        extra = {
            k: p.meta[k]
            for k in ("measure", "stream", "trace")
            if k in p.meta
        }
        PartWriter.write(
            dest / part_rel,
            ts=cols.ts,
            series=cols.series,
            version=cols.version,
            tag_codes=dict(cols.tags),
            tag_dicts=dict(cols.dicts),
            fields=dict(cols.fields),
            extra_meta=extra,
            payloads=cols.payloads,
        )
        rewritten += 1
    return {"copied_files": copied, "rewritten_parts": rewritten}


def _enclosing_part(rel: Path):
    for i, part in enumerate(rel.parts):
        if part.startswith("part-"):
            return str(Path(*rel.parts[: i + 1]))
    return None


def _part_fingerprint(pd: Path) -> tuple[int, dict[str, str]]:
    """(rows, per-column content hash of DECODED data) — encoding may
    legally differ between versions; the decoded values must not."""
    p = Part(pd)
    cols = p.read(
        range(len(p.blocks)),
        tags=p.meta["tags"],
        fields=p.meta["fields"],
        want_payload=bool(p.meta.get("has_payload")),
        cached=False,  # one-shot migration sweep
    )
    sums = {
        "ts": hashlib.blake2b(cols.ts.tobytes(), digest_size=8).hexdigest(),
        "series": hashlib.blake2b(cols.series.tobytes(), digest_size=8).hexdigest(),
    }
    for t, codes in sorted(cols.tags.items()):
        vals = b"\x00".join(cols.dicts[t][c] for c in codes.tolist())
        sums[f"tag:{t}"] = hashlib.blake2b(vals, digest_size=8).hexdigest()
    for f, v in sorted(cols.fields.items()):
        sums[f"field:{f}"] = hashlib.blake2b(v.tobytes(), digest_size=8).hexdigest()
    if cols.payloads is not None:
        sums["payload"] = hashlib.blake2b(
            b"\x00".join(cols.payloads), digest_size=8
        ).hexdigest()
    return p.total_count, sums


def verify(root: str | Path, dest: str | Path) -> dict:
    """Decoded-content equality for every part present in both trees."""
    root, dest = Path(root), Path(dest)
    mismatches = []
    checked = 0
    for pd in _iter_parts(root):
        rel = pd.relative_to(root)
        other = dest / rel
        if not other.exists():
            mismatches.append({"part": str(rel), "error": "missing in target"})
            continue
        try:
            a = _part_fingerprint(pd)
            b = _part_fingerprint(other)
        except Exception as e:  # noqa: BLE001
            mismatches.append({"part": str(rel), "error": str(e)})
            continue
        if a != b:
            mismatches.append({"part": str(rel), "error": "content diverged"})
        checked += 1
    return {"checked": checked, "mismatches": mismatches, "ok": not mismatches}
