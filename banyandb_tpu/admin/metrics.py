"""Metrics facade (pkg/meter analog) with two sinks:

- in-memory registry with Prometheus text exposition
  (pkg/meter/prom analog — scrape via the server's "metrics" topic),
  now with exponential-bucket histograms so latency quantiles are
  recoverable from ``/metrics`` (the core lives in obs/metrics.py at
  the platform layer; this module re-exports it for admin callers),
- self-measure writer: periodic dump of all instruments as data points
  into the `_monitoring` group (pkg/meter/native/provider.go:39,81
  analog), so the database monitors itself with its own query engine.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from banyandb_tpu.obs.metrics import (  # noqa: F401 - the admin surface
    DEFAULT_BOUNDS,
    Histogram,
    Meter,
    global_meter,
)


class SelfMeasureSink:
    """Write instruments as measure points into `_monitoring`
    (the reference's native meter provider).

    ``start()`` runs a background flusher (``bydb-self-measure``) so the
    group is periodically populated without operator action; histograms
    land as count/sum plus p50/p99 estimates so the self-measures carry
    the same attribution ``/metrics`` does."""

    GROUP = "_monitoring"
    MEASURE = "instruments"
    DEFAULT_INTERVAL_S = 30.0

    def __init__(
        self,
        meter: Meter,
        measure_engine,
        interval_s: Optional[float] = None,
    ):
        from banyandb_tpu.utils.envflag import env_float

        self.meter = meter
        self.engine = measure_engine
        self.interval_s = (
            interval_s
            if interval_s is not None
            else env_float("BYDB_SELF_MEASURE_INTERVAL_S", self.DEFAULT_INTERVAL_S)
        )
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ensure_schema()

    def _ensure_schema(self) -> None:
        from banyandb_tpu.api.schema import (
            Catalog,
            Entity,
            FieldSpec,
            FieldType,
            Group,
            Measure,
            ResourceOpts,
            TagSpec,
            TagType,
        )

        reg = self.engine.registry
        try:
            reg.get_group(self.GROUP)
        except KeyError:
            reg.create_group(
                Group(self.GROUP, Catalog.MEASURE, ResourceOpts(shard_num=1))
            )
        try:
            reg.get_measure(self.GROUP, self.MEASURE)
        except KeyError:
            reg.create_measure(
                Measure(
                    group=self.GROUP,
                    name=self.MEASURE,
                    tags=(
                        TagSpec("name", TagType.STRING),
                        TagSpec("kind", TagType.STRING),
                    ),
                    fields=(FieldSpec("value", FieldType.FLOAT),),
                    entity=Entity(("name", "kind")),
                )
            )

    # -- periodic flusher ---------------------------------------------------
    def start(self) -> None:
        """Populate `_monitoring` on a cadence (idempotent)."""
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="bydb-self-measure", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.flush()
            except Exception:  # noqa: BLE001 - the sink must not die with
                # a transient engine error (e.g. mid-shutdown write refusal)
                import logging

                logging.getLogger(__name__).exception("self-measure flush failed")

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None

    def flush(self, now_millis: Optional[int] = None) -> int:
        from banyandb_tpu.api.model import DataPointValue, WriteRequest

        ts = now_millis or int(time.time() * 1000)
        snap = self.meter.snapshot()
        points = []
        def add(kind: str, name: str, lbls: tuple, value: float):
            label_sfx = ",".join(f"{k}={val}" for k, val in lbls)
            full = f"{name}|{label_sfx}" if label_sfx else name
            points.append(
                DataPointValue(
                    ts_millis=ts,
                    tags={"name": full, "kind": kind},
                    fields={"value": float(value)},
                    version=ts,
                )
            )

        for (name, lbls), v in snap["counters"].items():
            add("counter", name, lbls, v)
        for (name, lbls), v in snap["gauges"].items():
            add("gauge", name, lbls, v)
        for (name, lbls), (count, total) in snap["histograms"].items():
            add("histogram_count", name, lbls, count)
            add("histogram_sum", name, lbls, total)
            bounds, counts = snap["hist_buckets"][(name, lbls)]
            if count:
                from banyandb_tpu.obs.metrics import quantile_from_buckets

                for q, kind in ((0.5, "histogram_p50"), (0.99, "histogram_p99")):
                    add(
                        kind, name, lbls,
                        quantile_from_buckets(bounds, counts, count, q),
                    )
        if points:
            self.engine.write(
                WriteRequest(self.GROUP, self.MEASURE, tuple(points)),
                _internal=True,
            )
        return len(points)
