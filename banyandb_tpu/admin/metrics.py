"""Metrics facade (pkg/meter analog) with two sinks:

- in-memory registry with Prometheus text exposition
  (pkg/meter/prom analog — scrape via the server's "metrics" topic),
- self-measure writer: periodic dump of all instruments as data points
  into the `_monitoring` group (pkg/meter/native/provider.go:39,81
  analog), so the database monitors itself with its own query engine.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Optional


class Meter:
    """Scoped instrument registry: counters, gauges, histograms."""

    def __init__(self, scope: str = ""):
        self.scope = scope
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        # histograms keep running (count, sum) — bounded memory per key
        self._hist: dict[tuple, tuple[int, float]] = {}

    def _key(self, name: str, labels: Optional[dict]) -> tuple:
        return (name, tuple(sorted((labels or {}).items())))

    def counter_add(self, name: str, value: float = 1.0, labels: Optional[dict] = None):
        with self._lock:
            self._counters[self._key(name, labels)] += value

    def gauge_set(self, name: str, value: float, labels: Optional[dict] = None):
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, value: float, labels: Optional[dict] = None):
        with self._lock:
            k = self._key(name, labels)
            count, total = self._hist.get(k, (0, 0.0))
            self._hist[k] = (count + 1, total + value)

    # -- exposition ---------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": dict(self._hist),
            }

    def prometheus_text(self) -> str:
        """Prometheus exposition format (pkg/meter/prom analog)."""
        pfx = (self.scope + "_") if self.scope else ""
        lines = []

        def fmt_labels(lbls: tuple) -> str:
            if not lbls:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in lbls)
            return "{" + inner + "}"

        snap = self.snapshot()
        for (name, lbls), v in sorted(snap["counters"].items()):
            lines.append(f"{pfx}{name}_total{fmt_labels(lbls)} {v}")
        for (name, lbls), v in sorted(snap["gauges"].items()):
            lines.append(f"{pfx}{name}{fmt_labels(lbls)} {v}")
        for (name, lbls), (count, total) in sorted(snap["histograms"].items()):
            lines.append(f"{pfx}{name}_count{fmt_labels(lbls)} {count}")
            lines.append(f"{pfx}{name}_sum{fmt_labels(lbls)} {total}")
        return "\n".join(lines) + "\n"


class SelfMeasureSink:
    """Write instruments as measure points into `_monitoring`
    (the reference's native meter provider)."""

    GROUP = "_monitoring"
    MEASURE = "instruments"

    def __init__(self, meter: Meter, measure_engine):
        self.meter = meter
        self.engine = measure_engine
        self._ensure_schema()

    def _ensure_schema(self) -> None:
        from banyandb_tpu.api.schema import (
            Catalog,
            Entity,
            FieldSpec,
            FieldType,
            Group,
            Measure,
            ResourceOpts,
            TagSpec,
            TagType,
        )

        reg = self.engine.registry
        try:
            reg.get_group(self.GROUP)
        except KeyError:
            reg.create_group(
                Group(self.GROUP, Catalog.MEASURE, ResourceOpts(shard_num=1))
            )
        try:
            reg.get_measure(self.GROUP, self.MEASURE)
        except KeyError:
            reg.create_measure(
                Measure(
                    group=self.GROUP,
                    name=self.MEASURE,
                    tags=(
                        TagSpec("name", TagType.STRING),
                        TagSpec("kind", TagType.STRING),
                    ),
                    fields=(FieldSpec("value", FieldType.FLOAT),),
                    entity=Entity(("name", "kind")),
                )
            )

    def flush(self, now_millis: Optional[int] = None) -> int:
        from banyandb_tpu.api.model import DataPointValue, WriteRequest

        ts = now_millis or int(time.time() * 1000)
        snap = self.meter.snapshot()
        points = []
        def add(kind: str, name: str, lbls: tuple, value: float):
            label_sfx = ",".join(f"{k}={val}" for k, val in lbls)
            full = f"{name}|{label_sfx}" if label_sfx else name
            points.append(
                DataPointValue(
                    ts_millis=ts,
                    tags={"name": full, "kind": kind},
                    fields={"value": float(value)},
                    version=ts,
                )
            )

        for (name, lbls), v in snap["counters"].items():
            add("counter", name, lbls, v)
        for (name, lbls), v in snap["gauges"].items():
            add("gauge", name, lbls, v)
        for (name, lbls), (count, total) in snap["histograms"].items():
            add("histogram_count", name, lbls, count)
            add("histogram_sum", name, lbls, total)
        if points:
            self.engine.write(
                WriteRequest(self.GROUP, self.MEASURE, tuple(points)),
                _internal=True,
            )
        return len(points)
