"""Backup & restore (banyand/backup/backup.go + restore.go analog).

Backups are time-dirs of the snapshot-consistent data tree:

    <dest>/<YYYYMMDDHHMMSS>/
        schema/...        # registry JSON
        data/...          # part dirs + snapshots + indexes

The remote-FS abstraction mirrors pkg/fs/remote: a tiny put/get/list
interface with a local-directory implementation; S3/GCS/Azure drivers
plug in behind the same surface (cloud SDKs aren't in this image, so
they're gated imports for deployments that have them).
"""

from __future__ import annotations

import datetime as dt
import shutil
from pathlib import Path
from typing import Optional, Protocol


class RemoteFS(Protocol):  # pkg/fs/remote FS interface analog
    def put(self, rel: str, local: Path) -> None: ...
    def get(self, rel: str, local: Path) -> None: ...
    def list(self, prefix: str) -> list[str]: ...


class LocalDirFS:
    """Local-directory RemoteFS (the dockertest/minio stand-in)."""

    def __init__(self, root: str | Path):
        self.root = Path(root)

    def put(self, rel: str, local: Path) -> None:
        dest = self.root / rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(local, dest)

    def get(self, rel: str, local: Path) -> None:
        local.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(self.root / rel, local)

    def list(self, prefix: str) -> list[str]:
        base = self.root / prefix
        if not base.exists():
            return []
        return sorted(
            str(p.relative_to(self.root))
            for p in base.rglob("*")
            if p.is_file()
        )


def _walk_files(root: Path):
    for p in sorted(root.rglob("*")):
        if p.is_file() and not p.name.startswith(".tmp"):
            yield p


def backup(
    server_root: str | Path,
    remote: RemoteFS,
    *,
    time_dir: Optional[str] = None,
    flush: Optional[callable] = None,
) -> str:
    """Snapshot (via the provided flush hook) then copy the tree.

    Returns the time-dir name (backup/timedir.go analog).
    """
    server_root = Path(server_root)
    if flush:
        flush()
    stamp = time_dir or dt.datetime.now(dt.timezone.utc).strftime("%Y%m%d%H%M%S")
    for f in _walk_files(server_root):
        rel = f.relative_to(server_root)
        remote.put(f"{stamp}/{rel}", f)
    return stamp


def list_backups(remote: RemoteFS) -> list[str]:
    stamps = {r.split("/", 1)[0] for r in remote.list("")}
    return sorted(stamps)


def restore(
    remote: RemoteFS, time_dir: str, server_root: str | Path
) -> int:
    """Materialize a backup into an empty server root. Returns file count."""
    server_root = Path(server_root)
    if server_root.exists() and any(server_root.iterdir()):
        raise FileExistsError(f"restore target {server_root} not empty")
    files = remote.list(time_dir)
    for rel in files:
        local_rel = rel.split("/", 1)[1]
        remote.get(rel, server_root / local_rel)
    return len(files)
