"""Backup & restore (banyand/backup/backup.go + restore.go analog).

Backups are time-dirs of the snapshot-consistent data tree:

    <dest>/<YYYYMMDDHHMMSS>/
        schema/...        # registry JSON
        data/...          # part dirs + snapshots + indexes

The remote-FS abstraction mirrors pkg/fs/remote: a tiny put/get/list
interface with a local-directory implementation; S3/GCS/Azure drivers
plug in behind the same surface (cloud SDKs aren't in this image, so
they're gated imports for deployments that have them).
"""

from __future__ import annotations

import datetime as dt
import shutil
from pathlib import Path
from typing import Optional, Protocol


class RemoteFS(Protocol):  # pkg/fs/remote FS interface analog
    def put(self, rel: str, local: Path) -> None: ...
    def get(self, rel: str, local: Path) -> None: ...
    def list(self, prefix: str) -> list[str]: ...


class LocalDirFS:
    """Local-directory RemoteFS (the dockertest/minio stand-in)."""

    def __init__(self, root: str | Path):
        self.root = Path(root)

    def put(self, rel: str, local: Path) -> None:
        dest = self.root / rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(local, dest)

    def get(self, rel: str, local: Path) -> None:
        local.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(self.root / rel, local)

    def list(self, prefix: str) -> list[str]:
        base = self.root / prefix
        if not base.exists():
            return []
        return sorted(
            str(p.relative_to(self.root))
            for p in base.rglob("*")
            if p.is_file()
        )


# the shared prefix/key base lives with the platform-level drivers
# (utils/object_store.py) so this L6 module depends strictly downward
from banyandb_tpu.utils.object_store import _PrefixedCloudFS


class S3FS(_PrefixedCloudFS):
    """S3 RemoteFS driver (pkg/fs/remote/aws analog). Gated import: boto3
    is not in the base image; deployments that have it get the driver."""

    def __init__(self, bucket: str, prefix: str = "", client=None):
        if client is None:
            try:
                import boto3  # noqa: PLC0415 - gated optional dependency
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "S3FS needs boto3 (not in the base image)"
                ) from e
            client = boto3.client("s3")
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.client = client

    def put(self, rel: str, local: Path) -> None:
        self.client.upload_file(str(local), self.bucket, self._key(rel))

    def get(self, rel: str, local: Path) -> None:
        local.parent.mkdir(parents=True, exist_ok=True)
        self.client.download_file(self.bucket, self._key(rel), str(local))

    def _iter_keys(self, probe: str):
        paginator = self.client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=probe):
            for obj in page.get("Contents", []):
                yield obj["Key"]


class GcsFS(_PrefixedCloudFS):
    """GCS RemoteFS driver (pkg/fs/remote/gcp analog). Gated import."""

    def __init__(self, bucket: str, prefix: str = "", client=None):
        if client is None:
            try:
                from google.cloud import storage  # noqa: PLC0415
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "GcsFS needs google-cloud-storage (not in the base image)"
                ) from e
            client = storage.Client()
        if not hasattr(client, "bucket"):
            raise TypeError("GcsFS client must expose .bucket(name)")
        self.bucket = client.bucket(bucket)
        self.prefix = prefix.strip("/")

    def put(self, rel: str, local: Path) -> None:
        self.bucket.blob(self._key(rel)).upload_from_filename(str(local))

    def get(self, rel: str, local: Path) -> None:
        local.parent.mkdir(parents=True, exist_ok=True)
        self.bucket.blob(self._key(rel)).download_to_filename(str(local))

    def _iter_keys(self, probe: str):
        for blob in self.bucket.list_blobs(prefix=probe):
            yield blob.name


def _walk_files(root: Path):
    for p in sorted(root.rglob("*")):
        if p.is_file() and not p.name.startswith(".tmp"):
            yield p


def backup(
    server_root: str | Path,
    remote: RemoteFS,
    *,
    time_dir: Optional[str] = None,
    flush: Optional[callable] = None,
) -> str:
    """Snapshot (via the provided flush hook) then copy the tree.

    Returns the time-dir name (backup/timedir.go analog).
    """
    server_root = Path(server_root)
    if flush:
        flush()
    stamp = time_dir or dt.datetime.now(dt.timezone.utc).strftime("%Y%m%d%H%M%S")
    for f in _walk_files(server_root):
        rel = f.relative_to(server_root)
        remote.put(f"{stamp}/{rel}", f)
    return stamp


def list_backups(remote: RemoteFS) -> list[str]:
    stamps = {r.split("/", 1)[0] for r in remote.list("")}
    return sorted(stamps)


def restore(
    remote: RemoteFS, time_dir: str, server_root: str | Path
) -> int:
    """Materialize a backup into an empty server root. Returns file count."""
    server_root = Path(server_root)
    if server_root.exists() and any(server_root.iterdir()):
        raise FileExistsError(f"restore target {server_root} not empty")
    files = remote.list(time_dir)
    for rel in files:
        local_rel = rel.split("/", 1)[1]
        remote.get(rel, server_root / local_rel)
    return len(files)
