"""Cluster tier migration: ship expired-from-hot segments to the next
stage's node over the chunked-sync wire.

Analog of the reference's lifecycle agent (banyand/backup/lifecycle/
service.go steps: snapshot -> per-model visitors copy segments to the
target tier -> verify -> delete from source; progress.go makes every
step resumable).  The TPU build's form:

  TierMigrator(data_node, transport, target).run(older_than_millis)

- seals each expired segment (flush + index persist; trace sidx first),
- ships every part via the SYNC_PART chunked protocol with a metadata
  patch stamping catalog + ordered_tags so the receiver routes it to the
  right engine and rebuilds trace blooms/sidx (data_node._on_sync_part),
- records progress per part in `.tier-migration.json` — an interrupted
  run resumes where it stopped, and receiver-side content digests make
  re-ships of already-installed parts no-ops,
- drops the local segment only after every shipped part is acknowledged
  (copy -> verify -> swap, lifecycle/steps.go ordering).

Stage routing composes: once the hot node drops the segment, queries
naming stages=('warm',) resolve to the target node (pub/stage.go
ResolveStage analog in cluster.liaison._shard_assignment).
"""

from __future__ import annotations

import shutil
from typing import Optional

from banyandb_tpu.cluster.liaison import ChunkedSyncClient
from banyandb_tpu.utils import fs

PROGRESS_FILE = ".tier-migration.json"


class TierMigrator:
    def __init__(self, node, transport, target_addr: str):
        """node: the hot-tier cluster DataNode; target_addr: transport
        address of the warm/cold-tier node receiving the segments."""
        self.node = node
        self.client = ChunkedSyncClient(transport, target_addr)
        self.progress_path = node.root / PROGRESS_FILE

    # -- progress ----------------------------------------------------------
    def _load_progress(self) -> dict:
        if self.progress_path.exists():
            return fs.read_json(self.progress_path)
        return {"shipped": [], "migrated_segments": []}

    def _save_progress(self, progress: dict) -> None:
        fs.atomic_write_json(self.progress_path, progress)

    # -- engine walk -------------------------------------------------------
    def _engines(self):
        return (
            ("measure", self.node.measure),
            ("stream", self.node.stream),
            ("trace", self.node.trace),
        )

    def _attach_disk_groups(self, engine) -> None:
        """Engines open group TSDBs lazily on first write/query; an
        offline agent (the lifecycle CLI) sees an empty map until the
        on-disk groups are attached explicitly."""
        root = getattr(engine, "root", None)
        if root is None or not root.exists():
            return
        for gdir in root.iterdir():
            if not gdir.is_dir():
                continue
            try:
                engine._tsdb(gdir.name)
            except KeyError:
                continue  # directory for a group the registry dropped

    def _seal(self, catalog: str, engine, db) -> None:
        """Everything in memtables/mem-sidx must be on disk before the
        directory tree is shipped (lifecycle takes a snapshot first)."""
        if catalog == "trace":
            # ordered keys first, the engine's own flush-ordering contract
            engine._flush_sidx_first()
        db.flush_all()

    def _trace_ordered_tags(self, seg) -> list[str]:
        """Tree-indexed tags of a segment, recovered from its on-disk
        sidx stores — shipped in the metadata patch so the receiver
        rebuilds ordered indexes for the migrated spans."""
        return sorted(
            p.name[len("sidx-"):]
            for p in seg.root.glob("sidx-*")
            if p.is_dir()
        )

    # -- run ---------------------------------------------------------------
    def _ship_segment(
        self, catalog, group, seg, meta_patch, done, progress, resumed_keys
    ) -> int:
        """Ship every part of the segment until it is quiescent: each pass
        flushes late memtable rows into new parts and ships anything not
        yet recorded; done when a pass ships nothing and memtables are
        empty.  Merge-freeze (MIGRATING_MARKER) keeps part names stable,
        so the progress keys survive a crash + resume."""
        seg_name = seg.root.name
        shipped = 0
        while True:
            new_this_pass = 0
            for shard in seg.shards:
                shard.flush()
                for part in shard.parts:
                    key = "/".join(
                        (catalog, group, seg_name, shard.root.name, part.name)
                    )
                    if key in done:
                        if key in resumed_keys:
                            resumed_keys[key] = True
                        continue
                    self.client.sync_part(
                        part.dir,
                        group=group,
                        segment=seg_name,
                        segment_start_millis=seg.start,
                        shard=shard.root.name,
                        meta_patch=meta_patch,
                    )
                    new_this_pass += 1
                    done.add(key)
                    progress["shipped"] = sorted(done)
                    self._save_progress(progress)
            if new_this_pass == 0 and all(
                not sh.has_unflushed for sh in seg.shards
            ):
                return shipped
            shipped += new_this_pass

    def run(self, older_than_millis: int, catalogs: Optional[tuple] = None) -> dict:
        """Migrate every sealed segment with end <= cutoff. Returns
        {"shipped_parts": N, "migrated_segments": [...], "resumed": N}."""
        from contextlib import ExitStack

        from banyandb_tpu.storage.tsdb import MIGRATING_MARKER

        progress = self._load_progress()
        done = set(progress["shipped"])
        # keys recorded by a PREVIOUS (interrupted) run; flipped True when
        # this run actually skips a re-ship because of them
        resumed_keys = {k: False for k in done}
        shipped = 0
        for catalog, engine in self._engines():
            if catalogs is not None and catalog not in catalogs:
                continue
            self._attach_disk_groups(engine)
            for group, db in list(engine._tsdbs.items()):
                expired = [
                    seg for seg in db.segments if seg.end <= older_than_millis
                ]
                if not expired:
                    continue
                self._seal(catalog, engine, db)  # once per db, not per seg
                for seg in expired:
                    # merge-freeze FIRST: progress keys are part names
                    (seg.root / MIGRATING_MARKER).touch()
                    meta_patch = {"catalog": catalog, "group": group}
                    if catalog == "trace":
                        ordered = self._trace_ordered_tags(seg)
                        if ordered:
                            meta_patch["ordered_tags"] = ordered
                    seg_name = seg.root.name
                    shipped += self._ship_segment(
                        catalog, group, seg, meta_patch, done, progress,
                        resumed_keys,
                    )
                    # swap phase: drop from the hot tier only after every
                    # part is acknowledged.  All shard locks + db lock are
                    # held so no in-flight ingest/flush interleaves with
                    # the removal; a write that enters after the pop gets
                    # a fresh segment object (stays hot — safe, re-ships
                    # on the next migration pass).
                    with ExitStack() as stack:
                        stack.enter_context(db._lock)
                        for sh in seg.shards:
                            stack.enter_context(sh._lock)
                        if any(sh.has_unflushed for sh in seg.shards):
                            # a write slipped in after the quiesce pass:
                            # leave the segment in place for the next run
                            # rather than dropping unshipped rows
                            continue
                        db._segments.pop(seg.start, None)
                    shutil.rmtree(seg.root, ignore_errors=True)
                    progress["migrated_segments"].append(
                        f"{catalog}/{group}/{seg_name}"
                    )
                    # shipped-part records for a dropped segment are dead
                    # weight (part names are epoch-unique per shard dir)
                    prefix = f"{catalog}/{group}/{seg_name}/"
                    done = {k for k in done if not k.startswith(prefix)}
                    progress["shipped"] = sorted(done)
                    self._save_progress(progress)
        return {
            "shipped_parts": shipped,
            "resumed": sum(1 for hit in resumed_keys.values() if hit),
            "migrated_segments": progress["migrated_segments"],
        }
