"""Disk-usage write gates.

Analog of the reference's disk monitor
(/root/reference/banyand/internal/storage/disk_monitor.go:86): the data
path's filesystem usage is sampled periodically; when it crosses the
high watermark, writes are rejected with a retryable DiskFull error
until usage falls back below the low watermark (hysteresis, so the
gate doesn't flap around one threshold).  Queries are never gated.

The usage probe is injectable for tests (and for exotic mounts where
shutil.disk_usage lies).
"""

from __future__ import annotations

import shutil
import threading
import time
from pathlib import Path
from typing import Callable, Optional


class DiskFull(RuntimeError):
    """Write rejected: data filesystem above the high watermark."""


def _default_probe(path: Path) -> float:
    u = shutil.disk_usage(path)
    return u.used / u.total * 100.0


class DiskMonitor:
    def __init__(
        self,
        path: str | Path,
        *,
        high_pct: float = 95.0,
        low_pct: float = 90.0,
        interval_s: float = 10.0,
        probe: Optional[Callable[[Path], float]] = None,
    ):
        assert low_pct <= high_pct
        self.path = Path(path)
        self.high_pct = high_pct
        self.low_pct = low_pct
        self.interval_s = interval_s
        self._probe = probe or _default_probe
        self._lock = threading.Lock()
        self._gated = False
        self._last_check = 0.0
        self._last_pct = 0.0
        self.rejected = 0  # metrics counter

    def _refresh_locked(self) -> None:
        now = time.monotonic()
        if now - self._last_check < self.interval_s:
            return
        self._last_check = now
        try:
            self._last_pct = float(self._probe(self.path))
        except OSError:
            return  # keep the previous verdict on probe failure
        if self._gated:
            if self._last_pct < self.low_pct:
                self._gated = False
        elif self._last_pct >= self.high_pct:
            self._gated = True

    def check_write(self) -> None:
        """Raises DiskFull when the gate is closed (call on every write
        admission, alongside the memory protector).  Deliberately takes
        no size: the probe is percentage-based, and a byte argument
        would imply projected-usage admission this gate doesn't do."""
        with self._lock:
            self._refresh_locked()
            if self._gated:
                self.rejected += 1
                raise DiskFull(
                    f"disk usage {self._last_pct:.1f}% >= "
                    f"{self.high_pct:.0f}% high watermark on {self.path}"
                )

    def status(self) -> dict:
        with self._lock:
            return {
                "gated": self._gated,
                "usage_pct": round(self._last_pct, 2),
                "high_pct": self.high_pct,
                "low_pct": self.low_pct,
                "rejected": self.rejected,
            }
