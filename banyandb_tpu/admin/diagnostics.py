"""Node diagnostics collector (FODC agent analog, re-scoped to host
telemetry per SURVEY.md §2 — the reference's eBPF kernel probes become
/proc readings; on-demand pprof capture becomes a Python thread dump).

collect() returns one self-contained snapshot: runtime parameters,
process/memory stats, storage inventory, thread stacks, and the meter
snapshot — served over the bus ("diagnostics" topic) and dumpable to a
crash-artifact file (pkg/panicdiag analog).
"""

from __future__ import annotations

import json
import sys
import threading
import time
import traceback
from pathlib import Path
from typing import Optional


# the one definition of the diagnostics bus topic (fodc proxy polls it;
# standalone server + data nodes subscribe it)
DIAG_TOPIC = "diagnostics"


def runtime_params() -> dict:
    import jax

    return {
        "python": sys.version.split()[0],
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "pid": __import__("os").getpid(),
    }


def read_self_io() -> "tuple[int, int] | None":
    """(read_bytes, write_bytes) of this process from /proc/self/io —
    the one parser shared by diagnostics snapshots and the FODC agent's
    IO telemetry source."""
    try:
        vals = {}
        with open("/proc/self/io") as f:
            for line in f:
                k, _, v = line.partition(":")
                if k in ("read_bytes", "write_bytes"):
                    vals[k] = int(v)
        return (vals.get("read_bytes", 0), vals.get("write_bytes", 0))
    except (OSError, ValueError):
        return None


def process_stats() -> dict:
    out = {"uptime_s": time.monotonic()}
    try:
        with open("/proc/self/statm") as f:
            pages = f.read().split()
        out["rss_bytes"] = int(pages[1]) * 4096
        out["vsz_bytes"] = int(pages[0]) * 4096
    except OSError:
        pass
    io = read_self_io()
    if io is not None:
        out["io_read_bytes"], out["io_write_bytes"] = io
    out["threads"] = threading.active_count()
    return out


def thread_dump() -> dict:
    """Stacks of every live thread (pprof goroutine-dump analog)."""
    frames = sys._current_frames()
    out = {}
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        out[t.name] = (
            traceback.format_stack(frame) if frame is not None else []
        )
    return out


def storage_inventory(root: str | Path) -> dict:
    from banyandb_tpu.admin.inspect import inspect_root

    try:
        info = inspect_root(root)
    except OSError:
        return {}
    totals = {"parts": 0, "rows": 0, "bytes": 0}
    for groups in info["engines"].values():
        for segs in groups.values():
            for shards in segs.values():
                for shard in shards.values():
                    for p in shard["parts"]:
                        totals["parts"] += 1
                        totals["rows"] += p.get("rows", 0)
                        totals["bytes"] += p.get("bytes", 0)
    return totals


class DiagnosticsCollector:
    """Bundles one node's full diagnostic snapshot (FODC agent collect)."""

    def __init__(self, root: str | Path, meter=None):
        self.root = Path(root)
        self.meter = meter

    def collect(self, *, include_threads: bool = False) -> dict:
        snap = {
            "ts_millis": int(time.time() * 1000),
            "runtime": runtime_params(),
            "process": process_stats(),
            "storage": storage_inventory(self.root),
        }
        if self.meter is not None:
            m = self.meter.snapshot()
            snap["metrics"] = {
                "counters": {str(k): v for k, v in m["counters"].items()},
                "gauges": {str(k): v for k, v in m["gauges"].items()},
            }
        if include_threads:
            snap["threads"] = thread_dump()
        return snap

    def write_crash_artifact(self, reason: str, dest: Optional[str | Path] = None) -> Path:
        """Persist a full snapshot incl. stacks (pkg/panicdiag analog).
        Filenames carry a uuid suffix: two crashes in the same
        millisecond (e.g. a shared resource breaking several threads at
        once) must not overwrite each other's evidence."""
        import uuid

        dest = Path(dest) if dest else self.root / "diagnostics"
        dest.mkdir(parents=True, exist_ok=True)
        snap = self.collect(include_threads=True)
        snap["reason"] = reason
        path = dest / f"crash-{snap['ts_millis']}-{uuid.uuid4().hex[:8]}.json"
        path.write_text(json.dumps(snap, indent=1, default=str))
        return path
