"""FODC proxy REST + Prometheus aggregation API.

Analog of /root/reference/fodc/proxy/internal/api/server.go (869 LoC):
the HTTP face of the proxy — aggregated Prometheus exposition over every
registered agent's latest metrics, windowed JSON metrics, agent health,
cluster topology/lifecycle views, crash diagnostics, and pressure-profile
listing/download driven over the FODCService command stream.  Routes
mirror the reference's mux (server.go:101-108):

    GET /metrics
    GET /metrics-windows?start=<unix_s>&end=<unix_s>
    GET /health
    GET /cluster/topology
    GET /cluster/lifecycle
    GET /diagnostics[?capture=1]
    GET /pressure-profiles
    GET /pressure-profiles/<pod_name>/<profile_id>/<type>
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from banyandb_tpu.admin import fodc_wire


def _sanitize_filename_part(s: str) -> str:
    """Strip anything that could inject header syntax or path separators
    into the Content-Disposition filename (server.go:806 analog)."""
    return re.sub(r"[^A-Za-z0-9._-]", "_", s)[:128]


def _fmt_value(v: float) -> str:
    return f"{int(v)}" if float(v).is_integer() else repr(float(v))


class FodcApiServer:
    """HTTP server over the proxy state (+ optional bundle proxy)."""

    def __init__(
        self,
        state: fodc_wire.FodcProxyState,
        *,
        proxy=None,  # admin.fodc.FodcProxy for /diagnostics bundles
        host: str = "127.0.0.1",
        port: int = 0,
        stale_after_s: float = 90.0,
    ):
        self.state = state
        self.proxy = proxy
        self.stale_after_s = stale_after_s
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str, extra=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _json(self, obj, code: int = 200):
                self._send(
                    code,
                    json.dumps(obj, indent=1, default=str).encode(),
                    "application/json",
                )

            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    route = outer._route(u.path, q)
                except FileNotFoundError as e:
                    self._json({"error": str(e)}, 404)
                    return
                except Exception as e:  # noqa: BLE001 - surface, don't crash
                    self._json({"error": f"{type(e).__name__}: {e}"}, 500)
                    return
                kind, payload = route
                if kind == "prom":
                    self._send(200, payload.encode(), "text/plain; version=0.0.4")
                elif kind == "json":
                    self._json(payload)
                else:  # download
                    fname, data = payload
                    self._send(
                        200,
                        data,
                        "application/octet-stream",
                        extra=(
                            (
                                "Content-Disposition",
                                f'attachment; filename="{fname}"',
                            ),
                        ),
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self.addr = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- routing -------------------------------------------------------------
    def _route(self, path: str, q: dict):
        if path == "/metrics":
            return ("prom", self._prometheus_text())
        if path == "/metrics-windows":
            start = float(q.get("start", ["0"])[0])
            end = float(q.get("end", ["1e18"])[0])
            return ("json", self._metrics_windows(start, end))
        if path == "/health":
            return ("json", self._health())
        if path == "/cluster/topology":
            return ("json", self._topology())
        if path == "/cluster/lifecycle":
            return ("json", self._lifecycle())
        if path == "/diagnostics":
            return ("json", self._diagnostics(capture="capture" in q))
        if path == "/pressure-profiles":
            return ("json", self._pressure_profiles())
        m = re.fullmatch(r"/pressure-profiles/([^/]+)/([^/]+)/([^/]+)", path)
        if m:
            return ("download", self._pressure_download(*m.groups()))
        raise FileNotFoundError(path)

    # -- views ---------------------------------------------------------------
    def _identity_labels(self, st) -> list[tuple[str, str]]:
        ident = st.identity
        out = [("pod", ident.get("pod_name", ""))]
        if ident.get("node_role"):
            out.append(("node_role", ident["node_role"]))
        return out

    def _prometheus_text(self) -> str:
        """Aggregate every agent's latest cycle into one exposition,
        grouped into typed families (server.go:293 formatPrometheusText)."""
        families: dict[str, tuple[str, list[str]]] = {}
        for st in self.state.all_agents():
            ident = dict(self._identity_labels(st))
            for m in st.metrics:
                lbls = dict(m.labels)
                lbls.update(ident)
                inner = ",".join(
                    f'{k}="{v}"' for k, v in sorted(lbls.items()) if v != ""
                )
                line = f"{m.name}{{{inner}}} {_fmt_value(m.value)}"
                typ, lines = families.setdefault(m.name, (m.type, []))
                lines.append(line)
        out = []
        for name in sorted(families):
            typ, lines = families[name]
            if typ in ("gauge", "counter", "histogram", "summary"):
                out.append(f"# TYPE {name} {typ}")
            out.extend(sorted(lines))
        return "\n".join(out) + "\n"

    def _metrics_windows(self, start_s: float, end_s: float) -> list[dict]:
        out = []
        for st in self.state.all_agents():
            ident = dict(self._identity_labels(st))
            for ts, cycle in st.metric_history:
                if not (start_s <= ts <= end_s):
                    continue
                out.append(
                    {
                        "timestamp": ts,
                        **ident,
                        "metrics": [
                            {
                                "name": m.name,
                                "labels": dict(m.labels),
                                "value": m.value,
                                "type": m.type,
                            }
                            for m in cycle
                        ],
                    }
                )
        out.sort(key=lambda w: w["timestamp"])
        return out

    def _health(self) -> dict:
        import time as _t

        now = _t.time()
        agents = [
            {
                "agent_id": st.agent_id,
                **dict(self._identity_labels(st)),
                "last_seen_s_ago": round(now - st.last_seen, 1),
                "healthy": (now - st.last_seen) < self.stale_after_s,
            }
            for st in self.state.all_agents()
        ]
        return {
            "status": "ok" if all(a["healthy"] for a in agents) else "degraded",
            "agents": agents,
        }

    def _topology(self) -> dict:
        nodes, calls, seen = [], [], set()
        for st in self.state.all_agents():
            if not st.topology:
                continue
            for n in st.topology.get("nodes", []):
                if n["name"] not in seen:
                    seen.add(n["name"])
                    nodes.append(n)
            calls.extend(st.topology.get("calls", []))
        return {"nodes": nodes, "calls": calls}

    def _lifecycle(self) -> list[dict]:
        return [st.lifecycle for st in self.state.all_agents() if st.lifecycle]

    def _diagnostics(self, capture: bool) -> dict:
        out = {
            "crashes": {
                st.identity.get("pod_name", st.agent_id): st.crashes
                for st in self.state.all_agents()
                if st.crashes
            }
        }
        if self.proxy is not None:
            if capture:
                out["captured"] = self.proxy.capture(reason="api").name
            out["bundles"] = self.proxy.list_bundles()
        return out

    def _pressure_profiles(self) -> list[dict]:
        out = []
        for st in self.state.all_agents():
            if not st.pp_connected:
                continue
            pod = st.identity.get("pod_name", st.agent_id)
            try:
                for rec in fodc_wire.list_pressure_profiles(st):
                    rec["pod_name"] = pod
                    rec["node_role"] = st.identity.get("node_role", "")
                    out.append(rec)
            except Exception:  # noqa: BLE001 - one dead agent must not 500 the list
                continue
        # top-N by RSS at trigger — the reference's sort key
        out.sort(key=lambda r: -int(r.get("rss_bytes", 0)))
        return out

    def _pressure_download(self, pod_name: str, profile_id: str, kind: str):
        st = self.state.by_pod(pod_name)
        if st is None:
            raise FileNotFoundError(f"no agent for pod {pod_name}")
        data = fodc_wire.fetch_pressure_profile(st, profile_id, kind)
        fname = "-".join(
            _sanitize_filename_part(p) for p in (pod_name, profile_id, kind)
        )
        return (f"{fname}.txt", data)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="fodc-api"
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
