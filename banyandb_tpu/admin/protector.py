"""Memory admission control (banyand/protector/protector.go:55,97,108
analog).

Writes acquire resources against a memory budget derived from the cgroup
limit (pkg/cgroups analog) or an explicit cap; over-budget acquisition
raises ServerBusy after a bounded backoff — ingestion sheds load instead
of OOMing the node.  On a TPU host the same gate also tracks a logical
HBM budget for device-resident query state.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Optional


class ServerBusy(RuntimeError):
    """ErrServerBusy (banyand/queue/queue.go:45 analog)."""


def cgroup_memory_limit() -> Optional[int]:
    """Read the v2 (then v1) cgroup memory limit, None when unlimited."""
    for path, parse in (
        ("/sys/fs/cgroup/memory.max", lambda s: None if s == "max" else int(s)),
        (
            "/sys/fs/cgroup/memory/memory.limit_in_bytes",
            lambda s: None if int(s) >= 2**60 else int(s),
        ),
    ):
        try:
            return parse(Path(path).read_text().strip())
        except (OSError, ValueError):
            continue
    return None


def process_rss() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * 4096


class MemoryProtector:
    def __init__(
        self,
        *,
        limit_bytes: Optional[int] = None,
        limit_ratio: float = 0.8,
        hbm_limit_bytes: Optional[int] = None,
        max_wait_s: float = 2.0,
    ):
        cg = cgroup_memory_limit()
        self.limit = limit_bytes or (int(cg * limit_ratio) if cg else None)
        self.hbm_limit = hbm_limit_bytes
        self.max_wait_s = max_wait_s
        self._lock = threading.Lock()
        self._reserved = 0
        self._hbm_reserved = 0

    def acquire(self, size_bytes: int, *, hbm: bool = False) -> None:
        """Block (with backoff) until the budget admits `size_bytes`,
        else raise ServerBusy (AcquireResource analog)."""
        deadline = time.monotonic() + self.max_wait_s
        wait = 0.01
        while True:
            with self._lock:
                if hbm:
                    if self.hbm_limit is None or self._hbm_reserved + size_bytes <= self.hbm_limit:
                        self._hbm_reserved += size_bytes
                        return
                else:
                    if self.limit is None:
                        self._reserved += size_bytes
                        return
                    used = process_rss() + self._reserved
                    if used + size_bytes <= self.limit:
                        self._reserved += size_bytes
                        return
            if time.monotonic() >= deadline:
                raise ServerBusy(
                    f"memory budget exceeded acquiring {size_bytes}B"
                )
            time.sleep(wait)
            wait = min(wait * 2, 0.25)

    def release(self, size_bytes: int, *, hbm: bool = False) -> None:
        with self._lock:
            if hbm:
                self._hbm_reserved = max(0, self._hbm_reserved - size_bytes)
            else:
                self._reserved = max(0, self._reserved - size_bytes)
