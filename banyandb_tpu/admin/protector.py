"""Memory admission control (banyand/protector/protector.go:55,97,108
analog).

Writes acquire resources against a memory budget derived from the cgroup
limit (pkg/cgroups analog) or an explicit cap; over-budget acquisition
raises ServerBusy after a bounded backoff — ingestion sheds load instead
of OOMing the node.  On a TPU host the same gate also tracks a logical
HBM budget for device-resident query state.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Optional


class ServerBusy(RuntimeError):
    """ErrServerBusy (banyand/queue/queue.go:45 analog)."""


def cgroup_memory_limit() -> Optional[int]:
    """Read the v2 (then v1) cgroup memory limit, None when unlimited."""
    for path, parse in (
        ("/sys/fs/cgroup/memory.max", lambda s: None if s == "max" else int(s)),
        (
            "/sys/fs/cgroup/memory/memory.limit_in_bytes",
            lambda s: None if int(s) >= 2**60 else int(s),
        ),
    ):
        try:
            return parse(Path(path).read_text().strip())
        except (OSError, ValueError):
            continue
    return None


def process_rss() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * 4096


class MemoryProtector:
    def __init__(
        self,
        *,
        limit_bytes: Optional[int] = None,
        limit_ratio: float = 0.8,
        hbm_limit_bytes: Optional[int] = None,
        max_wait_s: float = 2.0,
        tenant_limit_fn=None,
    ):
        cg = cgroup_memory_limit()
        self.limit = limit_bytes or (int(cg * limit_ratio) if cg else None)
        self.hbm_limit = hbm_limit_bytes
        self.max_wait_s = max_wait_s
        # per-tenant in-flight write-byte budgets (docs/robustness.md
        # "Multi-tenant QoS"): tenant -> byte cap, 0/None = unlimited.
        # Injected (usually qos.QosPlane.inflight_limit) so the gate has
        # no upward config dependency.
        self.tenant_limit_fn = tenant_limit_fn
        self._lock = threading.Lock()
        self._reserved = 0
        self._hbm_reserved = 0
        self._tenant_reserved: dict[str, int] = {}

    def _tenant_limit(self, tenant: Optional[str]) -> int:
        if tenant is None or self.tenant_limit_fn is None:
            return 0
        try:
            return int(self.tenant_limit_fn(tenant) or 0)
        except Exception:  # noqa: BLE001 - a config error must not gate writes
            return 0

    def acquire(
        self, size_bytes: int, *, hbm: bool = False,
        tenant: Optional[str] = None,
    ) -> None:
        """Block (with backoff) until the budget admits `size_bytes`,
        else raise ServerBusy (AcquireResource analog).  `tenant`
        additionally charges the per-tenant in-flight budget: one
        tenant's write burst sheds against its OWN cap while the node's
        global budget still has room for everyone else."""
        t_limit = self._tenant_limit(tenant)
        if t_limit and size_bytes > t_limit:
            # no amount of draining admits this acquisition: shed NOW
            # instead of pinning a handler thread through the whole
            # backoff window on every doomed retry
            from banyandb_tpu.obs.metrics import global_meter

            global_meter().counter_add(
                "qos_inflight_shed", 1.0, {"tenant": tenant}
            )
            raise ServerBusy(
                f"tenant {tenant!r} write of {size_bytes}B exceeds its "
                f"whole in-flight budget ({t_limit}B)"
            )
        deadline = time.monotonic() + self.max_wait_s
        wait = 0.01
        while True:
            tenant_over = False
            with self._lock:
                if t_limit and (
                    self._tenant_reserved.get(tenant, 0) + size_bytes
                    > t_limit
                ):
                    tenant_over = True
                elif hbm:
                    if self.hbm_limit is None or self._hbm_reserved + size_bytes <= self.hbm_limit:
                        self._hbm_reserved += size_bytes
                        return
                else:
                    admit = False
                    if self.limit is None:
                        admit = True
                    else:
                        used = process_rss() + self._reserved
                        admit = used + size_bytes <= self.limit
                    if admit:
                        self._reserved += size_bytes
                        if tenant is not None:
                            self._tenant_reserved[tenant] = (
                                self._tenant_reserved.get(tenant, 0)
                                + size_bytes
                            )
                        return
            if time.monotonic() >= deadline:
                if tenant_over:
                    from banyandb_tpu.obs.metrics import global_meter

                    global_meter().counter_add(
                        "qos_inflight_shed", 1.0, {"tenant": tenant}
                    )
                    raise ServerBusy(
                        f"tenant {tenant!r} over in-flight write budget "
                        f"({t_limit}B) acquiring {size_bytes}B"
                    )
                raise ServerBusy(
                    f"memory budget exceeded acquiring {size_bytes}B"
                )
            time.sleep(wait)
            wait = min(wait * 2, 0.25)

    def release(
        self, size_bytes: int, *, hbm: bool = False,
        tenant: Optional[str] = None,
    ) -> None:
        with self._lock:
            if hbm:
                self._hbm_reserved = max(0, self._hbm_reserved - size_bytes)
            else:
                self._reserved = max(0, self._reserved - size_bytes)
                if tenant is not None:
                    left = self._tenant_reserved.get(tenant, 0) - size_bytes
                    if left > 0:
                        self._tenant_reserved[tenant] = left
                    else:
                        self._tenant_reserved.pop(tenant, None)

    def tenant_usage(self) -> dict[str, int]:
        """Current per-tenant in-flight reserved bytes (obs export)."""
        with self._lock:
            return dict(self._tenant_reserved)
