"""Hierarchical query tracer (pkg/query/tracer.go:50 analog).

A ``Tracer`` owns one root ``Span``; nested ``tracer.span(...)`` context
managers build the tree.  Spans carry a name, wall duration, a flat tag
map (device_ms/host_ms attribution, cache hit/miss, row counts...) and
child spans.  ``Span.attach`` grafts an already-serialized subtree —
the cluster merge: each data node runs its own tracer and returns
``tracer.finish()`` in the RPC reply, the liaison attaches the subtree
under that node's scatter span, and the response carries ONE tree.

Serialized form (JSON-safe, the ``res.trace["span_tree"]`` payload and
the wire common/v1 Span mapping):

    {"name": str, "duration_ms": float, "tags": {str: scalar},
     "children": [<span>...], "error": str?}

Tracing off must cost nothing: callers thread ``None`` (executors skip
span work on a ``None`` span) or ``NOOP_TRACER`` (handlers keep one
code path); both avoid allocation on the hot path.
"""

from __future__ import annotations

import time
from typing import Optional


class Span:
    """One timed node of the trace tree.  Not thread-safe: a span is
    owned by the thread that created it (worker-side timings are
    accumulated into plain tags by the owner, see measure_exec)."""

    __slots__ = ("name", "t0", "t1", "tags", "children", "error_msg")

    def __init__(self, name: str):
        self.name = name
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.tags: dict = {}
        self.children: list = []  # Span | dict (attached subtree)
        self.error_msg: Optional[str] = None

    # -- building -----------------------------------------------------------
    def tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    def error(self, msg: str) -> "Span":
        self.error_msg = str(msg)
        return self

    def child(self, name: str) -> "Span":
        s = Span(name)
        self.children.append(s)
        return s

    def attach(self, subtree: dict) -> None:
        """Graft a serialized span tree (a remote node's subtree)."""
        if subtree:
            self.children.append(subtree)

    def finish(self) -> "Span":
        if self.t1 is None:
            # bdlint: disable=wp-shared-state -- a Span belongs to ONE
            # query's tracer (constructed per request, never shared
            # across requests); many roots run queries, but no two roots
            # ever hold the same Span instance
            self.t1 = time.perf_counter()
        return self

    # spans double as context managers so executors can scope a leg
    # without holding a Tracer
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and self.error_msg is None:
            self.error(f"{type(exc).__name__}: {exc}")
        self.finish()

    # -- reading ------------------------------------------------------------
    @property
    def duration_ms(self) -> float:
        end = self.t1 if self.t1 is not None else time.perf_counter()
        return (end - self.t0) * 1000.0

    def to_dict(self) -> dict:
        self.finish()
        out = {
            "name": self.name,
            "duration_ms": round(self.duration_ms, 3),
            "tags": dict(self.tags),
            "children": [
                c.to_dict() if isinstance(c, Span) else c
                for c in self.children
            ],
        }
        if self.error_msg is not None:
            out["error"] = self.error_msg
        return out


class _SpanCtx:
    """Context manager pushing/popping one span on a tracer's stack."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and self._span.error_msg is None:
            self._span.error(f"{type(exc).__name__}: {exc}")
        self._span.finish()
        self._tracer._stack.pop()


class Tracer:
    """Span-tree builder for one query.  Single-owner (the query's
    request thread); remote subtrees arrive serialized via attach."""

    __slots__ = ("root", "_stack")

    def __init__(self, name: str):
        self.root = Span(name)
        self._stack: list[Span] = [self.root]

    def current(self) -> Span:
        return self._stack[-1]

    def span(self, name: str) -> _SpanCtx:
        s = self._stack[-1].child(name)
        self._stack.append(s)
        return _SpanCtx(self, s)

    def finish(self) -> dict:
        """Close the root and return the serialized tree."""
        return self.root.to_dict()


class _NoopSpan:
    """Absorbs the whole Span surface at near-zero cost."""

    __slots__ = ()

    def tag(self, key, value):
        return self

    def error(self, msg):
        return self

    def child(self, name):
        return self

    def attach(self, subtree):
        pass

    def finish(self):
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        pass

    def to_dict(self) -> dict:
        return {}


class _NoopTracer:
    __slots__ = ()

    root = _NoopSpan()

    def current(self):
        return NOOP_SPAN

    def span(self, name):
        return NOOP_SPAN

    def finish(self) -> dict:
        return {}


NOOP_SPAN = _NoopSpan()
NOOP_TRACER = _NoopTracer()


def attach_tree(res, req, tree: dict):
    """Attach a finished span tree to a QueryResult when the request
    asked for in-band tracing (`res.trace["span_tree"]`) — the one
    response-side attach, shared by every serving surface."""
    if getattr(req, "trace", False):
        res.trace = dict(res.trace or {})
        res.trace["span_tree"] = tree
    return res


def find_span(tree: Optional[dict], name: str) -> Optional[dict]:
    """Depth-first lookup by span name in a serialized tree (tests,
    smoke scripts, slowlog consumers)."""
    if not tree:
        return None
    if tree.get("name") == name:
        return tree
    for c in tree.get("children", ()):
        hit = find_span(c, name)
        if hit is not None:
            return hit
    return None


def iter_spans(tree: Optional[dict]):
    """Yield every span dict of a serialized tree, depth-first."""
    if not tree:
        return
    yield tree
    for c in tree.get("children", ()):
        yield from iter_spans(c)
