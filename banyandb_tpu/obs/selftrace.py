"""Self-trace sink: the dogfood half of the trace engine.

Slow/sampled query span trees — the same trees the slowlog's 128-entry
ring keeps transiently — are mirrored as PERSISTENT trace rows in the
``_monitoring.self_query`` trace model, written through the database's
own ``TraceEngine.write`` (standalone) or its cluster facades.  Each
span of a recorded tree becomes one row: ``trace_id`` is the query's
id, the span name lands in ``stage``, and the span duration (µs, INT)
is the sidx ordering key — so ``cli.py``/bydbql answer "slowest queries
last hour, stage breakdown, per tenant" from the database itself
(ORDER BY duration_us DESC), exercising the full trace query surface
on a built-in production workload.

Flag-gated OFF by default (``BYDB_SELF_TRACE``); the sampling threshold
``BYDB_SELF_TRACE_MS`` mirrors the slowlog's rule (0 records every
traced query the serving surface offers).  The sink NEVER blocks the
query path: ``offer()`` drops into a bounded in-memory queue and sheds
(counted by ``selftrace_dropped_total``) when full; a background
flusher (``bydb-self-trace``) writes batches on a cadence and counts
flushed rows in ``selftrace_spans_total``.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.utils.envflag import env_flag, env_float, env_int

GROUP = "_monitoring"
NAME = "self_query"


class SelfTraceSink:
    """Mirror query span trees into the DB's own trace model."""

    DEFAULT_INTERVAL_S = 5.0
    DEFAULT_QUEUE = 256

    def __init__(self, trace_engine, registry, *, node: str = "standalone"):
        self.engine = trace_engine
        self.registry = registry
        self.node = node
        self.enabled = env_flag("BYDB_SELF_TRACE", False)
        self.threshold_ms = env_float("BYDB_SELF_TRACE_MS", 0.0)
        self.interval_s = env_float(
            "BYDB_SELF_TRACE_INTERVAL_S", self.DEFAULT_INTERVAL_S
        )
        self.queue_cap = max(env_int("BYDB_SELF_TRACE_QUEUE", self.DEFAULT_QUEUE), 1)
        self._lock = threading.Lock()
        self._schema_lock = threading.Lock()
        self._queue: list[dict] = []
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._schema_ready = False

    # -- query-path half (must shed, never block) ---------------------------
    def offer(
        self,
        *,
        engine: str,
        group: str,
        name: str,
        duration_ms: float,
        tree: Optional[dict],
        tenant: str = "",
        ql: Optional[str] = None,
        query_id: Optional[str] = None,
    ) -> bool:
        """Enqueue one query's span tree for mirroring.  Returns True
        when queued.  Never raises, never blocks: a full queue sheds the
        NEW entry and counts it — backpressure on the telemetry loop
        must not become backpressure on queries."""
        if not self.enabled or not tree:
            return False
        if duration_ms < self.threshold_ms:
            return False
        if group == GROUP:
            # never re-record queries against the monitoring group
            # itself: reading self_query would otherwise grow it
            return False
        entry = {
            "query_id": query_id or uuid.uuid4().hex,
            "ts_millis": int(time.time() * 1000),
            "engine": engine,
            "name": name,
            "tenant": tenant,
            "tree": tree,
        }
        with self._lock:
            if len(self._queue) >= self.queue_cap:
                obs_metrics.global_meter().counter_add(
                    "selftrace_dropped", 1.0
                )
                return False
            self._queue.append(entry)
        return True

    # -- background half ----------------------------------------------------
    def _ensure_schema(self) -> None:
        if self._schema_ready:
            return
        with self._schema_lock:
            # double-checked: the background flusher and a snapshot's
            # synchronous flush may race here; registry ops run under
            # the schema lock, never the queue lock (offer() stays free)
            if self._schema_ready:
                return
            from banyandb_tpu.api.schema import (
                Catalog,
                Group,
                ResourceOpts,
                TagSpec,
                TagType,
                Trace,
            )

            reg = self.registry
            try:
                reg.get_group(GROUP)
            except KeyError:
                # match SelfMeasureSink's group spec: both sinks share
                # `_monitoring`, whichever initializes first creates it
                reg.create_group(
                    Group(GROUP, Catalog.MEASURE, ResourceOpts(shard_num=1))
                )
            try:
                reg.get_trace(GROUP, NAME)
            except KeyError:
                reg.create_trace(
                    Trace(
                        group=GROUP,
                        name=NAME,
                        tags=(
                            TagSpec("trace_id", TagType.STRING),
                            TagSpec("name", TagType.STRING),
                            TagSpec("engine", TagType.STRING),
                            TagSpec("stage", TagType.STRING),
                            TagSpec("tenant", TagType.STRING),
                            TagSpec("node", TagType.STRING),
                            TagSpec("duration_us", TagType.INT),
                        ),
                        trace_id_tag="trace_id",
                    )
                )
            self._schema_ready = True

    def start(self) -> None:
        """Run the background flusher (idempotent; no-op when the flag
        is off — the flag-off path must stay byte-identical)."""
        if not self.enabled or self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="bydb-self-trace", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.flush()
            except Exception:  # noqa: BLE001 - the sink must not die with
                # a transient engine error (e.g. mid-shutdown write refusal)
                import logging

                logging.getLogger(__name__).exception("self-trace flush failed")

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None

    def flush(self) -> int:
        """Drain the queue into `_monitoring.self_query` (one row per
        span, duration_us maintained in the sidx ordered index).
        Returns the number of span rows written."""
        if not self.enabled:
            return 0
        with self._lock:
            entries, self._queue = self._queue, []
        if not entries:
            return 0
        from banyandb_tpu.models.trace import SpanValue
        from banyandb_tpu.obs.tracer import iter_spans

        self._ensure_schema()
        spans = []
        for e in entries:
            for sp in iter_spans(e["tree"]):
                spans.append(
                    SpanValue(
                        ts_millis=e["ts_millis"],
                        tags={
                            "trace_id": e["query_id"],
                            "name": e["name"],
                            "engine": e["engine"],
                            "stage": sp.get("name", ""),
                            "tenant": e["tenant"],
                            "node": self.node,
                            "duration_us": int(
                                float(sp.get("duration_ms", 0.0)) * 1000
                            ),
                        },
                        span=b"",
                    )
                )
        if spans:
            self.engine.write(
                GROUP, NAME, spans, ordered_tags=("duration_us",)
            )
            obs_metrics.global_meter().counter_add(
                "selftrace_spans", float(len(spans))
            )
        return len(spans)
