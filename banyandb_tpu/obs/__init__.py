"""Self-observability plane (pkg/query/tracer + pkg/meter analogs).

Three units, all dependency-free so every layer can reach them:

- ``tracer``:  hierarchical in-band query tracing — a ``Tracer`` owns a
  span tree threaded liaison -> data nodes and merged back into the
  response (``res.trace["span_tree"]``), with explicit device/host time
  attribution around jax dispatch and cache-plane hit/miss tags.
- ``metrics``: the instrument registry (counters, gauges, exponential-
  bucket histograms with per-instrument handles) behind ``/metrics``
  and the ``_monitoring`` self-measure sink.
- ``recorder``: the slow-query flight recorder — a bounded ring buffer
  of span trees + plan text for queries over the slow threshold,
  retrievable via ``cli.py slowlog`` and the HTTP gateway.

See docs/observability.md for the span-tree shape and instrument
naming scheme.
"""

from banyandb_tpu.obs.metrics import Histogram, Meter, global_meter
from banyandb_tpu.obs.recorder import SlowQueryRecorder, default_recorder
from banyandb_tpu.obs.tracer import NOOP_TRACER, Span, Tracer, find_span

__all__ = [
    "Histogram",
    "Meter",
    "NOOP_TRACER",
    "SlowQueryRecorder",
    "Span",
    "Tracer",
    "default_recorder",
    "find_span",
    "global_meter",
]
