"""Prometheus exposition parsing + histogram quantile recovery.

The read side of obs/metrics: the bench (bench.py), the load probe
(scripts/load.py) and tests scrape a RUNNING server's exposition text
and recover stage latency quantiles from the ``_bucket`` series —
using the same inversion the live handles use
(obs.metrics.quantile_from_buckets), so scraped and in-process
estimates cannot drift.
"""

from __future__ import annotations

import re
from typing import Optional

from banyandb_tpu.obs.metrics import quantile_from_buckets

_LINE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """-> [(metric name, label dict, value)] for every sample line."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_value = m.groups()
        labels = dict(_LABEL.findall(raw_labels or ""))
        try:
            value = float(raw_value)
        except ValueError:
            continue
        out.append((name, labels, value))
    return out


def histogram_series(text: str, metric: str) -> dict[tuple, dict]:
    """Collect one histogram family from exposition text.

    -> {sorted non-le label items: {"buckets": [(le, cumulative)...],
        "count": int, "sum": float}}; buckets sorted by bound with the
    +Inf entry last."""
    series: dict[tuple, dict] = {}

    def slot(labels: dict) -> dict:
        key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        return series.setdefault(key, {"buckets": [], "count": 0, "sum": 0.0})

    for name, labels, value in parse_exposition(text):
        if name == metric + "_bucket":
            le = labels.get("le", "+Inf")
            bound = float("inf") if le == "+Inf" else float(le)
            slot(labels)["buckets"].append((bound, value))
        elif name == metric + "_count":
            slot(labels)["count"] = int(value)
        elif name == metric + "_sum":
            slot(labels)["sum"] = value
    for s in series.values():
        s["buckets"].sort(key=lambda bv: bv[0])
    return series


def quantile(series_entry: dict, q: float) -> float:
    """Quantile estimate from one scraped histogram series entry."""
    buckets = series_entry["buckets"]
    count = series_entry["count"]
    if not buckets or count <= 0:
        return 0.0
    bounds = tuple(b for b, _ in buckets if b != float("inf"))
    # de-cumulate (exposition buckets are cumulative)
    counts = []
    prev = 0.0
    for _, cum in buckets:
        counts.append(max(cum - prev, 0.0))
        prev = cum
    if len(counts) == len(bounds):  # no explicit +Inf line
        counts.append(max(count - prev, 0.0))
    return quantile_from_buckets(bounds, counts, count, q)


def stage_breakdown(
    text: str,
    metric: str = "banyandb_query_stage_ms",
    quantiles: tuple[float, ...] = (0.5, 0.99),
) -> dict[str, dict]:
    """Per-stage latency attribution from a scraped exposition.

    -> {stage: {"count": n, "p50_ms": ..., "p99_ms": ...}} — the
    bench-artifact section ROADMAP item 1 wants landing with every TPU
    run (gather vs device-execute vs merge, measured not inferred).

    Series sharing a stage but differing in OTHER labels (the
    multi-process data plane stamps ``worker="wNNN"`` per worker
    exposition) merge before inversion: buckets share the exponential
    bound grid, so summing cumulative counts per bound is exact."""
    merged: dict[str, dict] = {}
    for key, entry in histogram_series(text, metric).items():
        stage = dict(key).get("stage")
        if stage is None or entry["count"] == 0:
            continue
        slot = merged.setdefault(
            stage, {"buckets": {}, "count": 0, "sum": 0.0}
        )
        for bound, cum in entry["buckets"]:
            slot["buckets"][bound] = slot["buckets"].get(bound, 0.0) + cum
        slot["count"] += entry["count"]
        slot["sum"] += entry["sum"]
    out: dict[str, dict] = {}
    for stage, slot in merged.items():
        entry = {
            "buckets": sorted(slot["buckets"].items()),
            "count": slot["count"],
            "sum": slot["sum"],
        }
        rec: dict = {"count": entry["count"]}
        for q in quantiles:
            rec[f"p{int(q * 100)}_ms"] = round(quantile(entry, q), 3)
        out[stage] = rec
    return out


def stage_breakdown_delta(
    before: str,
    after: str,
    metric: str = "banyandb_query_stage_ms",
    quantiles: tuple[float, ...] = (0.5, 0.99),
) -> dict[str, dict]:
    """Per-stage attribution of ONLY the window between two scrapes.

    Cumulative bucket counts are diffed per (stage, le) so one phase of
    a run — e.g. each leg of the bench's fused-vs-staged A/B — gets its
    own quantiles instead of the process-lifetime aggregate."""
    prior = histogram_series(before, metric)
    merged: dict[str, dict] = {}
    for key, entry in histogram_series(after, metric).items():
        stage = dict(key).get("stage")
        if stage is None:
            continue
        base = prior.get(key)
        buckets = entry["buckets"]
        count = entry["count"]
        total = entry["sum"]
        if base is not None:
            base_map = dict(base["buckets"])
            buckets = [
                (bound, max(cum - base_map.get(bound, 0.0), 0.0))
                for bound, cum in buckets
            ]
            count = entry["count"] - base["count"]
            total = entry["sum"] - base["sum"]
        if count <= 0:
            continue
        # merge across non-stage labels (per-worker expositions), same
        # shared-bound-grid argument as stage_breakdown
        slot = merged.setdefault(
            stage, {"buckets": {}, "count": 0, "sum": 0.0}
        )
        for bound, cum in buckets:
            slot["buckets"][bound] = slot["buckets"].get(bound, 0.0) + cum
        slot["count"] += count
        slot["sum"] += total
    out: dict[str, dict] = {}
    for stage, slot in merged.items():
        window = {
            "buckets": sorted(slot["buckets"].items()),
            "count": slot["count"],
            "sum": slot["sum"],
        }
        rec: dict = {"count": slot["count"]}
        for q in quantiles:
            rec[f"p{int(q * 100)}_ms"] = round(quantile(window, q), 3)
        out[stage] = rec
    return out


def gauge_value(text: str, metric: str, labels: Optional[dict] = None):
    """First sample matching metric (+ label subset), or None."""
    want = labels or {}
    for name, lbls, value in parse_exposition(text):
        if name != metric:
            continue
        if all(lbls.get(k) == v for k, v in want.items()):
            return value
    return None
