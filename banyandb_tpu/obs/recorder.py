"""Slow-query flight recorder: a bounded ring buffer of span trees.

Queries whose wall time crosses the slow threshold (accesslog's
``slow_query_ms``) persist their full span tree + plan text here; the
newest entries are retrievable live via ``cli.py slowlog``, the
``slowlog`` bus topic and ``GET /api/v1/slowlog`` on the HTTP gateway
(the reference's slow-query log, banyand/dquery/measure.go:169, grown
into a flight recorder).

``SignatureStats`` is the recorder plane's second table: a bounded
per-query-signature hit counter fed by the server query epilogue for
EVERY measure query (slow queries count double — they are the ones
materialization helps most).  The auto-registration loop
(query/planner.AutoRegistrar) mines it each tick to find hot
streamagg-eligible signatures; it holds no span trees, just
(group, measure, key_tags, fields) -> hits.

Bounded by construction (``BYDB_SLOWLOG_CAPACITY`` entries, oldest
evicted; ``SignatureStats`` caps distinct signatures and drops the
coldest) so a pathological workload cannot grow either without limit.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

_DEFAULT_CAPACITY = 128


def _env_capacity() -> int:
    from banyandb_tpu.utils.envflag import env_int

    return max(env_int("BYDB_SLOWLOG_CAPACITY", _DEFAULT_CAPACITY), 1)


class SlowQueryRecorder:
    """Thread-safe ring buffer of slow-query records.

    A record is a plain JSON-safe dict; ``record`` stamps ``seq`` (a
    monotonic id that survives eviction — consumers can detect gaps)
    and ``ts`` (epoch millis) onto it.
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity if capacity is not None else _env_capacity()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0

    def record(self, entry: dict) -> int:
        with self._lock:
            self._seq += 1
            entry = dict(entry, seq=self._seq, ts=int(time.time() * 1000))
            self._ring.append(entry)
            return self._seq

    def entries(self, limit: Optional[int] = None) -> list[dict]:
        """Newest first."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        if limit is not None and limit >= 0:
            out = out[: int(limit)]
        return out

    def clear(self) -> int:
        with self._lock:
            n = len(self._ring)
            self._ring.clear()
            return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class SignatureStats:
    """Bounded per-signature query counter (the autoreg evidence
    table).  Keys are the planner's signature tuples
    ``(group, measure, key_tags, fields)``; values are cumulative hit
    counts (monotonic — the miner diffs against its last snapshot).

    Capacity-bounded: past ``cap`` distinct signatures the coldest
    (lowest-count) entry is dropped, so churn-heavy ad-hoc query
    populations cannot grow the table without limit while a steady
    dashboard signature keeps accumulating."""

    def __init__(self, cap: int = 512):
        self.cap = max(int(cap), 8)
        self._lock = threading.Lock()
        self._hits: dict[tuple, int] = {}

    def observe(self, key: Optional[tuple], weight: int = 1) -> None:
        if key is None:
            return
        with self._lock:
            n = self._hits.get(key)
            if n is None and len(self._hits) >= self.cap:
                coldest = min(self._hits, key=self._hits.get)
                del self._hits[coldest]
            self._hits[key] = (n or 0) + weight

    def snapshot(self) -> dict[tuple, int]:
        with self._lock:
            return dict(self._hits)

    def __len__(self) -> int:
        with self._lock:
            return len(self._hits)


# one per process by default (all server roles in a process share it,
# like the global meter); servers own explicit instances when isolation
# matters — the default exists so surfaces without a server handle
# (offline tooling) can still read the buffer
_DEFAULT: Optional[SlowQueryRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def record_slow_query(
    recorder: SlowQueryRecorder,
    threshold_ms: float,
    *,
    engine: str,
    group: str,
    name: str,
    duration_ms: float,
    rows: int,
    span_tree: dict,
    ql: Optional[str] = None,
    plan: Optional[str] = None,
    plan_fn=None,
    tenant: str = "",
) -> bool:
    """The slow-query epilogue every server role shares: one record
    schema, one threshold check.  `plan_fn` renders the plan post-hoc
    (role-specific: local vs distributed analyzer) — invoked only for
    queries already past the threshold, never on the hot path."""
    if duration_ms < threshold_ms:
        return False
    if plan is None and plan_fn is not None:
        try:
            plan = plan_fn()
        except Exception:  # noqa: BLE001 - the record stays useful
            plan = None
    if not tenant:
        from banyandb_tpu.qos.tenancy import tenant_of_group

        tenant = tenant_of_group(group)
    recorder.record(
        {
            "engine": engine,
            "group": group,
            "name": name,
            "tenant": tenant,
            "ql": ql,
            "duration_ms": round(duration_ms, 3),
            "rows": rows,
            "threshold_ms": threshold_ms,
            "span_tree": span_tree,
            "plan": plan,
        }
    )
    return True


def slowlog_topic_reply(
    recorder: SlowQueryRecorder, env: dict, threshold_ms: float
) -> dict:
    """The `slowlog` bus-topic contract, shared by every server role so
    the surfaces cannot drift: {limit} reads newest-first, {clear: true}
    drains the ring."""
    if env.get("clear"):
        return {"cleared": recorder.clear(), "entries": []}
    return {
        "entries": recorder.entries(limit=env.get("limit")),
        "threshold_ms": threshold_ms,
        "capacity": recorder.capacity,
    }


def default_recorder() -> SlowQueryRecorder:
    global _DEFAULT
    r = _DEFAULT
    if r is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = SlowQueryRecorder()
            r = _DEFAULT
    return r
