"""Instrument registry: counters, gauges, exponential-bucket histograms.

The ``Meter`` core of the metrics facade (pkg/meter analog) lives here
at the platform layer so storage loops, executors and the cluster
fabric can all instrument themselves without upward imports;
``admin/metrics.py`` re-exports it and adds the ``_monitoring``
self-measure sink on top.

Histograms are exponential-bucket (factor 2 from 0.25 ms): 26 buckets
cover 250 µs .. ~2330 s, so any latency quantile is recoverable from
``/metrics`` within one bucket factor (and much closer with the log
interpolation in ``Histogram.quantile`` — tests/test_obs.py pins the
error bound).  Hot-path contract: ``meter.histogram(...)`` hands out a
per-instrument handle ONCE; ``handle.observe(v)`` touches only that
handle's lock — the registry dict+lock is never on the per-observation
path (the reference's provider/instrument split).
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Optional

# exponential bucket ladder: bounds[i] = 0.25 * 2**i (ms)
_BUCKET_START_MS = 0.25
_BUCKET_FACTOR = 2.0
_NUM_BUCKETS = 26

DEFAULT_BOUNDS: tuple[float, ...] = tuple(
    _BUCKET_START_MS * (_BUCKET_FACTOR**i) for i in range(_NUM_BUCKETS)
)


class Histogram:
    """One instrument: bucket counts + running count/sum.

    observe() is the hot path: bucket search outside the lock, three
    plain stores under it.  Values land in the first bucket whose upper
    bound is >= value; values past the ladder land in the +Inf bucket.
    """

    __slots__ = ("bounds", "_counts", "count", "sum", "_lock")

    def __init__(self, bounds: Optional[tuple[float, ...]] = None):
        self.bounds = tuple(bounds) if bounds is not None else DEFAULT_BOUNDS
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += value

    def snapshot(self) -> tuple[int, float, tuple[int, ...]]:
        """-> (count, sum, per-bucket counts incl. trailing +Inf)."""
        with self._lock:
            return self.count, self.sum, tuple(self._counts)

    def quantile(self, q: float) -> float:
        """CDF inversion with log interpolation inside the hit bucket —
        exact to within one bucket, typically much closer on smooth
        distributions (the bound tests/test_obs.py pins)."""
        count, _total, counts = self.snapshot()
        return quantile_from_buckets(self.bounds, counts, count, q)


def quantile_from_buckets(
    bounds: tuple[float, ...], counts, count: int, q: float
) -> float:
    """Shared inversion used by live handles AND scraped exposition
    (obs/prom.py) so the bench's stage_breakdown and the in-process
    estimate cannot drift."""
    if count <= 0:
        return 0.0
    target = max(1.0, math.ceil(q * count))
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            if i >= len(bounds):  # +Inf bucket: report the last bound
                return bounds[-1]
            hi = bounds[i]
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (target - (cum - c)) / max(c, 1.0)
            frac = min(max(frac, 0.0), 1.0)
            if lo <= 0.0:
                return hi * frac
            return lo * (hi / lo) ** frac
    return bounds[-1]


class Meter:
    """Scoped instrument registry: counters, gauges, histograms.

    Counters/gauges stay dict-under-one-lock (write-rate is per-request,
    not per-row); histograms hand out per-instrument handles.
    """

    def __init__(self, scope: str = ""):
        self.scope = scope
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hist: dict[tuple, Histogram] = {}

    def _key(self, name: str, labels: Optional[dict]) -> tuple:
        return (name, tuple(sorted((labels or {}).items())))

    def counter_add(self, name: str, value: float = 1.0, labels: Optional[dict] = None):
        k = self._key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def gauge_set(self, name: str, value: float, labels: Optional[dict] = None):
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def histogram(
        self,
        name: str,
        labels: Optional[dict] = None,
        bounds: Optional[tuple[float, ...]] = None,
    ) -> Histogram:
        """Per-instrument handle; grab once, observe many.  The lock-free
        first read keeps repeat lookups off the registry lock too."""
        k = self._key(name, labels)
        h = self._hist.get(k)
        if h is None:
            with self._lock:
                h = self._hist.get(k)
                if h is None:
                    h = self._hist[k] = Histogram(bounds)
        return h

    def observe(self, name: str, value: float, labels: Optional[dict] = None):
        self.histogram(name, labels).observe(value)

    # -- exposition ---------------------------------------------------------
    def snapshot(self) -> dict:
        """histograms keep the legacy (count, sum) shape consumed by the
        fodc watchdog source; hist_buckets adds the full ladder."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hist)
        hist_cs: dict[tuple, tuple[int, float]] = {}
        buckets: dict[tuple, tuple[tuple[float, ...], tuple[int, ...]]] = {}
        for k, h in hists.items():
            count, total, counts = h.snapshot()
            hist_cs[k] = (count, total)
            buckets[k] = (h.bounds, counts)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": hist_cs,
            "hist_buckets": buckets,
        }

    def prometheus_text(self) -> str:
        """Prometheus exposition format (pkg/meter/prom analog), with
        cumulative ``_bucket{le=...}`` series per histogram."""
        pfx = (self.scope + "_") if self.scope else ""
        lines = []

        def fmt_labels(lbls: tuple, extra: Optional[tuple] = None) -> str:
            items = list(lbls) + (list(extra) if extra else [])
            if not items:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in items)
            return "{" + inner + "}"

        def fmt_le(b: float) -> str:
            return repr(b) if b != int(b) else str(int(b))

        snap = self.snapshot()
        for (name, lbls), v in sorted(snap["counters"].items()):
            lines.append(f"{pfx}{name}_total{fmt_labels(lbls)} {v}")
        for (name, lbls), v in sorted(snap["gauges"].items()):
            lines.append(f"{pfx}{name}{fmt_labels(lbls)} {v}")
        for (name, lbls), (count, total) in sorted(snap["histograms"].items()):
            bounds, counts = snap["hist_buckets"][(name, lbls)]
            cum = 0
            for b, c in zip(bounds, counts):
                cum += c
                lines.append(
                    f"{pfx}{name}_bucket"
                    f"{fmt_labels(lbls, (('le', fmt_le(b)),))} {cum}"
                )
            lines.append(
                f"{pfx}{name}_bucket{fmt_labels(lbls, (('le', '+Inf'),))} "
                f"{count}"
            )
            lines.append(f"{pfx}{name}_count{fmt_labels(lbls)} {count}")
            lines.append(f"{pfx}{name}_sum{fmt_labels(lbls)} {total}")
        return "\n".join(lines) + "\n"


# -- process-global meter ----------------------------------------------------
# One registry per process: engines, storage loops, executors and the
# RPC fabric all write here, and every server role's metrics topic /
# /metrics endpoint exposes it.  (Multi-node-in-one-process test
# topologies share it — per-node split is a label, not a registry.)
_GLOBAL: Optional[Meter] = None
_GLOBAL_LOCK = threading.Lock()


def global_meter() -> Meter:
    global _GLOBAL
    m = _GLOBAL
    if m is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = Meter("banyandb")
            m = _GLOBAL
    return m


def stage_histogram(stage: str) -> Histogram:
    """Handle for one query-stage latency instrument
    (``banyandb_query_stage_ms{stage=...}``) — the instrument the bench's
    stage_breakdown and ROADMAP item 1's attribution read."""
    return global_meter().histogram("query_stage_ms", {"stage": stage})
