"""Query engine: logical request -> device execution plan -> result.

Replaces the reference's row planner + vectorized operator pipeline
(pkg/query/logical, pkg/query/vectorized) with a single fused device
computation per (plan signature, chunk shape), plus thin host glue for
dictionary resolution and result assembly.
"""
