"""Plan precompile registry: compile query kernels before queries arrive.

Three cooperating pieces close the cold-start compile gap:

1. **Recording**: the measure/stream executors call ``record()`` every
   time they resolve a plan signature (``PlanSpec`` / ``_MaskSpec``), so
   the registry always knows the live plan population of this process.
2. **Persistence**: when a server attaches a store file
   (``<root>/plan-registry.json``), newly seen signatures are saved (top
   ``MAX_STORED`` by use count) and reloaded on the next boot — the
   process remembers WHICH kernels matter across restarts, while
   ``utils/compile_cache`` remembers their compiled XLA executables.
3. **Warming**: ``warm_async()`` (server start = schema load, and once
   after the first flush via ``note_flush``) compiles the stored
   signatures plus the builtin dashboard matrix on a background daemon
   thread, by building each kernel into the executors' process-global
   jit caches and dispatching it once on zero-filled arguments of the
   exact production shapes/dtypes — so the first real query finds a
   warm jit cache instead of paying XLA compilation.

``builtin_plans()``/``builtin_fused()``/``builtin_masks()`` are the
checked-in dashboard kernel matrix.  The lint plan auditor
(``lint/whole_program/plan_audit.py``) eval_shape-audits EXACTLY this
list — a meta-test pins the agreement, so a signature added here is
automatically contract-checked and a signature audited is automatically
precompiled.

``BYDB_PRECOMPILE=0`` disables recording and warming (tests that need a
deterministic kernel-cache population set this).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from pathlib import Path
from typing import Optional

MAX_STORED = 64


def enabled() -> bool:
    from banyandb_tpu.utils.envflag import env_flag

    return env_flag("BYDB_PRECOMPILE", default=True)


# -- the builtin dashboard matrix (single source for warm + plan audit) ------


def builtin_plans():
    """(name, PlanSpec) pairs: the dashboard plan population.

    Mirrors the shapes real consoles issue: flat count tiles, grouped
    eq+LUT filters with scan-order tracking, the two-pass percentile
    histogram, OR criteria trees, and the TopN ranking shape (grouped
    mean/minmax + representative tracking at a scan-chunk bucket)."""
    from banyandb_tpu.query.measure_exec import PlanSpec, _PredSpec

    flat = PlanSpec(
        tags_code=(),
        fields=("v",),
        preds=(),
        group_tags=(),
        radices=(),
        num_groups=1,
        want_minmax=True,
        nrows=8192,
    )
    grouped = PlanSpec(
        tags_code=("region", "svc"),
        fields=("v",),
        preds=(
            _PredSpec("code", "svc", "eq"),
            _PredSpec("lut", "region", "le", nvals=4),
        ),
        group_tags=("svc", "region"),
        radices=(8, 4),
        num_groups=32,
        want_minmax=True,
        nrows=8192,
        want_rep=True,
    )
    pct = PlanSpec(
        tags_code=("svc",),
        fields=("lat",),
        preds=(),
        group_tags=("svc",),
        radices=(16,),
        num_groups=16,
        want_minmax=True,
        hist_field="lat",
        nrows=65536,
    )
    orplan = PlanSpec(
        tags_code=("svc",),
        fields=("v",),
        preds=(
            _PredSpec("code", "svc", "in", nvals=4),
            _PredSpec("code", "svc", "eq"),
        ),
        group_tags=(),
        radices=(),
        num_groups=1,
        want_minmax=False,
        nrows=8192,
        expr=("or", ("p", 0), ("p", 1)),
    )
    topn = PlanSpec(
        tags_code=("region", "svc"),
        fields=("value",),
        preds=(_PredSpec("code", "region", "ne"),),
        group_tags=("svc",),
        radices=(1024,),
        num_groups=1024,
        want_minmax=True,
        nrows=65536,
        want_rep=True,
    )
    return (
        ("measure/flat-count", flat),
        ("measure/group-eq-lut", grouped),
        ("measure/percentile-hist", pct),
        ("measure/or-expr", orplan),
        ("measure/topn-dashboard", topn),
    )


def builtin_masks():
    """(name, _MaskSpec) pairs for the stream retrieval mask kernel."""
    from banyandb_tpu.query.stream_exec import _MaskSpec

    return (
        ("stream/mask-eq-in", _MaskSpec(preds=(("eq", 1), ("in", 4)), nrows=32768)),
    )


def builtin_fused():
    """(name, FusedSpec) pairs: the fused whole-plan twins of the builtin
    measure matrix (query/fused_exec).  One-chunk buckets — the shape a
    dashboard part-batch resolves — warmed, plan-audited and budget-
    ratcheted alongside their staged counterparts."""
    from banyandb_tpu.query.fused_exec import FusedSpec

    return tuple(
        (name.replace("measure/", "fused/"), FusedSpec(plan=spec, num_chunks=1))
        for name, spec in builtin_plans()
    )


# -- shape/dtype argument builders (shared with the lint plan auditor) -------


def chunk_struct(spec) -> dict:
    """ShapeDtypeStruct pytree matching _device_chunk's output exactly."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    n = spec.nrows
    return {
        "ts": S((n,), jnp.int32),
        "series": S((n,), jnp.int32),
        "valid": S((n,), jnp.bool_),
        "row": S((n,), jnp.int32),
        "tags_code": {t: S((n,), jnp.int32) for t in spec.tags_code},
        "fields": {f: S((n,), jnp.float32) for f in spec.fields},
    }


def pred_struct(spec) -> dict:
    """ShapeDtypeStruct map matching compute_partials' pred_vals."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    out = {}
    for i, p in enumerate(spec.preds):
        if p.kind == "lut":
            out[f"p{i}"] = S((p.nvals,), jnp.bool_)
        elif p.op in ("in", "not_in"):
            out[f"p{i}"] = S((p.nvals,), jnp.int32)
        else:
            out[f"p{i}"] = S((), jnp.int32)
    return out


def mask_structs(mspec) -> tuple:
    """(cols, pred_vals) ShapeDtypeStructs matching device_tag_mask."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    cols = tuple(S((mspec.nrows,), jnp.int32) for _ in mspec.preds)
    vals = tuple(
        S((nv,), jnp.int32) if op in ("in", "not_in") else S((), jnp.int32)
        for op, nv in mspec.preds
    )
    return cols, vals


def _zeros_like_structs(tree):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), tree
    )


def measure_warm_args(spec) -> tuple:
    """Zero-filled production-shaped args for one measure plan kernel."""
    import jax.numpy as jnp

    return (
        _zeros_like_structs(chunk_struct(spec)),
        _zeros_like_structs(pred_struct(spec)),
        jnp.float32(0.0),
        jnp.float32(1.0),
    )


def measure_decode_warm_args(spec) -> tuple:
    """Warm args for the COMPRESSED staged ship form (the production
    default under ``BYDB_DEVICE_DECODE=1``) at the canonical widths."""
    import jax.numpy as jnp

    return (
        _zeros_like_structs(decode_chunk_struct(spec)),
        _zeros_like_structs(pred_struct(spec)),
        jnp.float32(0.0),
        jnp.float32(1.0),
    )


def mask_warm_args(mspec) -> tuple:
    cols, vals = mask_structs(mspec)
    return (_zeros_like_structs(cols), _zeros_like_structs(vals))


def fused_chunk_struct(fspec) -> dict:
    """ShapeDtypeStruct pytree matching fused_exec._stacked_chunks."""
    import jax

    base = chunk_struct(fspec.plan)
    c = fspec.num_chunks
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct((c,) + s.shape, s.dtype), base
    )


def _decode_lut_len(spec, t: str) -> int:
    for tag, radix in zip(spec.group_tags, spec.radices):
        if tag == t:
            return 1 << max(int(radix) - 1, 1).bit_length()
    return 16


def _decode_code_dtype(spec, t: str):
    """Canonical narrow code width per tag: from the group radix where
    the signature pins one, i8 otherwise (the dashboard population's
    dictionaries are small).  Production widths are data-dependent — a
    mismatch just means one extra trace on first contact, the same cost
    class as an unseen row bucket."""
    import jax.numpy as jnp

    import numpy as _np

    from banyandb_tpu.storage import encoded as enc_mod

    for tag, radix in zip(spec.group_tags, spec.radices):
        if tag == t:
            return jnp.dtype(enc_mod.code_dtype(int(radix)))
    return jnp.dtype(_np.int8)


def decode_chunk_struct(spec) -> dict:
    """ShapeDtypeStruct pytree for the COMPRESSED ship form of one
    STAGED chunk (measure_exec._device_chunk's compressed branch) at
    the canonical single-source shape of fused_decode_chunk_struct;
    the staged form never carries a ``tags_code`` key (the fused
    stacker keeps an empty one)."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    n = spec.nrows
    out = {
        "ts": S((n,), jnp.int32),
        "series": S((n,), jnp.int32),
        "valid": S((n,), jnp.bool_),
        "row": S((n,), jnp.int32),
        "fields": {
            f: S((n,), jnp.float32)
            for f in spec.fields
            if f == spec.hist_field
        },
    }
    if spec.tags_code:
        out["tags_enc"] = {
            t: S((n,), _decode_code_dtype(spec, t)) for t in spec.tags_code
        }
        out["tags_lut"] = {
            t: S((1, _decode_lut_len(spec, t)), jnp.int32)
            for t in spec.tags_code
        }
        out["src_ord"] = S((n,), jnp.int16)
    enc = {
        f: S((n,), jnp.int16)
        for f in spec.fields
        if f != spec.hist_field
    }
    if enc:
        out["fields_enc"] = enc
    return out


def fused_decode_chunk_struct(fspec) -> dict:
    """ShapeDtypeStruct pytree for the COMPRESSED ship form of a fused
    part-batch (``BYDB_DEVICE_DECODE``, fused_exec._stacked_chunks'
    compressed branch), at a canonical single-source shape:

    - tag columns as narrow local codes (width from the group radix, i8
      otherwise) plus a ``[1, L]`` remap LUT with L
      the power-of-two bucket of the tag's radix (group tags) or 16;
    - one i16 source-ordinal column;
    - fields as i16 exact-int columns, except the histogram field
      (percentile inputs are real-valued) which stays dense f32.

    Production widths vary with the data (i8 dictionaries, multi-source
    LUT stacks — jit re-specializes per pytree); this canonical shape is
    what the ``fused+decode/*`` budget rows lower and jaxpr-audit, the
    same way nrows is a representative row bucket."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    spec = fspec.plan
    c, n = fspec.num_chunks, spec.nrows
    lut_len = lambda t: _decode_lut_len(spec, t)  # noqa: E731

    out = {
        "ts": S((c, n), jnp.int32),
        "series": S((c, n), jnp.int32),
        "valid": S((c, n), jnp.bool_),
        "row": S((c, n), jnp.int32),
        "tags_code": {},
        "fields": {
            f: S((c, n), jnp.float32)
            for f in spec.fields
            if f == spec.hist_field
        },
    }
    if spec.tags_code:
        out["tags_enc"] = {
            t: S((c, n), _decode_code_dtype(spec, t))
            for t in spec.tags_code
        }
        out["tags_lut"] = {
            t: S((1, lut_len(t)), jnp.int32) for t in spec.tags_code
        }
        out["src_ord"] = S((c, n), jnp.int16)
    enc = {
        f: S((c, n), jnp.int16)
        for f in spec.fields
        if f != spec.hist_field
    }
    if enc:
        out["fields_enc"] = enc
    return out


def builtin_fused_decode():
    """(name, FusedSpec) pairs for the ``fused+decode/*`` audit rows —
    the SAME FusedSpecs as builtin_fused() (the ship form is not part of
    the plan signature), paired by the kernel audit with the compressed
    chunk structs from fused_decode_chunk_struct."""
    return tuple(
        (name.replace("fused/", "fused+decode/"), fspec)
        for name, fspec in builtin_fused()
    )


def fused_warm_args(fspec) -> tuple:
    """Zero-filled production-shaped args for one fused plan program."""
    import jax.numpy as jnp

    return (
        _zeros_like_structs(fused_chunk_struct(fspec)),
        _zeros_like_structs(pred_struct(fspec.plan)),
        jnp.float32(0.0),
        jnp.float32(1.0),
    )


def fused_decode_warm_args(fspec) -> tuple:
    """Warm args for the COMPRESSED fused ship form (the production
    default under ``BYDB_DEVICE_DECODE=1``) at the canonical widths."""
    import jax.numpy as jnp

    return (
        _zeros_like_structs(fused_decode_chunk_struct(fspec)),
        _zeros_like_structs(pred_struct(fspec.plan)),
        jnp.float32(0.0),
        jnp.float32(1.0),
    )


# -- signature (de)serialization ---------------------------------------------


def spec_to_json(kind: str, spec) -> dict:
    d = dataclasses.asdict(spec)
    d["kind"] = kind
    return d


def _tuplify(node):
    """JSON lists -> tuples, recursively (expr trees, pred tuples)."""
    if isinstance(node, list):
        return tuple(_tuplify(v) for v in node)
    return node


def spec_from_json(d: dict):
    kind = d["kind"]
    if kind == "fused":
        from banyandb_tpu.query.fused_exec import FusedSpec

        _, plan = spec_from_json({**d["plan"], "kind": "measure"})
        return kind, FusedSpec(plan=plan, num_chunks=int(d["num_chunks"]))
    if kind == "measure":
        from banyandb_tpu.query.measure_exec import PlanSpec, _PredSpec

        return kind, PlanSpec(
            tags_code=tuple(d["tags_code"]),
            fields=tuple(d["fields"]),
            preds=tuple(_PredSpec(**p) for p in d["preds"]),
            group_tags=tuple(d["group_tags"]),
            radices=tuple(d["radices"]),
            num_groups=int(d["num_groups"]),
            want_minmax=bool(d["want_minmax"]),
            hist_field=d.get("hist_field", ""),
            nrows=int(d["nrows"]),
            group_method=d.get("group_method", "auto"),
            want_rep=bool(d.get("want_rep", False)),
            rep_desc=bool(d.get("rep_desc", False)),
            expr=_tuplify(d.get("expr", [])),
        )
    if kind == "stream_mask":
        from banyandb_tpu.query.stream_exec import _MaskSpec

        return kind, _MaskSpec(
            preds=_tuplify(d["preds"]), nrows=int(d["nrows"])
        )
    raise ValueError(f"unknown plan signature kind {kind!r}")


# -- the registry ------------------------------------------------------------


class PrecompileRegistry:
    """Thread-safe record of live plan signatures + background warming."""

    def __init__(self):
        self._lock = threading.Lock()
        # (kind, spec) -> use count; insertion order = first-seen order
        self._recorded: dict[tuple, int] = {}
        # (kind, spec) -> epoch-ms of the latest record() — persisted so
        # warming (and the autoreg miner) can rank by freshness too
        self._last_hit: dict[tuple, int] = {}
        # (kind, spec) -> (group, measure) the executor resolved the
        # plan for: the context that turns an anonymous PlanSpec into a
        # registrable streamagg signature (query/planner mining)
        self._contexts: dict[tuple, tuple] = {}
        self._store_path: Optional[Path] = None
        self._warm_thread: Optional[threading.Thread] = None
        self._warm_pending = False
        self._cancel = threading.Event()
        self._save_timer: Optional[threading.Timer] = None
        self._flush_warmed = False
        self.compiled = 0
        self.errors = 0

    # -- recording / persistence --------------------------------------------
    def record(self, kind: str, spec, context: Optional[tuple] = None) -> None:
        """Called by executors on every plan resolution.  Never blocks
        the query hot path: a first-seen signature schedules a debounced
        background save instead of rewriting the store inline.

        ``context`` ((group, measure), measure plans only) attaches the
        schema identity the plan resolved against — the evidence the
        auto-registration miner needs to turn a hot PlanSpec into a
        streamagg registration."""
        if not enabled():
            return
        new = False
        with self._lock:
            key = (kind, spec)
            n = self._recorded.get(key)
            self._recorded[key] = (n or 0) + 1
            import time as _time

            self._last_hit[key] = int(_time.time() * 1000)
            if context is not None:
                self._contexts[key] = tuple(context)
            new = n is None and self._store_path is not None
        if new:
            self._schedule_save()

    def evidence(self) -> list[tuple]:
        """[(kind, spec, count, context-or-None)] for the autoreg
        miner, hottest first."""
        with self._lock:
            return [
                (k, s, count, self._contexts.get((k, s)))
                for (k, s), count in sorted(
                    self._recorded.items(),
                    key=lambda kv: (-kv[1], -self._last_hit.get(kv[0], 0)),
                )
            ]

    def _schedule_save(self, delay: float = 1.0) -> None:
        with self._lock:
            if self._save_timer is not None:
                return  # a pending save will pick this signature up too
            t = threading.Timer(delay, self._save_timer_fire)
            t.daemon = True
            t.name = "bydb-plan-save"
            self._save_timer = t
        t.start()

    def _save_timer_fire(self) -> None:
        with self._lock:
            self._save_timer = None
        self._save()

    def attach_store(self, path) -> None:
        """Bind (and load) the persistent signature store."""
        p = Path(path)
        loaded: list[tuple[tuple, int, int, Optional[tuple]]] = []
        try:
            if p.exists():
                for rec in json.loads(p.read_text()).get("signatures", []):
                    try:
                        kind, spec = spec_from_json(rec)
                        ctx = rec.get("context")
                        loaded.append((
                            (kind, spec),
                            int(rec.get("count", 1)),
                            int(rec.get("last_hit_ms", 0)),
                            tuple(ctx) if ctx else None,
                        ))
                    except Exception:  # noqa: BLE001 — skip stale entries
                        continue
        except (OSError, ValueError):
            loaded = []
        with self._lock:
            self._store_path = p
            for key, count, last_ms, ctx in loaded:
                self._recorded[key] = max(self._recorded.get(key, 0), count)
                if last_ms:
                    self._last_hit[key] = max(
                        self._last_hit.get(key, 0), last_ms
                    )
                if ctx is not None and key not in self._contexts:
                    self._contexts[key] = ctx
            have_unsaved = len(self._recorded) > len(loaded)
        if have_unsaved:
            # signatures recorded before the store was bound (embedded
            # engines, bench) persist now, not on the next new plan
            self._save()

    def _save(self) -> None:
        with self._lock:
            p = self._store_path
            if p is None:
                return
            # frequency-weighted persistence, recency as the tiebreak:
            # the top-MAX_STORED ACTUALLY-HOT signatures survive a
            # restart (and warm first), not the most recently seen ones
            top = sorted(
                self._recorded.items(),
                key=lambda kv: (-kv[1], -self._last_hit.get(kv[0], 0)),
            )[:MAX_STORED]
            doc = {
                "signatures": [
                    {
                        **spec_to_json(kind, spec),
                        "count": count,
                        "last_hit_ms": self._last_hit.get((kind, spec), 0),
                        **(
                            {"context": list(self._contexts[(kind, spec)])}
                            if (kind, spec) in self._contexts
                            else {}
                        ),
                    }
                    for (kind, spec), count in top
                ]
            }
        try:
            p.parent.mkdir(parents=True, exist_ok=True)
            tmp = p.with_suffix(".tmp")
            tmp.write_text(json.dumps(doc, indent=1))
            os.replace(tmp, p)
        except OSError:
            pass  # persistence is an optimization, never a query failure

    def signatures(self) -> list[tuple[str, object]]:
        """Hottest first (count, then recency): warm_async compiles the
        actually-hot population before the long tail."""
        with self._lock:
            return [
                (k, s)
                for (k, s), _ in sorted(
                    self._recorded.items(),
                    key=lambda kv: (-kv[1], -self._last_hit.get(kv[0], 0)),
                )
            ]

    # -- warming -------------------------------------------------------------
    def _compile_one(self, kind: str, spec) -> None:
        import jax

        from banyandb_tpu.query import fused_exec, measure_exec, stream_exec

        from banyandb_tpu.storage import encoded as enc_mod

        # measure/fused kernels trace per chunk-pytree STRUCTURE, and
        # the compressed ship form (BYDB_DEVICE_DECODE, default on) is a
        # different structure from the dense one — warm the form(s)
        # production queries will actually resolve, at the canonical
        # decode widths
        if kind == "measure":
            cache, build = (
                measure_exec._KERNEL_CACHE,
                measure_exec._build_kernel,
            )
            args_list = [measure_warm_args(spec)]
            if enc_mod.device_decode_enabled():
                args_list.append(measure_decode_warm_args(spec))
        elif kind == "fused":
            cache, build = (
                fused_exec._KERNEL_CACHE,
                fused_exec._build_kernel,
            )
            args_list = [fused_warm_args(spec)]
            if enc_mod.device_decode_enabled():
                args_list.append(fused_decode_warm_args(spec))
        elif kind == "stream_mask":
            cache, build = (
                stream_exec._KERNEL_CACHE,
                stream_exec._build_kernel,
            )
            args_list = [mask_warm_args(spec)]
        else:
            return
        kernel = cache.get(spec)
        if kernel is None:
            kernel = cache[spec] = build(spec)
        # one dispatch per ship form on zero args of the production
        # shapes: populates the jit executable cache AND (through
        # utils/compile_cache) the persistent XLA cache; values are
        # irrelevant to the cache key
        for args in args_list:
            # bdlint: disable=host-sync -- warming runs on a background
            # thread and MUST block until the compile finishes; there is
            # no result to batch
            jax.block_until_ready(kernel(*args))

    def warm(self, include_builtin: bool = True, sigs=None) -> int:
        """Compile signatures now (callers wanting async use warm_async)."""
        if sigs is None:
            sigs = list(self.signatures())
            if include_builtin:
                sigs += [("measure", s) for _, s in builtin_plans()]
                sigs += [("fused", s) for _, s in builtin_fused()]
                sigs += [("stream_mask", s) for _, s in builtin_masks()]
        done = 0
        seen = set()
        for kind, spec in sigs:
            if self._cancel.is_set():
                break  # shutdown: stop at a kernel boundary, never mid-compile
            if (kind, spec) in seen:
                continue
            seen.add((kind, spec))
            try:
                self._compile_one(kind, spec)
                done += 1
            except Exception:  # noqa: BLE001 — warm must never take a server down
                self.errors += 1
        self.compiled += done
        return done

    def _warm_loop(self, include_builtin: bool) -> None:
        """Warm rounds until no more work was queued while running —
        a note_flush/warm_async arriving mid-round (e.g. plans recorded
        while the boot warm is still compiling) queues another round
        instead of being silently dropped."""
        while True:
            self.warm(include_builtin=include_builtin)
            with self._lock:
                if not self._warm_pending or self._cancel.is_set():
                    return
                self._warm_pending = False
            include_builtin = False  # follow-up rounds: recorded sigs only

    def warm_async(self, include_builtin: bool = True) -> Optional[threading.Thread]:
        """Background warm (server start / post-flush).  If a warm is
        already running, queues one more round for when it finishes."""
        if not enabled():
            return None
        with self._lock:
            if self._warm_thread is not None and self._warm_thread.is_alive():
                self._warm_pending = True
                return self._warm_thread
            t = threading.Thread(
                target=self._warm_loop,
                args=(include_builtin,),
                name="bydb-precompile",
                daemon=True,
            )
            self._warm_thread = t
        t.start()
        return t

    def note_flush(self) -> None:
        """First-flush hook: parts now exist on disk, the next query is
        the cold one — warm the recorded population once."""
        if not enabled():
            return
        with self._lock:
            if self._flush_warmed or not self._recorded:
                return
            self._flush_warmed = True
        self.warm_async(include_builtin=False)

    def wait_warm(self, timeout: float = 120.0) -> bool:
        """Block until the in-flight warm finishes (bench/tests)."""
        with self._lock:
            t = self._warm_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def shutdown(self, timeout: float = 30.0) -> None:
        """Server-stop hook: cancel warming at the next kernel boundary
        and join, so process exit never lands mid-XLA-compile (a daemon
        thread killed inside C++ aborts the interpreter); flushes any
        pending store save."""
        with self._lock:
            self._warm_pending = False
            self._cancel.set()
            t = self._warm_thread
            timer = self._save_timer
            self._save_timer = None
        if timer is not None:
            timer.cancel()
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                return  # leave cancel set; the thread exits at its next check
        self._cancel.clear()
        self._save()

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": enabled(),
                "recorded": len(self._recorded),
                "stored": str(self._store_path) if self._store_path else None,
                "compiled": self.compiled,
                "errors": self.errors,
                "warming": bool(
                    self._warm_thread and self._warm_thread.is_alive()
                ),
            }


_registry = PrecompileRegistry()


def default_registry() -> PrecompileRegistry:
    return _registry
