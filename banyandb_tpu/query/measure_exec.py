"""Device executor for measure aggregation queries.

Pipeline (SURVEY.md §3.3 data-node hot loop, rebuilt TPU-first):

  host:   sources (memtable + part blocks) -> global tag dictionaries ->
          code remap -> version dedup (lexsort) -> 8192-row chunks
  device: one jitted kernel per plan signature: time/tag masks ->
          mixed-radix group key -> segment reduce (count/sum/min/max) ->
          [+ histogram for percentile] ... executed per chunk
  host:   combine tiny per-chunk partials, invert histograms, top-N, limit

The jit cache is keyed by a static PlanSpec, so repeated queries with the
same shape (the dashboard pattern) skip compilation entirely — predicate
*values* are traced arguments, not compile-time constants.

Precision contract: device kernels produce f32 partials whose f32
accumulation span is bounded (Kahan-compensated across tiles — see
ops/groupby.py); this host loop merges per-chunk partials in f64. Net
effect: per-group sums stay within ~1e-5 relative of exact f64 at any
row count (tests/test_precision.py).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from banyandb_tpu import ops
from banyandb_tpu.api.model import (
    Aggregation,
    Condition,
    Criteria,
    LogicalExpression,
    QueryRequest,
    QueryResult,
)
from banyandb_tpu.api.schema import Measure, TagType
from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.storage.part import ColumnData
from banyandb_tpu.utils import hostops
from banyandb_tpu.utils.envflag import env_int

# stage latency instruments (always on, spans or not): the attribution
# plane ROADMAP item 1's bench reads back as stage_breakdown.  Handles
# resolved once at import — observe() never touches the registry lock.
_H_GATHER = obs_metrics.stage_histogram("gather")
_H_DEVICE = obs_metrics.stage_histogram("device_execute")
_H_MERGE = obs_metrics.stage_histogram("merge")
# the pad/pack/ship half of the decode stage (ROADMAP item 3): host-side
# narrow packing + H2D transfer time; the device half (widen/remap/f32
# convert) is fused INSIDE the plan kernel and shows up in
# device_execute, which is exactly the point
_H_DECODE = obs_metrics.stage_histogram("decode")

CHUNK = 8192
# Scan chunks are much larger than storage blocks (8192 rows,
# banyand/measure/measure.go:46): the kernel is HBM-bound, so per-chunk
# dispatch + [G]-sized host accumulation dominate at small chunks (profiled
# ~330ms of a 372ms warm 100k-group scan at 8192).  Power-of-two buckets up
# to SCAN_CHUNK keep the compiled-shape set finite.
SCAN_CHUNK = env_int("BYDB_SCAN_CHUNK", 1 << 20)
_NUM_HIST_BUCKETS = 512


def _scan_bucket(n: int) -> int:
    b = 64
    while b < n:
        b <<= 1
    return min(b, SCAN_CHUNK)


@dataclass(frozen=True)
class _PredSpec:
    """Static shape of one predicate; its value(s) arrive as traced args.

    kinds:
    - "code": compare against a global dictionary code (eq/ne) or a padded
      code set (in/not_in).
    - "lut":  a bool lookup table over global codes — how numeric range
      predicates on INT tags evaluate without shipping 64-bit tag values
      to the device (the host computes op(dict_value, literal) per code).
    """

    kind: str  # "code" | "lut"
    name: str  # tag name
    op: str  # eq/ne/in/not_in (code) | lt/le/gt/ge (lut)
    nvals: int = 1  # in/not_in set size or LUT length (static shape)


@dataclass(frozen=True)
class PlanSpec:
    """Static jit key: everything that shapes the compiled kernel."""

    tags_code: tuple[str, ...]  # tag columns shipped as global codes
    fields: tuple[str, ...]
    preds: tuple[_PredSpec, ...]
    group_tags: tuple[str, ...]
    radices: tuple[int, ...]  # global dict size per group tag
    num_groups: int
    want_minmax: bool
    hist_field: str = ""  # non-empty -> also emit histogram partials
    nrows: int = CHUNK
    group_method: str = "auto"  # ops.group_reduce method override
    # scan-order tracking: emit per-group min (scan asc) or max (desc)
    # of (ts<<32 | row) — drives first-appearance group ordering AND the
    # representative row for projected-but-not-grouped tags
    want_rep: bool = False
    rep_desc: bool = False
    # predicate expression tree over `preds`: ("p", i) leaves combined by
    # ("and", l, r) / ("or", l, r) nodes — the device lowering of a full
    # model/v1 Criteria tree (pkg/query/logical analog). () = AND of all
    # preds (the common flat case keeps its original plan signature).
    expr: tuple = ()


_KERNEL_CACHE: dict[PlanSpec, object] = {}


def _kernel_body(spec: PlanSpec):
    """The un-jitted per-chunk partial computation for `spec`.

    Shared verbatim between the staged executor (jitted per chunk by
    `_build_kernel`) and the fused whole-plan executor
    (query/fused_exec scans it over a stacked chunk batch inside ONE
    program) — one trace graph per chunk either way, which is what
    makes the staged/fused A/B byte-identical."""

    def kernel(chunk: dict, pred_vals: dict, hist_lo, hist_span):
        valid = chunk["valid"]

        def pred_mask(i: int):
            p = spec.preds[i]
            col = chunk["tags_code"][p.name]
            v = pred_vals[f"p{i}"]
            if p.kind == "lut":
                return jnp.take(v, col, mode="clip")
            if p.op in ("in", "not_in"):
                m = ops.in_set_mask(col, v)
                return ~m if p.op == "not_in" else m
            return ops.cmp_mask(col, p.op, v)

        def eval_expr(node):
            if node[0] == "p":
                return pred_mask(node[1])
            left = eval_expr(node[1])
            right = eval_expr(node[2])
            return (left & right) if node[0] == "and" else (left | right)

        if spec.expr:
            mask = valid & eval_expr(spec.expr)
        else:  # flat AND of all preds (original plan shape)
            mask = ops.mask_and(
                valid, *[pred_mask(i) for i in range(len(spec.preds))]
            )

        key_cols = [chunk["tags_code"][t] for t in spec.group_tags]
        if key_cols:
            key, _ = ops.mixed_radix_key(key_cols, spec.radices)
        else:
            key = jnp.zeros_like(chunk["series"])

        res = ops.group_reduce(
            key,
            mask,
            chunk["fields"],
            spec.num_groups,
            want_minmax=spec.want_minmax,
            method=spec.group_method,
        )
        out = {
            "count": res.count,
            "sums": res.sums,
            "mins": res.mins,
            "maxs": res.maxs,
        }
        if spec.hist_field:
            out["hist"] = ops.group_histogram(
                key,
                mask,
                chunk["fields"][spec.hist_field],
                spec.num_groups,
                hist_lo,
                hist_span,
                _NUM_HIST_BUCKETS,
            )
        if spec.want_rep:
            # scan-order tracking, 32-bit friendly (device x64 stays
            # off): per-group min/max ts, then min/max row among rows AT
            # that ts — the first row of each group under a ts ASC scan
            # (DESC under ORDER BY time DESC), which drives both group
            # emission order and the representative row (reference
            # measure_plan_groupby.go first-appearance + aggregation
            # first-fed row semantics)
            ts32 = chunk["ts"]
            row32 = chunk["row"]
            G1 = spec.num_groups + 1
            skey = jnp.where(mask, key, jnp.int32(spec.num_groups))
            if spec.rep_desc:
                gts = jax.ops.segment_max(
                    jnp.where(mask, ts32, jnp.int32(-(2**31) + 1)),
                    skey, num_segments=G1,
                )
                at = mask & (ts32 == jnp.take(gts, skey, mode="clip"))
                grow = jax.ops.segment_max(
                    jnp.where(at, row32, jnp.int32(-1)),
                    skey, num_segments=G1,
                )
            else:
                gts = jax.ops.segment_min(
                    jnp.where(mask, ts32, jnp.int32(2**31 - 1)),
                    skey, num_segments=G1,
                )
                at = mask & (ts32 == jnp.take(gts, skey, mode="clip"))
                grow = jax.ops.segment_min(
                    jnp.where(at, row32, jnp.int32(2**31 - 1)),
                    skey, num_segments=G1,
                )
            out["rep_ts"] = gts[: spec.num_groups]
            out["rep_row"] = grow[: spec.num_groups]
        return out

    return kernel


def _build_kernel(spec: PlanSpec):
    """Construct + jit the per-chunk partial computation for `spec`.

    The device-side decode stage (ops.decode.decode_chunk) runs FIRST
    inside the jitted program: chunks arriving in the compressed ship
    form (narrow dict codes + [S, L] remap LUTs + narrow int fields,
    ``BYDB_DEVICE_DECODE``) widen/remap on device, fused into the same
    dispatch; canonical (pre-decoded) chunks pass through untouched, so
    one jitted kernel serves both ship forms (jit re-specializes per
    chunk pytree structure)."""
    body = _kernel_body(spec)

    def kernel(chunk: dict, pred_vals: dict, hist_lo, hist_span):
        return body(ops.decode_chunk(chunk), pred_vals, hist_lo, hist_span)

    return jax.jit(kernel)


class GlobalDicts:
    """Union of per-source tag dictionaries -> stable global codes.

    Codes are append-only: once a value has a code it keeps it forever,
    which is what lets DictState persist dictionaries (and cached
    per-part remap LUTs) across queries.
    """

    def __init__(self, tag_names: Sequence[str]):
        self.maps: dict[str, dict[bytes, int]] = {t: {} for t in tag_names}

    def ensure(self, tag: str) -> None:
        self.maps.setdefault(tag, {})

    def add_source(self, tag: str, d: list[bytes]) -> np.ndarray:
        """-> LUT local_code -> global_code for one source."""
        m = self.maps[tag]
        return np.fromiter(
            (m.setdefault(v, len(m)) for v in d), dtype=np.int32, count=len(d)
        )

    def size(self, tag: str) -> int:
        return max(len(self.maps[tag]), 1)

    def code_of(self, tag: str, value: bytes) -> int:
        return self.maps[tag].get(value, -1)

    def absent_code(self, tag: str) -> int:
        """Global code for the empty value (rows from sources that predate
        the tag)."""
        m = self.maps[tag]
        return m.setdefault(b"", len(m))

    def values(self, tag: str) -> list[bytes]:
        m = self.maps[tag]
        out = [b""] * len(m)
        for v, c in m.items():
            out[c] = v
        return out


class DictState:
    """Per-(engine, measure) persistent dictionary + remap state.

    The serving-cache companion (VERDICT r1 weak #5): global tag
    dictionaries grow monotonically across queries, per-part remap LUTs
    are cached by immutable part identity, and the token keys gathered
    chunks in the process serving cache so a repeat query skips
    _gather_rows entirely.  All access to `dicts` (reads included — dict
    iteration during insert raises) happens under `lock`; queries run
    concurrently on server threads.

    Growth bound: group cardinality is the product of all-time dict
    sizes, so tag churn under retention would inflate kernels without
    bound.  reset() discards the state (new token orphans old cache
    entries, which simply LRU out) — compute_partials calls it when the
    group space exceeds BYDB_MAX_PERSISTENT_GROUPS, rebounding
    cardinality to the live data on the next gather.
    """

    def __init__(self):
        import threading

        self.lock = threading.Lock()
        self._reset_locked()

    def _reset_locked(self):
        import uuid

        self.dicts = GlobalDicts(())
        self.remaps: dict[tuple, np.ndarray] = {}
        self.token = uuid.uuid4().hex
        # snapshot caches, valid while their length still covers the
        # (append-only) dict: values = code -> bytes list; ranks = code ->
        # lexicographic position among dict values (canonical group order
        # without a per-query Python sort over 100k groups)
        self.values_cache: dict[str, list] = {}
        self.rank_cache: dict[str, np.ndarray] = {}

    def reset(self):
        with self.lock:
            self._reset_locked()

    def values_snapshot(self, tag: str) -> list:
        """code -> value list for `tag`; cached, caller holds self.lock.
        The returned list is immutable by convention (shared across
        queries): dict growth rebuilds a fresh list."""
        m = self.dicts.maps.get(tag, {})
        cached = self.values_cache.get(tag)
        if cached is None or len(cached) != len(m):
            cached = self.dicts.values(tag)
            self.values_cache[tag] = cached
        return cached

    def rank_lut(self, tag: str, values: list) -> np.ndarray:
        """code -> bytes-lexicographic rank over at least `values`.

        Ranks from a larger (append-only) snapshot stay order-preserving
        over any older snapshot's codes, so a cached superset is reusable;
        callers only need relative order, not density.  The cache is
        guarded by snapshot identity — `values` must be the object
        values_snapshot currently hands out — so a query holding a
        pre-reset snapshot can neither reuse nor poison the post-reset
        cache (codes from the old dict generation rank differently).
        Takes self.lock.
        """
        with self.lock:
            current = self.values_cache.get(tag)
            if values is not current:
                return _build_rank_lut(values)  # stale/foreign: uncached
            lut = self.rank_cache.get(tag)
            if lut is None or len(lut) < len(values):
                lut = self.rank_cache[tag] = _build_rank_lut(values)
            return lut


def _build_rank_lut(values: list) -> np.ndarray:
    """code -> bytes-lexicographic rank among `values` (inverse argsort)."""
    order = sorted(range(len(values)), key=values.__getitem__)
    lut = np.empty(len(values), dtype=np.int64)
    lut[np.asarray(order, dtype=np.int64)] = np.arange(
        len(values), dtype=np.int64
    )
    return lut


_MAX_PERSISTENT_GROUPS = env_int("BYDB_MAX_PERSISTENT_GROUPS", 1 << 18)


def _tag_value_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, int):
        return v.to_bytes(8, "little", signed=True)
    raise TypeError(f"unsupported tag literal {type(v)}")


def _collect_conditions(c: Optional[Criteria]) -> list[Condition]:
    """Flatten an AND-tree; callers needing OR use _lower_criteria."""
    if c is None:
        return []
    if isinstance(c, Condition):
        return [c]
    assert isinstance(c, LogicalExpression)
    if c.op != "and":
        raise NotImplementedError(
            "AND-only path; OR criteria lower via _lower_criteria"
        )
    return _collect_conditions(c.left) + _collect_conditions(c.right)


def _lower_criteria(c: Optional[Criteria]) -> tuple[list[Condition], tuple]:
    """Full Criteria tree -> (predicate leaves, index expression tree).

    Pure-AND trees return expr=() so the common flat case keeps its
    original plan signature (jit-cache stability); OR anywhere produces
    a nested ("and"|"or", left, right) tree over ("p", i) leaves that
    the kernel evaluates as mask algebra (union of in-set masks — the
    device lowering of pkg/query/logical's OR nodes)."""
    conds: list[Condition] = []

    def walk(node):
        if isinstance(node, Condition):
            conds.append(node)
            return ("p", len(conds) - 1)
        assert isinstance(node, LogicalExpression), node
        if node.op not in ("and", "or"):
            raise ValueError(f"unknown logical op {node.op!r}")
        return (node.op, walk(node.left), walk(node.right))

    if c is None:
        return [], ()
    expr = walk(c)

    def pure_and(n) -> bool:
        return n[0] == "p" or (
            n[0] == "and" and pure_and(n[1]) and pure_and(n[2])
        )

    return conds, (() if pure_and(expr) else expr)


class Partials:
    """Per-node partial aggregates keyed by decoded tag-value tuples.

    The wire unit of distributed map-reduce aggregation (the reference's
    agg_return_partial InternalQueryResponse,
    docs/concept/distributed-measure-aggregation.md): nodes return these,
    the liaison combines by group tuple and finalizes.  Arrays cover only
    nonempty groups (dense [G] layouts never cross nodes).

    Group identity is dual-representation: either materialized value
    tuples (`groups`, the wire/combine form) or dense global-code rows
    (`codes` [K, T] + `group_values` dict snapshots, the standalone hot
    path).  Tuples materialize lazily on first `.groups` access — a
    standalone TopN over 100k groups never builds 100k Python tuples
    (profiled at ~130ms/query before this split).
    """

    __slots__ = (
        "group_tags", "count", "sums", "mins", "maxs", "hist", "hist_lo",
        "hist_span", "field_stats", "_groups", "codes", "group_values",
        "rep_key", "rep_desc", "rep_vals",
    )

    def __init__(
        self,
        group_tags: tuple[str, ...],
        groups: Optional[list] = None,  # tag-value tuple per nonempty group
        count: np.ndarray = None,  # f64 [K]
        sums: dict = None,  # field -> f64 [K]
        mins: dict = None,
        maxs: dict = None,
        hist: Optional[np.ndarray] = None,  # [K, B]
        hist_lo: float = 0.0,
        hist_span: float = 1.0,
        field_stats: dict = None,  # f -> (min, max)
        codes: Optional[np.ndarray] = None,  # int32 [K, T] global codes
        group_values: Optional[dict] = None,  # tag -> list[bytes] snapshot
        rep_key: Optional[np.ndarray] = None,  # int64 [K] scan-order key
        rep_desc: bool = False,
        rep_vals: Optional[dict] = None,  # tag -> list[bytes] [K] rep row
    ):
        if groups is None and codes is None:
            raise TypeError("Partials needs groups or codes+group_values")
        self.group_tags = group_tags
        self._groups = groups
        self.codes = codes
        self.group_values = group_values
        self.count = count
        self.sums = sums
        self.mins = mins
        self.maxs = maxs
        self.hist = hist
        self.hist_lo = hist_lo
        self.hist_span = hist_span
        self.field_stats = {} if field_stats is None else field_stats
        self.rep_key = rep_key
        self.rep_desc = rep_desc
        self.rep_vals = rep_vals

    @property
    def groups(self) -> list[tuple[bytes, ...]]:
        if self._groups is None:
            k = self.codes.shape[0]
            if not self.group_tags:
                self._groups = [()] * k
            elif k == 0:
                self._groups = []
            else:
                cols = [
                    np.asarray(self.group_values[t], dtype=object)[
                        self.codes[:, i]
                    ]
                    for i, t in enumerate(self.group_tags)
                ]
                self._groups = list(zip(*cols))
        return self._groups

    @groups.setter
    def groups(self, v: list) -> None:
        self._groups = v

    def group_key(self, i: int) -> tuple[bytes, ...]:
        """Decode ONE group's value tuple without materializing the rest."""
        if self._groups is not None:
            return self._groups[i]
        return tuple(
            self.group_values[t][int(self.codes[i, j])]
            for j, t in enumerate(self.group_tags)
        )

    def content_bytes(self) -> bytes:
        """Canonical byte serialization of every numeric/representative
        component — THE byte-parity oracle the A/B contracts
        (``BYDB_FUSED``, ``BYDB_DEVICE_DECODE``, ``BYDB_PIPELINE``)
        are asserted against (tests/test_fused_exec.py,
        tests/test_decode.py, scripts/decode_smoke.py all compare this
        one serialization, so a new Partials field added here is
        parity-pinned everywhere at once)."""
        parts = [
            self.count.tobytes(),
            self.codes.tobytes() if self.codes is not None else b"",
        ]
        for d in (self.sums, self.mins, self.maxs):
            for k in sorted(d):
                parts.append(d[k].tobytes())
        if self.hist is not None:
            parts.append(self.hist.tobytes())
        if self.rep_key is not None:
            parts.append(self.rep_key.tobytes())
        if self.rep_vals is not None:
            parts.append(repr(sorted(self.rep_vals.items())).encode())
        return b"".join(parts)


def execute_aggregate(
    measure: Measure,
    request: QueryRequest,
    sources: list[ColumnData],
    dict_state: Optional[DictState] = None,
    analyzers: Optional[dict] = None,
    span=None,
    plan_hints=None,
) -> QueryResult:
    """Run a group-by/aggregate/top-N/percentile query over decoded sources."""
    partial = compute_partials(
        measure, request, sources, dict_state=dict_state, analyzers=analyzers,
        span=span, plan_hints=plan_hints,
    )
    return finalize_partials(
        measure, request, [partial], dict_state=dict_state, span=span
    )


def compute_partials(
    measure: Measure,
    request: QueryRequest,
    sources: list[ColumnData],
    hist_range: Optional[tuple[float, float]] = None,
    dict_state: Optional[DictState] = None,
    analyzers: Optional[dict] = None,
    span=None,
    plan_hints=None,
) -> Partials:
    """The 'map' phase: device scan+reduce over local sources.

    `hist_range` fixes the percentile histogram range (distributed
    two-pass: the liaison first combines field_stats, then re-requests
    with the global range so node histograms are combinable).

    `dict_state` (engine-owned) turns on the serving-cache fast path:
    persistent global dictionaries, cached per-part remaps, and cached
    gathered chunks keyed by part identities — repeat queries skip the
    whole host gather.

    `span` (obs.tracer.Span or None): tracing sink — gather/reduce child
    spans with cache hit/miss tags and device/host attribution.  None
    keeps the path span-free; the stage histograms observe either way.

    `plan_hints` (query/planner.PlanDecision or None): the cost-based
    planner's result-preserving refinements — a group-method override
    when the estimated distinct group count crosses the hash/sort
    crossover on the other side of the static radix product, a minimum
    fused chunk-count bucket (signature stability), and a
    prefer-staged routing when the estimated footprint exceeds the
    fused budget.  ``actual_rows`` is written back for the planner
    span's est-vs-actual tag.
    """
    import time as _time
    conds, expr = _lower_criteria(request.criteria)
    group_tags = tuple(request.group_by.tag_names) if request.group_by else ()
    agg = request.agg

    # --- which columns ride to the device ---------------------------------
    range_ops = {"lt", "le", "gt", "ge"}
    tags_code: set[str] = set(group_tags)
    for c in conds:
        measure.tag(c.name)  # validate against schema (KeyError on typo)
        tags_code.add(c.name)
    # Representative tags: projected but not grouped — each output group
    # carries the first-scanned row's values for these (reference
    # aggregation copies the first fed row's TagFamilies).  Unknown
    # projected tags are schema errors (ref WantErr cases).
    rep_tags: tuple[str, ...] = ()
    if group_tags or agg is not None:
        schema_fields = {f.name for f in measure.fields}
        rep_list = []
        for t in request.tag_projection:
            if t in group_tags:
                continue
            if t in schema_fields:
                # bydbql puts the SELECT list into BOTH projections, so a
                # grouped `SELECT svc, value ...` names the field here;
                # fields are never representative tags
                continue
            measure.tag(t)  # KeyError -> INVALID_ARGUMENT on the wire
            rep_list.append(t)
            tags_code.add(t)
        rep_tags = tuple(dict.fromkeys(rep_list))
    rep_desc = request.order_by_ts == "desc"
    # scan-order tracking serves grouped ordering AND the global-agg
    # representative row (a no-group aggregate's output row carries the
    # first scanned row's projected tags)
    want_rep = bool(group_tags) or bool(rep_tags)
    # Projection names that aren't schema fields (e.g. tags from a QL
    # SELECT list) are dropped — they'd only materialize zero columns.
    # Raw (string/binary) fields never ride the device path either: they
    # are stored as '@f:' tag columns and only the raw-row path serves
    # them (models/measure._raw_fields).
    from banyandb_tpu.api.schema import FieldType as _FT

    known = {
        f.name
        for f in measure.fields
        if f.type not in (_FT.STRING, _FT.DATA_BINARY)
    }
    fields = {f for f in request.field_projection if f in known}
    if agg:
        fields.add(agg.field_name)
    if request.top:
        fields.add(request.top.field_name)

    # --- global dictionaries + remapped concatenated columns --------------
    # gd and token are captured atomically under the lock: a concurrent
    # cap-triggered reset swaps dict_state.dicts/token together, and all
    # cache writes below guard on `dict_state.dicts is gd` so an in-flight
    # query can never poison the post-reset caches with old codes.
    if dict_state is None:
        gd = GlobalDicts(sorted(tags_code))
        token = None
    else:
        with dict_state.lock:
            # Growth bound: reset bloated state (tag churn under
            # retention) so cardinality re-bounds to live data.
            prod = 1
            for t in group_tags:
                prod *= max(len(dict_state.dicts.maps.get(t, ())), 1)
            if prod > _MAX_PERSISTENT_GROUPS:
                dict_state._reset_locked()
            gd = dict_state.dicts
            token = dict_state.token
            for t in tags_code:
                gd.ensure(t)

    # the compressed-ship flag is read ONCE per query and pinned into the
    # gather cache key: the two ship forms produce differently-shaped
    # gathered snapshots, and a live flag flip must never serve one
    # mode's cache entry to the other
    from banyandb_tpu.storage import encoded as enc_mod

    device_decode = enc_mod.device_decode_enabled()
    gather_key = None
    if dict_state is not None and sources and all(
        s.cache_key is not None for s in sources
    ):
        gather_key = (
            "gather",
            token,
            tuple(s.cache_key for s in sources),
            request.time_range.begin_millis,
            request.time_range.end_millis,
            tuple(sorted(tags_code)),
            tuple(sorted(fields)),
            device_decode,
        )

    def _do_gather():
        return _gather_rows(
            sources,
            sorted(tags_code),
            sorted(fields),
            gd,
            request.time_range.begin_millis,
            request.time_range.end_millis,
            dict_state=dict_state,
            device_decode=device_decode,
        )

    t_gather0 = _time.perf_counter()
    gather_loaded: list = []  # loader ran -> serving-cache miss
    if gather_key is not None:
        from banyandb_tpu.storage.cache import global_cache

        def _loader():
            gather_loaded.append(1)
            return _do_gather()

        chunks_np = global_cache().get_or_load(gather_key, _loader)
    else:
        gather_loaded.append(1)
        chunks_np = _do_gather()
    gather_ms = (_time.perf_counter() - t_gather0) * 1000
    _H_GATHER.observe(gather_ms)
    n = chunks_np["ts"].shape[0]
    if span is not None:
        g = span.child("gather").tag("rows", int(n)).tag(
            "sources", len(sources)
        ).tag(
            "serving_cache",
            ("off" if gather_key is None else "miss")
            if gather_loaded
            else "hit",
        )
        g.t0 = t_gather0  # span covers the gather that already ran
        g.finish()
    # epoch = global min ts keeps chunk-relative int32 offsets
    # nonnegative for the scan-order key; spans >= 2^31 ms (~24.8 days)
    # would wrap the int32 cast, so rep tracking degrades to canonical
    # ordering there instead of silently corrupting
    epoch = int(chunks_np["ts"].min()) if n else 0
    if n and int(chunks_np["ts"].max()) - epoch >= 2**31:
        want_rep = False
        rep_tags = ()

    # --- plan signature ---------------------------------------------------
    # All gd reads happen under the DictState lock (concurrent queries
    # mutate the same dicts); group value lists are snapshotted here for
    # the decode step below.
    import contextlib

    pred_specs = []
    pred_vals: dict[str, jax.Array] = {}
    with dict_state.lock if dict_state is not None else contextlib.nullcontext():
        for i, c in enumerate(conds):
            if c.op in range_ops or c.op == "match":
                # LUT predicates (range / MATCH): op(dict_value, literal)
                # evaluated host-side per global code -> bool LUT gathered
                # on device (64-bit tag values and analyzer tokenization
                # never reach the int32 kernel).  Shared with the raw row
                # path (query/filter.py) so host and device semantics
                # cannot drift.
                from banyandb_tpu.query.filter import match_lut, range_lut

                vals = gd.values(c.name)
                if c.op == "match":
                    lut = match_lut(c, analyzers, vals)
                else:
                    lut = range_lut(
                        c.op, c.value, vals, measure.tag(c.name).type
                    )
                if not len(lut):
                    lut = np.zeros(1, dtype=bool)
                pred_specs.append(_PredSpec("lut", c.name, c.op, nvals=len(lut)))
                pred_vals[f"p{i}"] = jnp.asarray(lut)
            elif c.op in ("in", "not_in"):
                vals = [gd.code_of(c.name, _tag_value_bytes(v)) for v in c.value]
                arr = np.asarray(vals or [-1], dtype=np.int32)
                pred_specs.append(_PredSpec("code", c.name, c.op, nvals=len(arr)))
                pred_vals[f"p{i}"] = jnp.asarray(arr)
            else:
                code = gd.code_of(c.name, _tag_value_bytes(c.value))
                pred_specs.append(_PredSpec("code", c.name, c.op))
                pred_vals[f"p{i}"] = jnp.int32(code)

        radices = tuple(gd.size(t) for t in group_tags)
        if dict_state is not None and dict_state.dicts is gd:
            group_values = {
                t: dict_state.values_snapshot(t) for t in group_tags
            }
        else:
            group_values = {t: gd.values(t) for t in group_tags}
    num_groups = 1
    for r in radices:
        num_groups *= r

    want_percentile = bool(agg and agg.function == "percentile")
    hist_field = agg.field_name if want_percentile else ""
    # min/max always computed when percentile (field_stats feed the
    # distributed two-pass range agreement).
    want_minmax = not agg or agg.function in ("min", "max") or want_percentile

    nrows = SCAN_CHUNK if n > SCAN_CHUNK else _scan_bucket(max(n, 1))
    # planner group-method override (query/planner): applied ONLY when
    # the estimate lands on the other side of the hash/sort crossover
    # from the static radix product — the common case keeps "auto" so
    # the plan signature (jit cache, precompile store, kernel budgets)
    # is exactly the pre-planner one.  Methods are bit-identical within
    # the span bound (ops/groupby contract), so BYDB_PLANNER=0/1 result
    # JSON stays byte-identical.
    group_method = "auto"
    if plan_hints is not None and plan_hints.group_method:
        group_method = plan_hints.group_method
    if plan_hints is not None:
        plan_hints.actual_rows = int(n)
    spec = PlanSpec(
        tags_code=tuple(sorted(tags_code)),
        fields=tuple(sorted(fields)),
        preds=tuple(pred_specs),
        group_tags=group_tags,
        radices=radices,
        num_groups=max(num_groups, 1),
        want_minmax=want_minmax,
        hist_field=hist_field,
        nrows=nrows,
        group_method=group_method,
        expr=expr,
        want_rep=want_rep,
        rep_desc=rep_desc,
    )
    kernel = _KERNEL_CACHE.get(spec)
    if kernel is None:
        kernel = _KERNEL_CACHE[spec] = _build_kernel(spec)
    # function-local import: precompile imports this module's builders
    from banyandb_tpu.query.precompile import default_registry

    # the (group, measure) context turns this anonymous signature into
    # autoreg evidence (query/planner.signature_from_spec)
    default_registry().record(
        "measure", spec, context=(measure.group, measure.name)
    )

    # --- histogram range from host stats (two-pass percentile) ------------
    if hist_range is not None:
        hist_lo, hist_span = hist_range
    elif want_percentile and n:
        fv = chunks_np["fields"][hist_field]
        hist_lo = float(fv.min())
        hist_span = max(float(fv.max()) - hist_lo, 1e-6)
    else:
        hist_lo, hist_span = 0.0, 1.0

    # --- partials-level serving cache -------------------------------------
    # Repeat queries over unchanged sources (the dashboard pattern) skip
    # the whole reduction: the cache key pins the gathered snapshot
    # (gather_key covers source identities + time range + dict token),
    # the compiled plan signature, and every predicate VALUE.
    partials_key = None
    if gather_key is not None:
        import hashlib as _hl

        h = _hl.blake2b(digest_size=16)
        for pk in sorted(pred_vals):
            h.update(pk.encode())
            h.update(np.asarray(pred_vals[pk]).tobytes())
        partials_key = (
            "partials",
            gather_key,
            spec,
            # rep_tags are NOT part of the kernel signature (the kernel
            # only tracks the representative ROW; decode happens host-
            # side), so they must pin the cache entry separately — a
            # projection-free query must never serve a projecting one
            # cached partials with rep_vals=None
            rep_tags,
            round(hist_lo, 9),
            round(hist_span, 9),
            h.hexdigest(),
        )

    rspan = span.child("reduce") if span is not None else None
    reduce_loaded: list = []

    def _reduce() -> Partials:
        reduce_loaded.append(1)
        return _reduce_partials(
            measure, chunks_np, conds, expr, pred_vals, spec, kernel,
            group_values, rep_tags, rep_desc, want_rep, gd, dict_state,
            hist_lo, hist_span, want_percentile, epoch, gather_key, agg,
            span=rspan, plan_hints=plan_hints,
        )

    try:
        if partials_key is not None:
            from banyandb_tpu.storage.cache import global_cache

            return global_cache().get_or_load(partials_key, _reduce)
        return _reduce()
    finally:
        if rspan is not None:
            rspan.tag(
                "partials_cache",
                ("off" if partials_key is None else "miss")
                if reduce_loaded
                else "hit",
            )
            if not reduce_loaded:  # replayed: no device leg ran
                rspan.tag("device_ms", 0.0).tag(
                    "host_ms", round(rspan.duration_ms, 3)
                )
            rspan.finish()


def _reduce_partials(
    measure,
    chunks_np,
    conds,
    expr,
    pred_vals,
    spec,
    kernel,
    group_values,
    rep_tags,
    rep_desc,
    want_rep,
    gd,
    dict_state,
    hist_lo,
    hist_span,
    want_percentile,
    epoch,
    gather_key,
    agg,
    span=None,
    plan_hints=None,
):
    """The reduction tail of compute_partials (cacheable unit).

    `span` gets the device/host attribution tags: device_ms is the time
    spent at the two accelerator boundaries (kernel dispatch + the
    batched device_get), host_ms the rest of the reduction; pad_ship_ms
    is the prefetch thread's chunk pad+transfer work (overlapped, so it
    is NOT a subset of the wall duration)."""
    import contextlib
    import time as _time

    t_reduce0 = _time.perf_counter()
    n = chunks_np["ts"].shape[0]
    group_tags = spec.group_tags
    radices = spec.radices
    want_minmax = spec.want_minmax
    # --- exact-f64 host path for FLOAT-field aggregation ------------------
    # The reference aggregates float64 fields in full f64 and its goldens
    # compare exactly (852.0409999999999 etc.); the device kernel's f32
    # partials cannot reproduce that.  Float aggregates therefore reduce
    # on host in f64 (vectorized bincount — still columnar, just not on
    # the accelerator); INT fields keep the device path (f32 partials
    # are exact to 2^24 per chunk and merge in f64).
    agg_is_float = False
    if agg and agg.function != "percentile":
        try:
            agg_is_float = measure.field(agg.field_name).type.name == "FLOAT"
        except KeyError:
            agg_is_float = False
    if agg_is_float and n:
        out = _host_float_partials(
            measure, None, _materialize_tag_codes(chunks_np, spec.tags_code),
            conds, expr, pred_vals, spec,
            group_values, rep_tags, rep_desc, want_rep, gd, dict_state,
        )
        if span is not None:
            # exact-f64 host reduction: no device leg by design
            span.tag("path", "host_f64").tag("device_ms", 0.0).tag(
                "host_ms",
                round((_time.perf_counter() - t_reduce0) * 1000, 3),
            )
        return out

    # --- run chunks, combine partials ------------------------------------
    G = spec.num_groups
    count = np.zeros(G, dtype=np.float64)
    sums = {f: np.zeros(G, dtype=np.float64) for f in spec.fields}
    mins = {f: np.full(G, np.inf, dtype=np.float64) for f in spec.fields}
    maxs = {f: np.full(G, -np.inf, dtype=np.float64) for f in spec.fields}
    hist = np.zeros((G, _NUM_HIST_BUCKETS), dtype=np.float64) if want_percentile else None
    rep_ts_acc = rep_row_acc = None
    if want_rep:
        sentinel = -(2**62) if rep_desc else 2**62
        rep_ts_acc = np.full(G, sentinel, dtype=np.int64)
        rep_row_acc = np.full(G, sentinel, dtype=np.int64)

    # device scalars hoisted out of the chunk loop: rebuilding them per
    # chunk costs two convert_element_type dispatches each iteration
    # (~profiled third of warm query latency on many-chunk scans)
    hist_lo_dev = jnp.float32(hist_lo)
    hist_span_dev = jnp.float32(hist_span)
    dev_cache = None
    if gather_key is not None:
        from banyandb_tpu.storage.cache import device_cache

        dev_cache = device_cache()

    def _absorb(out: dict) -> None:
        """Fold ONE chunk's partials (already on host) into the f64
        accumulators — the host half of the precision contract."""
        nonlocal count, hist, rep_ts_acc, rep_row_acc
        count += out["count"].astype(np.float64)
        for f in spec.fields:
            sums[f] += out["sums"][f].astype(np.float64)
            if want_minmax:
                mins[f] = np.minimum(mins[f], out["mins"][f])
                maxs[f] = np.maximum(maxs[f], out["maxs"][f])
        if hist is not None:
            hist += out["hist"].astype(np.float64)
        if rep_ts_acc is not None:
            rts = out["rep_ts"].astype(np.int64) + epoch
            rrow = out["rep_row"].astype(np.int64)
            if rep_desc:
                better = (rts > rep_ts_acc) | (
                    (rts == rep_ts_acc) & (rrow > rep_row_acc)
                )
            else:
                better = (rts < rep_ts_acc) | (
                    (rts == rep_ts_acc) & (rrow < rep_row_acc)
                )
            rep_ts_acc = np.where(better, rts, rep_ts_acc)
            rep_row_acc = np.where(better, rrow, rep_row_acc)

    # Gather/compute pipeline, two overlaps stacked per chunk:
    # (1) while the device executes chunk k, a prefetch thread pads and
    #     ships chunk k+1 (storage/chunk_stream; BYDB_PIPELINE=0 forces
    #     the strict-serial path — results are byte-identical either
    #     way because chunks are absorbed in scan order regardless);
    # (2) chunk k's device->host transfer happens AFTER chunk k+1's
    #     kernel is dispatched, so transfer overlaps compute.  The whole
    #     result pytree moves in a single batched device_get per chunk
    #     instead of one blocking np.asarray per column (the 29-site
    #     host-sync audit that motivated bdlint).
    from banyandb_tpu.storage.chunk_stream import prefetched

    # pad/ship accumulation crosses into the prefetch worker thread:
    # plain list appends (GIL-atomic), summed by the owner below — Span
    # objects themselves are single-owner and never touched off-thread
    pad_ship_s: list = []
    chunks_built: list = []
    # (shipped, dense) bytes per built chunk: the decode span's
    # compression evidence (dense = what the decoded i32/f32 ship form
    # would have moved for the same columns)
    ship_stats: list = []

    lut_cache: dict = {}  # remap LUTs ship once per reduction

    def _build_chunk(start: int, end: int):
        t0 = _time.perf_counter()
        chunks_built.append(1)
        try:
            return _device_chunk(
                chunks_np, start, end, spec, epoch, ship_stats=ship_stats,
                lut_cache=lut_cache,
            )
        finally:
            pad_ship_s.append(_time.perf_counter() - t0)

    def _make_chunk(start: int, end: int):
        if dev_cache is not None:
            # Chunks depend only on (gathered data, shape, columns): keep
            # the padded device arrays resident so repeat queries skip
            # host->HBM transfer too.
            ck = (
                "device_chunk",
                gather_key,
                start,
                end,
                spec.nrows,
                spec.tags_code,
                spec.fields,
            )
            return dev_cache.get_or_load(
                ck, lambda: _build_chunk(start, end)
            )
        return _build_chunk(start, end)

    chunk_spans = []
    for start in range(0, max(n, 1), spec.nrows):
        end = min(start + spec.nrows, n)
        if end <= start:
            break
        chunk_spans.append((start, end))

    # Fused whole-plan path (query/fused_exec, BYDB_FUSED=0 restores
    # the staged loop below): the SAME per-chunk body scans over a
    # stacked [C, nrows] batch inside ONE program — one dispatch in, one
    # batched device_get out per part-batch — and the per-chunk partials
    # come back stacked for the identical f64 absorb loop.
    from banyandb_tpu.query import fused_exec

    device_s = 0.0  # time at the accelerator boundaries (dispatch + get)
    dispatches = 0
    fused_cache_tag = None
    # planner hints (query/planner): prefer_staged routes an estimated-
    # over-budget batch straight to the staged loop; min_bucket rounds
    # the chunk-count bucket UP to the estimate's bucket (padding chunks
    # are fully invalid — byte-identical, one compiled program for a
    # part population oscillating around a bucket boundary)
    min_bucket = None
    hinted_staged = False
    if plan_hints is not None:
        min_bucket = plan_hints.chunk_bucket
        hinted_staged = plan_hints.prefer_staged
    if not hinted_staged and fused_exec.eligible(
        spec, len(chunk_spans), min_bucket=min_bucket
    ):
        path = "fused"
        moved_chunks, device_s, fused_cache_tag = fused_exec.run_fused(
            chunks_np,
            chunk_spans,
            spec,
            pred_vals,
            hist_lo_dev,
            hist_span_dev,
            epoch,
            gather_key=gather_key,
            dev_cache=dev_cache,
            pad_ship_s=pad_ship_s,
            ship_stats=ship_stats,
            min_bucket=min_bucket,
        )
        dispatches = 1
        for moved in moved_chunks:
            _absorb(moved)
    else:
        path = "staged"
        pending = None
        for chunk in prefetched(
            [lambda s=s, e=e: _make_chunk(s, e) for s, e in chunk_spans],
            name="bydb-chunk-prefetch",
        ):
            t_d = _time.perf_counter()
            out = kernel(chunk, pred_vals, hist_lo_dev, hist_span_dev)
            device_s += _time.perf_counter() - t_d
            dispatches += 1
            if pending is not None:
                t_d = _time.perf_counter()
                # bdlint: disable=host-sync -- the result boundary: one
                # batched transfer per chunk, overlapped with dispatch above
                moved = jax.device_get(pending)
                device_s += _time.perf_counter() - t_d
                _absorb(moved)
            pending = out
        if pending is not None:
            t_d = _time.perf_counter()
            # bdlint: disable=host-sync -- final chunk's result boundary
            moved = jax.device_get(pending)
            device_s += _time.perf_counter() - t_d
            _absorb(moved)
    _H_DEVICE.observe(device_s * 1000)
    # -- decode stage attribution (ROADMAP item 3) ------------------------
    # host half = narrow pack + pad + H2D ship (pad_ship_s, overlapped
    # with device execution under BYDB_PIPELINE); the device half
    # (widen/remap/f32 convert) is fused into the plan dispatch and is
    # deliberately part of device_execute.  Byte counters make the
    # compression win attributable even on a cpu-fallback bench run.
    decode_ms = sum(pad_ship_s) * 1000
    shipped_bytes = sum(s for s, _ in ship_stats)
    dense_bytes = sum(d for _, d in ship_stats)
    decode_mode = "device" if "src_ord" in chunks_np else "host"
    _H_DECODE.observe(decode_ms)
    if ship_stats:
        meter = obs_metrics.global_meter()
        meter.counter_add(
            "decode_ship_bytes", float(shipped_bytes), labels={"form": "shipped"}
        )
        meter.counter_add(
            "decode_ship_bytes", float(dense_bytes), labels={"form": "dense"}
        )
    if span is not None:
        dspan = span.child("decode")
        dspan.tag("mode", decode_mode).tag(
            "host_ms", round(decode_ms, 3)
        ).tag("shipped_bytes", shipped_bytes).tag(
            "dense_bytes", dense_bytes
        ).tag(
            "ratio",
            round(dense_bytes / shipped_bytes, 2) if shipped_bytes else 1.0,
        )
        dspan.finish()
    if span is not None:
        total_ms = (_time.perf_counter() - t_reduce0) * 1000
        span.tag("device_ms", round(device_s * 1000, 3)).tag(
            "host_ms", round(max(total_ms - device_s * 1000, 0.0), 3)
        ).tag("chunks", len(chunk_spans)).tag(
            "pad_ship_ms", round(sum(pad_ship_s) * 1000, 3)
        ).tag("path", path).tag("dispatches", dispatches)
        if dev_cache is not None:
            if fused_cache_tag is not None:
                span.tag("device_cache", fused_cache_tag)
            else:
                span.tag(
                    "device_cache",
                    f"{len(chunk_spans) - len(chunks_built)} hit / "
                    f"{len(chunks_built)} built",
                )

    # --- dense [G] arrays -> nonempty-group records (codes stay dense
    # int32 rows; value tuples materialize lazily, Partials.groups) -------
    if group_tags:
        nz = np.nonzero(count > 0)[0]
        codes = (
            np.stack(np.unravel_index(nz, radices), axis=1).astype(np.int32)
            if len(nz)
            else np.zeros((0, len(group_tags)), np.int32)
        )
    else:
        nz = np.asarray([0])
        codes = np.zeros((1, 0), np.int32)
    rep_key = None
    if rep_ts_acc is not None:
        # [K, 2] (absolute ts, row) scan-order key, compared
        # lexicographically; row is only a local tie-break (cross-node
        # combines compare ts first, which is what first-appearance
        # ordering needs)
        rep_key = np.stack([rep_ts_acc[nz], rep_row_acc[nz]], axis=1)
    rep_vals = None
    if rep_tags and rep_key is not None and len(nz):
        # decode each group's representative row into the gathered cols
        rows = np.clip(rep_key[:, 1], 0, max(n - 1, 0))
        with dict_state.lock if dict_state is not None else contextlib.nullcontext():
            rep_vals = {}
            for t in rep_tags:
                vals_list = gd.values(t)
                varr = np.asarray(vals_list, dtype=object)
                rep_codes_t = _host_tag_codes(chunks_np, t, rows)
                rep_vals[t] = varr[rep_codes_t].tolist()
    elif rep_tags:
        rep_vals = {t: [] for t in rep_tags}
    field_stats = {}
    if want_minmax:
        for f in spec.fields:
            valid_groups = count > 0
            if valid_groups.any():
                field_stats[f] = (
                    float(mins[f][valid_groups].min()),
                    float(maxs[f][valid_groups].max()),
                )
    return Partials(
        group_tags=group_tags,
        codes=codes,
        group_values=group_values,
        count=count[nz],
        sums={f: sums[f][nz] for f in spec.fields},
        mins={f: mins[f][nz] for f in spec.fields},
        maxs={f: maxs[f][nz] for f in spec.fields},
        hist=hist[nz] if hist is not None else None,
        hist_lo=hist_lo,
        hist_span=hist_span,
        field_stats=field_stats,
        rep_key=rep_key,
        rep_desc=rep_desc,
        rep_vals=rep_vals,
    )


def _host_float_partials(
    measure,
    request,
    chunks: dict,
    conds,
    expr,
    pred_vals: dict,
    spec: PlanSpec,
    group_values: dict,
    rep_tags: tuple,
    rep_desc: bool,
    want_rep: bool,
    gd: GlobalDicts,
    dict_state,
) -> Partials:
    """Exact-f64 reduction over the gathered columns (float agg fields).

    Mirrors the device kernel's semantics — same predicate LUT/code
    masks, same mixed-radix group keys, same scan-order representative —
    with numpy f64 arithmetic so float goldens compare exactly."""
    n = chunks["ts"].shape[0]
    G = spec.num_groups

    def pred_mask(i: int) -> np.ndarray:
        p = spec.preds[i]
        col = chunks["tags_code"][p.name]
        v = np.asarray(pred_vals[f"p{i}"])
        if p.kind == "lut":
            m = len(v)
            ok = (col >= 0) & (col < m)
            return np.where(ok, v[np.clip(col, 0, m - 1)], False)
        if p.op in ("in", "not_in"):
            m = np.isin(col, v)
            return ~m if p.op == "not_in" else m
        return (col == v) if p.op == "eq" else (col != v)

    def eval_expr(node) -> np.ndarray:
        if node[0] == "p":
            return pred_mask(node[1])
        left, right = eval_expr(node[1]), eval_expr(node[2])
        return (left & right) if node[0] == "and" else (left | right)

    if spec.expr:
        mask = eval_expr(spec.expr)
    else:
        mask = np.ones(n, dtype=bool)
        for i in range(len(spec.preds)):
            mask &= pred_mask(i)

    if spec.group_tags:
        key = np.zeros(n, dtype=np.int64)
        for t, r in zip(spec.group_tags, spec.radices):
            key = key * r + chunks["tags_code"][t].astype(np.int64)
    else:
        key = np.zeros(n, dtype=np.int64)

    sel = np.nonzero(mask)[0]
    k = key[sel]
    count = np.bincount(k, minlength=G).astype(np.float64)
    sums = {}
    mins = {}
    maxs = {}
    for f in spec.fields:
        vals = chunks["fields"][f][sel].astype(np.float64)
        sums[f] = np.bincount(k, weights=vals, minlength=G)
        mn = np.full(G, np.inf, dtype=np.float64)
        mx = np.full(G, -np.inf, dtype=np.float64)
        np.minimum.at(mn, k, vals)
        np.maximum.at(mx, k, vals)
        mins[f] = mn
        maxs[f] = mx

    rep_ts_acc = rep_row_acc = None
    if want_rep:
        # sentinels ALWAYS initialized when rep is on — a zero-match
        # node must still ship rep arrays or combine_partials would
        # drop rep for the whole cluster result
        sentinel = -(2**62) if rep_desc else 2**62
        rep_ts_acc = np.full(G, sentinel, dtype=np.int64)
        rep_row_acc = np.full(G, sentinel, dtype=np.int64)
        if sel.size:
            ts_sel = chunks["ts"][sel]
            order = (
                np.lexsort((-sel, -ts_sel))
                if rep_desc
                else np.lexsort((sel, ts_sel))
            )
            uk, first = np.unique(k[order], return_index=True)
            rep_ts_acc[uk] = ts_sel[order][first]
            rep_row_acc[uk] = sel[order][first]

    if spec.group_tags:
        nz = np.nonzero(count > 0)[0]
        codes = (
            np.stack(np.unravel_index(nz, spec.radices), axis=1).astype(np.int32)
            if len(nz)
            else np.zeros((0, len(spec.group_tags)), np.int32)
        )
    else:
        nz = np.asarray([0])
        codes = np.zeros((1, 0), np.int32)
    rep_key = None
    if rep_ts_acc is not None:
        rep_key = np.stack([rep_ts_acc[nz], rep_row_acc[nz]], axis=1)
    rep_vals = None
    if rep_tags and rep_key is not None and len(nz):
        rows = np.clip(rep_key[:, 1], 0, max(n - 1, 0))
        import contextlib as _cl

        with dict_state.lock if dict_state is not None else _cl.nullcontext():
            rep_vals = {}
            for t in rep_tags:
                varr = np.asarray(gd.values(t), dtype=object)
                rep_vals[t] = varr[chunks["tags_code"][t][rows]].tolist()
    elif rep_tags:
        rep_vals = {t: [] for t in rep_tags}

    field_stats = {}
    nonempty = count > 0
    if nonempty.any():
        for f in spec.fields:
            field_stats[f] = (
                float(mins[f][nonempty].min()),
                float(maxs[f][nonempty].max()),
            )
    return Partials(
        group_tags=spec.group_tags,
        codes=codes,
        group_values=group_values,
        count=count[nz],
        sums={f: sums[f][nz] for f in spec.fields},
        mins={f: mins[f][nz] for f in spec.fields},
        maxs={f: maxs[f][nz] for f in spec.fields},
        hist=None,
        field_stats=field_stats,
        rep_key=rep_key,
        rep_desc=rep_desc,
        rep_vals=rep_vals,
    )


def _source_lut(
    src: ColumnData, tag: str, gd: GlobalDicts, dict_state: Optional[DictState]
) -> np.ndarray:
    """local-code -> global-code LUT, cached by immutable part identity."""
    if dict_state is None:
        return gd.add_source(tag, list(src.dicts.get(tag, [])))
    if src.cache_key is None:
        with dict_state.lock:
            return gd.add_source(tag, list(src.dicts.get(tag, [])))
    # (source identity, tag, dict length): part dicts are immutable, but
    # memtable snapshots reuse one generation id while their dict grows
    # append-only — the length pins WHICH prefix this LUT covers, so a
    # grown dict gets a fresh (longer) LUT instead of a stale short one
    rk = (src.cache_key[1], tag, len(src.dicts.get(tag, ())))
    with dict_state.lock:
        if dict_state.dicts is not gd:
            # state was reset mid-query: codes from the old gd must not
            # enter the new remap cache
            return gd.add_source(tag, list(src.dicts.get(tag, [])))
        lut = dict_state.remaps.get(rk)
        if lut is None:
            lut = gd.add_source(tag, list(src.dicts.get(tag, [])))
            dict_state.remaps[rk] = lut
        return lut


def _gather_rows(
    sources: list[ColumnData],
    tags_code: list[str],
    fields: list[str],
    gd: GlobalDicts,
    begin_millis: int,
    end_millis: int,
    dict_state: Optional[DictState] = None,
    device_decode: bool = False,
) -> dict:
    """Concatenate sources with row-exact time filtering, global-code remap
    and version dedup (block pruning upstream is only block-granular).

    ``device_decode`` (ROADMAP item 3, ``BYDB_DEVICE_DECODE``): the
    gathered snapshot keeps tag columns in the COMPRESSED ship form —
    per-row narrow LOCAL codes (``tags_enc``), the per-source
    local->global LUTs (``tags_lut``) and a per-row source ordinal
    (``src_ord``) — instead of materializing the remapped i32 columns;
    the widen + remap run on device inside the plan kernel
    (ops.decode.decode_chunk).  Fields stay host-f64 (the exact host
    paths need them) but carry a ``fields_narrow`` dtype decision so the
    pad/ship stage can ship exact-int columns at i8/i16."""
    from banyandb_tpu.storage import encoded as enc_mod

    ts_l, series_l, ver_l = [], [], []
    tc_l: dict[str, list] = {t: [] for t in tags_code}
    lut_l: dict[str, list] = {t: [] for t in tags_code}
    ord_l: list = []
    f_l: dict[str, list] = {f: [] for f in fields}
    n_src = 0
    for src in sources:
        if src.ts.size == 0:
            continue
        rng = (src.ts >= begin_millis) & (src.ts < end_millis)
        if not rng.any():
            continue
        nsel = int(rng.sum())
        ts_l.append(src.ts[rng])
        series_l.append(src.series[rng])
        ver_l.append(src.version[rng])
        if device_decode:
            ord_l.append(np.full(nsel, n_src, dtype=enc_mod.SRC_ORD_DTYPE))
        n_src += 1
        for t in tags_code:
            col = src.tags.get(t)
            if col is None:
                # Source predates this tag (schema evolution): its rows all
                # carry the empty value, same convention as merge/raw paths.
                if dict_state is not None:
                    with dict_state.lock:
                        absent = gd.absent_code(t)
                else:
                    absent = gd.absent_code(t)
                if device_decode:
                    # compressed form: a one-entry LUT row and local
                    # code 0 everywhere — the device remap lands the
                    # same global absent code the dense path bakes in
                    tc_l[t].append(np.zeros(nsel, dtype=np.int8))
                    lut_l[t].append(np.asarray([absent], dtype=np.int32))
                else:
                    tc_l[t].append(np.full(nsel, absent, dtype=np.int32))
            else:
                lut = _source_lut(src, t, gd, dict_state)
                codes = col[rng]
                if device_decode:
                    if lut.size:
                        w = enc_mod.code_dtype(lut.size)
                        tc_l[t].append(codes.astype(w, copy=False))
                        lut_l[t].append(lut)
                    else:
                        tc_l[t].append(np.zeros(nsel, dtype=np.int8))
                        lut_l[t].append(np.zeros(1, dtype=np.int32))
                else:
                    tc_l[t].append(
                        lut[codes] if lut.size else np.zeros(nsel, np.int32)
                    )
        for f in fields:
            col = src.fields.get(f)
            if col is None:
                f_l[f].append(np.zeros(nsel, dtype=np.float64))
            else:
                f_l[f].append(col[rng])

    if not ts_l:
        empty = dict(
            ts=np.zeros(0, np.int64),
            series=np.zeros(0, np.int64),
            fields={f: np.zeros(0, np.float64) for f in fields},
        )
        if device_decode:
            empty["tags_enc"] = {t: np.zeros(0, np.int8) for t in tags_code}
            empty["tags_lut"] = {t: () for t in tags_code}
            empty["src_ord"] = np.zeros(0, enc_mod.SRC_ORD_DTYPE)
            empty["fields_narrow"] = {f: np.dtype(np.int8) for f in fields}
        else:
            empty["tags_code"] = {
                t: np.zeros(0, np.int32) for t in tags_code
            }
        return empty

    ts = np.concatenate(ts_l)
    series = np.concatenate(series_l)
    version = np.concatenate(ver_l)
    # Global version dedup: keep the max-version row per (series, ts).
    keep = hostops.dedup_max_version(series, ts, version)

    out = dict(
        ts=ts[keep],
        series=series[keep],
        fields={f: np.concatenate(f_l[f])[keep] for f in fields},
    )
    if device_decode:
        # narrow gather: mixed per-source widths promote to the widest
        # (np.concatenate's int promotion), values untouched
        out["tags_enc"] = {
            t: np.concatenate(tc_l[t])[keep] for t in tags_code
        }
        out["tags_lut"] = {t: tuple(lut_l[t]) for t in tags_code}
        out["src_ord"] = np.concatenate(ord_l)[keep]
        out["fields_narrow"] = {
            f: enc_mod.narrow_int_dtype(out["fields"][f]) for f in fields
        }
    else:
        out["tags_code"] = {
            t: np.concatenate(tc_l[t])[keep] for t in tags_code
        }
    return out


def _host_tag_codes(
    cols: dict, tag: str, rows: Optional[np.ndarray] = None
) -> np.ndarray:
    """Global i32 codes for `tag` from a gathered snapshot, either ship
    form.  The compressed form (device_decode) materializes host-side
    only where the host genuinely needs values — the exact-f64 float
    path and per-group representative rows — via the same
    local->global LUT composition the device kernel applies."""
    if "tags_code" in cols:
        col = cols["tags_code"][tag]
        return col if rows is None else col[rows]
    codes = cols["tags_enc"][tag]
    src_ord = cols["src_ord"]
    if rows is not None:
        codes = codes[rows]
        src_ord = src_ord[rows]
    luts = cols["tags_lut"][tag]
    if not luts:
        return np.zeros(codes.shape[0], dtype=np.int32)
    offs = np.zeros(len(luts), dtype=np.int64)
    np.cumsum([len(lu) for lu in luts[:-1]], out=offs[1:])
    flat = np.concatenate([np.asarray(lu, np.int32) for lu in luts])
    return flat[offs[src_ord] + codes].astype(np.int32)


def _materialize_tag_codes(cols: dict, tags: Sequence[str]) -> dict:
    """Snapshot with dense i32 ``tags_code`` present (host-path input)."""
    if "tags_code" in cols:
        return cols
    out = dict(cols)
    out["tags_code"] = {t: _host_tag_codes(cols, t) for t in tags}
    return out


def _device_chunk(
    cols: dict,
    start: int,
    end: int,
    spec: PlanSpec,
    epoch: int,
    ship_stats: Optional[list] = None,
    lut_cache: Optional[dict] = None,
) -> dict:
    """Pad one row range into the fixed chunk shape, ship to device.

    Compressed-form snapshots (``src_ord`` present, BYDB_DEVICE_DECODE)
    ship tag columns at their narrow local width plus the small [S, L]
    remap LUTs, and exact-int fields at i8/i16 — the device decode
    stage (ops.decode.decode_chunk, fused into the plan kernel) widens
    them back; PCIe traffic shrinks by the width ratio.
    ``ship_stats`` (list, GIL-atomic appends from the prefetch worker)
    collects (shipped_bytes, dense_bytes) per chunk for the decode span
    and the ``decode_ship_bytes_total`` counters — dense is what the
    decoded i32/f32 form would have shipped for the same columns.
    """
    n = end - start
    nb = spec.nrows
    compressed = "src_ord" in cols

    def pad(a: np.ndarray, dtype):
        out = np.zeros((nb,), dtype=dtype)
        out[:n] = a[start:end]
        return jnp.asarray(out)

    valid = np.zeros((nb,), dtype=bool)
    valid[:n] = True
    # ts offsets relative to the global-min epoch keep int32 exact; range
    # masks are applied on absolute millis host-side during block pruning,
    # so the residual in-chunk mask only needs relative comparisons.
    ts_off = cols["ts"][start:end] - epoch
    ts = np.zeros((nb,), dtype=np.int64)
    ts[:n] = ts_off
    chunk = {
        "ts": jnp.asarray(ts.astype(np.int32)),
        "series": pad(cols["series"] % (2**31), np.int32),
        "valid": jnp.asarray(valid),
    }
    shipped = dense = 0
    if compressed:
        from banyandb_tpu.storage import encoded as enc_mod

        if spec.tags_code:
            chunk["tags_enc"] = {
                t: pad(cols["tags_enc"][t], cols["tags_enc"][t].dtype)
                for t in spec.tags_code
            }
            # the [S, L] remap LUTs are per part-batch, not per chunk:
            # pack + ship once and share the device buffer across the
            # staged loop's chunks (lut_cache lives for one reduction;
            # the single prefetch worker builds chunks sequentially)
            luts = {}
            for t in spec.tags_code:
                dev = None if lut_cache is None else lut_cache.get(t)
                if dev is None:
                    dev = jnp.asarray(enc_mod.pack_luts(cols["tags_lut"][t]))
                    if lut_cache is not None:
                        lut_cache[t] = dev
                    shipped += dev.nbytes
                luts[t] = dev
            chunk["tags_lut"] = luts
            chunk["src_ord"] = pad(cols["src_ord"], enc_mod.SRC_ORD_DTYPE)
            shipped += chunk["src_ord"].nbytes
            for t in spec.tags_code:
                shipped += chunk["tags_enc"][t].nbytes
                dense += nb * 4
        fields_enc = {}
        fields_f32 = {}
        for f in spec.fields:
            ndt = cols["fields_narrow"].get(f)
            if ndt is not None:
                fields_enc[f] = pad(cols["fields"][f], ndt)
                shipped += fields_enc[f].nbytes
            else:
                fields_f32[f] = pad(cols["fields"][f], np.float32)
                shipped += fields_f32[f].nbytes
            dense += nb * 4
        if fields_enc:
            chunk["fields_enc"] = fields_enc
        chunk["fields"] = fields_f32
    else:
        chunk["tags_code"] = {
            t: pad(cols["tags_code"][t], np.int32) for t in spec.tags_code
        }
        chunk["fields"] = {
            f: pad(cols["fields"][f], np.float32) for f in spec.fields
        }
        dense = (len(spec.tags_code) + len(spec.fields)) * nb * 4
        shipped = dense
    # always present: the device-chunk cache is keyed by (gather, shape,
    # columns) and shared across plan variants — a chunk built for a
    # rep-less plan must still serve a rep-tracking one
    row = np.zeros((nb,), dtype=np.int32)
    row[:n] = np.arange(start, end, dtype=np.int32)
    chunk["row"] = jnp.asarray(row)
    if ship_stats is not None:
        ship_stats.append((shipped, dense))
    return chunk


def combine_partials(partials: list[Partials]) -> Partials:
    """The 'reduce' phase: merge node partials by group tuple.

    Vectorized (VERDICT r1 weak #4): the only per-group Python work is
    the group-tuple -> union-index dict build (one dict op per incoming
    group); all numeric accumulation is ufunc scatter (np.add.at /
    minimum.at / maximum.at) over whole arrays — at 100k groups this is
    C-speed instead of 5+ Python float ops per group per field per node.

    Histograms only combine when every contributing partial used the same
    (hist_lo, hist_span) — the distributed two-pass guarantees this.
    """
    base = partials[0]
    want_hist = base.hist is not None
    want_rep = all(p.rep_key is not None for p in partials)
    rep_desc = base.rep_desc
    rep_tags = (
        sorted(base.rep_vals.keys())
        if all(p.rep_vals is not None for p in partials)
        else None
    )
    fields = sorted(base.sums.keys())

    index: dict[tuple, int] = {}
    maps: list[np.ndarray] = []
    for p in partials:
        if want_hist and (p.hist_lo != base.hist_lo or p.hist_span != base.hist_span):
            raise ValueError("histogram partials with mismatched ranges")
        idx = np.empty(len(p.groups), dtype=np.int64)
        for k, g in enumerate(p.groups):
            i = index.get(g)
            if i is None:
                i = index[g] = len(index)
            idx[k] = i
        maps.append(idx)

    K = len(index)
    count = np.zeros(K, dtype=np.float64)
    sums = {f: np.zeros(K, dtype=np.float64) for f in fields}
    mins = {f: np.full(K, np.inf, dtype=np.float64) for f in fields}
    maxs = {f: np.full(K, -np.inf, dtype=np.float64) for f in fields}
    hist = (
        np.zeros((K, _NUM_HIST_BUCKETS), dtype=np.float64)
        if want_hist
        else None
    )
    field_stats: dict[str, tuple[float, float]] = {}
    rep_key = (
        np.full((K, 2), -(2**62) if rep_desc else 2**62, dtype=np.int64)
        if want_rep
        else None
    )
    rep_vals = (
        {t: [None] * K for t in rep_tags} if rep_tags is not None else None
    )

    for p, idx in zip(partials, maps):
        np.add.at(count, idx, p.count)
        for f in fields:
            np.add.at(sums[f], idx, p.sums[f])
            np.minimum.at(mins[f], idx, p.mins[f])
            np.maximum.at(maxs[f], idx, p.maxs[f])
        if want_hist and p.hist is not None:
            np.add.at(hist, idx, p.hist)
        if rep_key is not None and p.rep_key is not None:
            # the scan-order winner's representative values follow its key
            for k, i in enumerate(idx.tolist()):
                pk = (int(p.rep_key[k, 0]), int(p.rep_key[k, 1]))
                cur = (int(rep_key[i, 0]), int(rep_key[i, 1]))
                better = pk > cur if rep_desc else pk < cur
                if better:
                    rep_key[i] = pk
                    if rep_vals is not None:
                        for t in rep_tags:
                            rep_vals[t][i] = p.rep_vals[t][k]
        for f, (lo, hi) in p.field_stats.items():
            old = field_stats.get(f)
            field_stats[f] = (
                min(lo, old[0]) if old else lo,
                max(hi, old[1]) if old else hi,
            )

    return Partials(
        group_tags=base.group_tags,
        groups=list(index.keys()),
        count=count,
        sums=sums,
        mins=mins,
        maxs=maxs,
        hist=hist,
        hist_lo=base.hist_lo,
        hist_span=base.hist_span,
        field_stats=field_stats,
        rep_key=rep_key,
        rep_desc=rep_desc,
        rep_vals=rep_vals,
    )


def finalize_partials(
    measure: Measure,
    request: QueryRequest,
    partials: list[Partials],
    dict_state: Optional[DictState] = None,
    span=None,
) -> QueryResult:
    """Combine + select + decode: the liaison-side tail of the query.

    `dict_state` (standalone fast path only) caches the per-tag rank LUTs
    that vectorize canonical group ordering."""
    import time as _time

    t_merge0 = _time.perf_counter()
    mspan = span.child("merge") if span is not None else None
    try:
        return _finalize_partials_inner(
            measure, request, partials, dict_state, mspan
        )
    finally:
        _H_MERGE.observe((_time.perf_counter() - t_merge0) * 1000)
        if mspan is not None:
            mspan.tag("partials", len(partials)).finish()


def _finalize_partials_inner(
    measure: Measure,
    request: QueryRequest,
    partials: list[Partials],
    dict_state: Optional[DictState],
    mspan,
) -> QueryResult:
    p = combine_partials(partials) if len(partials) != 1 else partials[0]
    if mspan is not None:
        mspan.tag("groups", len(p.count) if p.count is not None else 0)
    agg = request.agg
    group_tags = p.group_tags
    count = p.count
    nonempty = count > 0

    def agg_values(fn: str, field: str) -> np.ndarray:
        if fn == "count":
            return count
        if fn == "sum":
            return p.sums[field]
        if fn == "mean":
            return p.sums[field] / np.maximum(count, 1)
        if fn == "min":
            return p.mins[field]
        if fn == "max":
            return p.maxs[field]
        raise ValueError(f"unknown aggregate {fn}")

    result = QueryResult()
    if not group_tags:
        # One logical group, reported even when empty (global count == 0).
        group_ids = np.asarray([0]) if len(p.groups) else np.zeros(0, int)
        if not len(p.groups):
            p.groups = [()]
            count = np.zeros(1, dtype=np.float64)
            group_ids = np.asarray([0])
    else:
        # Canonical lexicographic order for group lists.  The dense
        # group-id layout is topology-dependent (dict-code order
        # standalone vs combine order in the cluster), so positional
        # order would (a) keep different groups per topology once LIMIT
        # truncates and (b) break prefix-stability between pages issued
        # with different limits.  A total order fixes both.  Top-N
        # queries skip it outright — selection below rebuilds group_ids
        # from the ranking metric.  The standalone codes path orders via
        # per-tag rank LUTs + np.lexsort (identical bytes order, no
        # O(G log G) Python compares); combined tuple partials keep the
        # Python key sort (the distributed combine plane's group count
        # crossed the wire already).
        group_ids = np.nonzero(nonempty)[0]
        if request.top:
            pass  # order irrelevant: Top-N selection replaces group_ids
        elif p.rep_key is not None and group_ids.size:
            # First-appearance scan order (the reference's groupLst:
            # groups emit in the order their first row appears in the
            # ts-asc — or ts-desc under ORDER BY time DESC — scan, i.e.
            # by per-group min/max (ts, row) key).
            k = p.rep_key[group_ids]
            if p.rep_desc:
                order = np.lexsort((-k[:, 1], -k[:, 0]))
            else:
                order = np.lexsort((k[:, 1], k[:, 0]))
            group_ids = group_ids[order]
        elif p.codes is not None and group_ids.size:
            keys = []
            for i, t in enumerate(group_tags):
                vals = p.group_values[t]
                lut = (
                    dict_state.rank_lut(t, vals)
                    if dict_state is not None
                    else _build_rank_lut(vals)
                )
                keys.append(lut[p.codes[group_ids, i]])
            group_ids = group_ids[np.lexsort(tuple(reversed(keys)))]
        else:
            group_ids = np.asarray(
                sorted(group_ids.tolist(), key=lambda i: p.groups[i]),
                dtype=int,
            )

    # Top-N selection narrows the group id set.  Ranking field is
    # top.field_name; the ranking function is the request's aggregate when
    # it composes (sum/count/min/max/mean), else mean (percentile ranks
    # don't compose across groups — reference TopN is mean-of-field too).
    if request.top:
        fn = (
            agg.function
            if agg and agg.function != "percentile" and agg.field_name == request.top.field_name
            else "mean"
        )
        metric = agg_values(fn, request.top.field_name)
        k = min(request.top.number, int(nonempty.sum()))
        if k <= 0 or metric.size == 0:
            group_ids = np.zeros(0, dtype=int)
        else:
            asc = request.top.field_value_sort == "asc"
            metric = np.where(nonempty, metric, np.inf if asc else -np.inf)
            order = np.argsort(metric if asc else -metric, kind="stable")[:k]
            # Only the k-th-value boundary ties decide MEMBERSHIP of the
            # top set; resolve exactly those by group key so selection is
            # replay-identical across topologies without paying a Python
            # sort over all G groups (vectorized argsort does the bulk).
            kth_val = metric[order[k - 1]]
            head = [int(i) for i in order if metric[i] != kth_val]
            tied = sorted(
                (
                    int(i)
                    for i in np.nonzero((metric == kth_val) & nonempty)[0]
                ),
                key=p.group_key,
            )
            group_ids = np.asarray(head + tied[: k - len(head)], dtype=int)

    # offset/limit paging over the (possibly top-N-ranked) group list —
    # offset semantics match the reference's QueryRequest.offset
    off = request.offset or 0
    if off:
        group_ids = group_ids[off:]
    group_ids = group_ids[: request.limit] if request.limit else group_ids

    # Decode group tuples (bytes) to client values via the schema types.
    from banyandb_tpu.query import filter as qfilter

    for g in group_ids:
        raw = p.group_key(int(g))
        result.groups.append(
            tuple(
                qfilter.decode_tag_value(v, measure.tag(t).type)
                for t, v in zip(group_tags, raw)
            )
        )
    if p.rep_vals:
        # representative (first-scanned row) values for projected-but-
        # not-grouped tags, aligned with result.groups
        for t, vals in p.rep_vals.items():
            result.rep_tags[t] = [
                (
                    qfilter.decode_tag_value(vals[int(g)], measure.tag(t).type)
                    if vals[int(g)] is not None
                    else None
                )
                for g in group_ids
            ]

    if agg:
        if agg.function == "percentile":
            qs = list(agg.quantiles or (0.5,))
            result.values[f"percentile({agg.field_name})"] = _invert_histogram(
                p.hist, group_ids, qs, p.hist_lo, p.hist_span
            )
        else:
            v = agg_values(agg.function, agg.field_name)[group_ids]
            result.values[f"{agg.function}({agg.field_name})"] = v.tolist()
    result.values["count"] = count[group_ids].tolist()
    return result


def _invert_histogram(
    hist: Optional[np.ndarray],
    group_ids: np.ndarray,
    qs: list[float],
    lo: float,
    span: float,
) -> list[list[float]]:
    """Vectorized CDF inversion over all selected groups at once — the
    same interpolation the device kernel uses
    (ops/percentile.py group_percentile_histogram), on [G, B] arrays
    instead of a per-group per-quantile Python loop."""
    width = span / _NUM_HIST_BUCKETS
    ids = np.asarray(group_ids, dtype=np.int64)
    if ids.size == 0:
        return []
    if hist is None:
        return [[lo] * len(qs) for _ in range(ids.size)]
    valid = ids < len(hist)
    counts = np.zeros((ids.size, hist.shape[1]), dtype=np.float64)
    counts[valid] = hist[ids[valid]]
    cdf = np.cumsum(counts, axis=1)  # [G, B]
    total = cdf[:, -1:]  # [G, 1]
    q = np.asarray(qs, dtype=np.float64)[None, :]  # [1, Q]
    target = np.clip(np.ceil(q * total), 1.0, np.maximum(total, 1.0))
    hit = np.argmax(cdf[:, None, :] >= target[:, :, None], axis=2)  # [G, Q]
    cdf_at = np.take_along_axis(cdf, hit, axis=1)
    cnt_at = np.take_along_axis(counts, hit, axis=1)
    prev = cdf_at - cnt_at
    frac = np.where(cnt_at > 0, (target - prev) / np.maximum(cnt_at, 1.0), 0.0)
    est = lo + (hit + np.clip(frac, 0.0, 1.0)) * width
    est = np.where(total > 0, est, lo)
    return est.tolist()
