"""Continuous streaming aggregation: materialized rolling windows at ingest.

The Enthuse-style (PAPERS.md, arXiv 2405.18168) ingest-side twin of the
fused whole-plan executor: instead of making every dashboard query
rescan parts, the signatures dashboards re-ask — exactly the PlanSpec
population the precompile registry enumerates — are *registered* here,
and each registration maintains rolling pre-aggregated windows
(count / per-field sum / min / max in exact f64 host accumulators,
keyed by the signature's tag tuple, per shard, per tumbling window
aligned to the segment clock) updated **at ingest**:

- standalone / data-node direct writes feed windows from
  ``MeasureEngine.write`` / ``write_columns`` (the same hook point as
  TopN pre-aggregation, which keeps its own window machinery in
  ``models/topn.py`` — TopN heaps stay materialized there);
- parts drained from the liaison write queue feed windows when the data
  node installs them (``cluster/data_node.py``) — the install-digest
  idempotence means an ack-lost re-ship never double-counts;
- registration (and registry reload after a restart) *backfills* from a
  parts+memtable snapshot, deduplicated by ``(series, ts)`` max version
  against any batches that raced the snapshot, so windows are rebuilt
  deterministically from part replay.

The measure planner rewrite (``MeasureEngine.query`` /
``query_partials``) answers a covered query by FOLDING window states
into a ``measure_exec.Partials`` — partial head/tail windows fall back
to a *bounded rescan of only the uncovered range* and combine through
the ordinary ``combine_partials``/``finalize_partials`` machinery, so
materialized windows merge across shards and across cluster nodes
exactly like scan partials do.  ``BYDB_STREAMAGG=0`` (A/B flag, default
on) restores the full rescan live.

Exactness contract (docs/performance.md "Continuous streaming
aggregation"): count/min/max fold exactly; sums accumulate in f64, so
the fold is byte-identical to the rescan whenever per-group sums are
exactly representable (integer-valued fields below 2^53 — the dashboard
metric shape; arbitrary-real sums may differ in the last ulp because
f64 addition is order-sensitive).  Windows assume append-only ingest:
a same-(series, ts) version REWRITE inside the horizon is the one
workload shape that diverges from the deduplicating rescan — register
signatures only on append-only measures.

Everything here is host-side numpy — the ingest update path dispatches
ZERO device kernels by design (the documented host-only kernel-budget
exemption, docs/linting.md), so the write path's dispatch budget cannot
creep through this module.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

import numpy as np

from banyandb_tpu.api.model import QueryRequest, TimeRange
from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.utils import fs, hostops
from banyandb_tpu.utils.envflag import env_flag, env_int

log = logging.getLogger("banyandb.streamagg")

# the streamagg stage rides the same instrument scheme as gather /
# device_execute / merge: bench + load artifacts pick it up via
# obs/prom.stage_breakdown with no extra wiring
_H_STREAMAGG = obs_metrics.stage_histogram("streamagg")

_NEG_INF_TS = -(2**62)
_POS_INF_TS = 2**62


def _now_ms() -> int:
    import time as _time

    return int(_time.time() * 1000)


def enabled() -> bool:
    """The read-path A/B flag.  Ingest-side window maintenance always
    runs for registered signatures (a live flag flip must not leave
    gaps); the flag gates whether queries are ANSWERED from windows."""
    return env_flag("BYDB_STREAMAGG", default=True)


def default_window_ms() -> int:
    return env_int("BYDB_STREAMAGG_WINDOW_MS", 60_000)


def default_max_windows() -> int:
    return env_int("BYDB_STREAMAGG_MAX_WINDOWS", 4096)


@dataclass(frozen=True)
class SigSpec:
    """One materialized plan signature: the (group, measure) plus the
    tag tuple its window states are keyed by and the fields they
    accumulate.  A query is covered when its group-by tags AND its
    predicate tags are a subset of ``key_tags`` (the fold projects /
    filters over state keys) and its aggregate/top fields are a subset
    of ``fields``."""

    group: str
    measure: str
    key_tags: tuple[str, ...]  # sorted
    fields: tuple[str, ...]  # sorted
    window_millis: int

    def label(self) -> str:
        return (
            f"{self.group}/{self.measure}"
            f"[{','.join(self.key_tags)}]@{self.window_millis}ms"
        )


# acc layout inside one window state (per interned key id):
# [count, min_ts, max_ts, seq_first, seq_last, (sum, min, max) per field]
_ACC_FIXED = 5


class _Sig:
    """Mutable window state for one registered signature.  All fields
    are owned by the registry's lock; no method of this class exists —
    mutation happens only inside StreamAggRegistry under ``_lock``.

    Key tuples are INTERNED once per signature (``key_index`` /
    ``keys_rev``, append-only like measure_exec.GlobalDicts): window
    states key on the dense int id, closed windows freeze into numpy
    ``snapshots`` ([K] ids + [K, C] acc matrix, invalidated on touch),
    and predicate / group-projection evaluation caches per-id LUTs —
    which is what makes the fold a handful of ufunc reductions instead
    of per-state Python (the ops.groupby shape, host-side)."""

    __slots__ = (
        "spec", "windows", "covered_from", "watermark", "building",
        "pending", "max_windows", "rows", "late", "evicted",
        "key_index", "keys_rev", "snapshots", "cond_luts", "proj_luts",
        "backfill_parts", "origin", "hits", "last_hit_ms",
    )

    def __init__(self, spec: SigSpec, max_windows: int):
        self.spec = spec
        # window_start -> shard -> {key id -> acc list}
        self.windows: dict[int, dict[int, dict[int, list]]] = {}
        # every acked row with ts >= covered_from has been applied; the
        # fold may answer any window-aligned range at/after it
        self.covered_from = _POS_INF_TS  # until backfill completes
        self.watermark = _NEG_INF_TS  # max event ts applied
        self.building = True  # backfill in flight: buffer, don't serve
        self.pending: list[tuple] = []  # batches raced during backfill
        self.max_windows = max_windows
        self.rows = 0
        self.late = 0
        self.evicted = 0
        # key interning + fold caches (all append-only / invalidate-on-
        # touch, rebuilt lazily)
        self.key_index: dict[tuple, int] = {}
        self.keys_rev: list[tuple] = []
        self.snapshots: dict[tuple, tuple] = {}  # (w, shard) -> (ids, mat)
        self.cond_luts: dict[tuple, np.ndarray] = {}  # (op, val) -> bool[n]
        # group_tags -> (proj_index, proj_rev, id->gid int64 LUT)
        self.proj_luts: dict[tuple, tuple] = {}
        # part identities the registration backfill consumed: a part
        # introduced before the source snapshot whose install hook only
        # fires AFTER building flips off must not apply twice
        self.backfill_parts: frozenset = frozenset()
        # provenance + serve-hit stats (the autoreg eviction evidence:
        # least-recently-HIT auto signatures evict first, manual
        # registrations are never auto-evicted)
        self.origin = "manual"
        self.hits = 0
        self.last_hit_ms = 0


@dataclass
class Cover:
    """A resolved coverage plan for one query (``plan_cover`` output)."""

    sig: _Sig
    group_tags: tuple[str, ...]
    fields: tuple[str, ...]  # sorted, mirrors compute_partials' set
    conds: list  # [(key_index, op, value bytes | frozenset[bytes])]
    want_minmax: bool
    want_rep: bool
    rep_desc: bool
    cov_lo: int  # folded window range [cov_lo, cov_hi)
    cov_hi: int
    head: Optional[tuple[int, int]]  # uncovered [begin, cov_lo)
    tail: Optional[tuple[int, int]]  # uncovered [cov_hi, end)

    @property
    def kind(self) -> str:
        return "partial" if (self.head or self.tail) else "covered"


_COVERED_OPS = ("eq", "ne", "in", "not_in")


class CoverageLost(Exception):
    """Raised by the fold when the planned window range was evicted (or
    reset) between plan_cover and the fold's locked read — the caller
    falls back to the full rescan instead of answering with a gap."""


def coldata_tag_col(src, tag: str, n: int) -> np.ndarray:
    """Canonical per-row tag bytes from a ColumnData source (absent
    column = the empty value, same convention as merge/gather)."""
    codes = src.tags.get(tag)
    if codes is None:
        return np.full(n, b"", dtype=object)
    return np.asarray(src.dicts[tag], dtype=object)[np.asarray(codes)]


def coldata_field_col(src, field: str, n: int) -> np.ndarray:
    """f64 field column from a ColumnData source (absent = zeros)."""
    col = src.fields.get(field)
    if col is None:
        return np.zeros(n, dtype=np.float64)
    return np.asarray(col, dtype=np.float64)


class StreamAggRegistry:
    """Per-MeasureEngine registry of materialized signatures.

    Lock discipline: ``_lock`` is a LEAF lock — nothing else is ever
    acquired while holding it (backfill gathers its source snapshot
    before taking it; the fold is pure dict work), so it can never
    participate in a lock-order cycle with the storage/engine lock
    families.  ``_active`` / ``_by_measure`` are immutable snapshots
    rebound under the lock and read lock-free on the ingest hot path
    (the Liaison.alive idiom)."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self._sigs: dict[SigSpec, _Sig] = {}
        # ingest drain gate: write paths ticket in before appending to
        # the memtable and out after their observe() — register() waits
        # for pre-snapshot writers to drain before leaving `building`,
        # so a write in flight across the whole backfill cannot re-apply
        # rows the snapshot already consumed (see register())
        self._ingest_enter = 0
        self._ingest_exit = 0
        # lock-free fast-path snapshots (rebound, never mutated)
        self._active: frozenset = frozenset()  # {(group, measure)}
        self._by_measure: dict[tuple, tuple] = {}  # (g, m) -> (_Sig, ...)
        self._needs: dict[tuple, tuple] = {}  # (g, m) -> (tags, fields)
        self._seq = 0
        self._store = Path(engine.root) / "streamagg-registry.json"
        self._meter = obs_metrics.global_meter()
        # BYDB_STREAMAGG_AUTOLOAD=0 defers the persisted-registry reload
        # to an explicit load_persisted() call.  Shard-owning worker
        # processes (cluster/workers.py) boot with it off: the parent
        # replays its write journal into the fresh memtable FIRST, then
        # triggers the load — so the registration backfill's
        # (series, ts, version) dedup sees replayed rows and parts in
        # ONE snapshot instead of double-folding rows that are in both.
        from banyandb_tpu.utils.envflag import env_flag

        if env_flag("BYDB_STREAMAGG_AUTOLOAD", True):
            self._load()

    # -- registration / persistence -----------------------------------------
    def active(self, group: str, measure: str) -> bool:
        return (group, measure) in self._active

    def ingest_enter(self) -> None:
        """Write-path ticket (taken BEFORE the memtable append, released
        after observe()): lets register() drain in-flight writers before
        it stops buffering — see register()."""
        with self._lock:
            self._ingest_enter += 1

    def ingest_exit(self) -> None:
        with self._lock:
            self._ingest_exit += 1

    def _drain_ingest(self, timeout_s: float = 10.0) -> None:
        """Wait until every writer ticketed in before NOW has exited.
        Writers entering later observe into `pending` (the signature
        already exists), so they need no wait."""
        import time as _time

        with self._lock:
            target = self._ingest_enter
        end = _time.monotonic() + timeout_s
        while _time.monotonic() < end:
            with self._lock:
                if self._ingest_exit >= target:
                    return
            _time.sleep(0.005)
        log.warning(
            "streamagg: ingest drain timed out before backfill apply "
            "(a wedged writer may double-apply pre-snapshot rows)"
        )

    def needs(self, group: str, measure: str) -> Optional[tuple]:
        """(key tag union, field union) across this measure's signatures,
        or None — the data node's install hook checks this before paying
        a part read."""
        return self._needs.get((group, measure))

    def register(
        self,
        group: str,
        measure: str,
        key_tags,
        fields,
        window_millis: Optional[int] = None,
        max_windows: Optional[int] = None,
        origin: str = "manual",
    ) -> dict:
        """Register (idempotent) one materialized signature and backfill
        its windows from the engine's current parts + memtables.

        Backfill is linearizable with concurrent ingest: the signature
        is installed (``building``) BEFORE the source snapshot is taken,
        racing ingest batches buffer into ``pending``, and the final
        apply deduplicates snapshot+pending rows by (series, ts) max
        version — a row seen by both counts once, a row seen by neither
        cannot exist (it either landed before the snapshot or after the
        signature was installed)."""
        m = self.engine.registry.get_measure(group, measure)
        if m.index_mode:
            raise ValueError(
                f"streamagg: index-mode measure {group}/{measure} has no "
                "scan path to materialize"
            )
        tag_names = {t.name for t in m.tags}
        key_tags = tuple(sorted(dict.fromkeys(key_tags)))
        for t in key_tags:
            if t not in tag_names:
                raise KeyError(f"streamagg: unknown tag {t!r} on {measure}")
        from banyandb_tpu.api.schema import FieldType as _FT

        numeric = {
            f.name
            for f in m.fields
            if f.type not in (_FT.STRING, _FT.DATA_BINARY)
        }
        fields = tuple(sorted(dict.fromkeys(fields)))
        for f in fields:
            if f not in numeric:
                raise KeyError(
                    f"streamagg: {f!r} is not a numeric field of {measure}"
                )
        opts = self.engine.registry.get_group(group).resource_opts
        w = int(window_millis or default_window_ms())
        if w <= 0 or opts.segment_interval.millis % w != 0:
            # window rotation must align to the segment clock: a window
            # spanning a segment boundary would fold rows a segment-
            # pruned rescan could not see
            raise ValueError(
                f"streamagg: window {w}ms must divide the segment "
                f"interval ({opts.segment_interval.millis}ms)"
            )
        spec = SigSpec(group, measure, key_tags, fields, w)
        sig = _Sig(spec, int(max_windows or default_max_windows()))
        sig.origin = origin if origin in ("manual", "auto") else "manual"
        # registration grace stamp: the autoreg LRU evictor compares a
        # candidate's evidence time against this — a just-registered
        # signature must not be displaced by the NEXT candidate of the
        # same mining cycle before it ever had a chance to serve
        sig.last_hit_ms = _now_ms()
        existing_out = None
        promoted = False
        with self._lock:
            existing = self._sigs.get(spec)
            if existing is not None:
                if origin == "manual" and existing.origin == "auto":
                    # an operator re-registering an auto signature
                    # PROMOTES it: manual registrations never auto-evict
                    existing.origin = "manual"
                    promoted = True
                existing_out = self._stats_one_locked(existing)
        if existing_out is not None:
            if promoted:
                self._persist()
            return existing_out
        # per-tenant registration cap (docs/robustness.md "Multi-tenant
        # QoS"): a NEW signature must fit its tenant's quota — one
        # tenant registering signatures cannot grow another tenant's
        # node state (generous default: unlimited).  Idempotent
        # re-registration returned above and is never gated.
        from banyandb_tpu.qos.plane import global_qos as _global_qos
        from banyandb_tpu.qos.tenancy import tenant_of_group as _tenant_of

        _tenant = _tenant_of(group)
        with self._lock:
            if spec in self._sigs:  # raced a concurrent register
                return self._stats_one_locked(self._sigs[spec])
            # count + admit + install under ONE critical section, or
            # two concurrent registrations could both squeeze past the
            # cap (the plane's lock nests under this one; nothing takes
            # them in the opposite order)
            _existing_n = sum(
                1 for s in self._sigs if _tenant_of(s.group) == _tenant
            )
            _global_qos().admit_streamagg(group, _existing_n)
            self._sigs[spec] = sig
            self._rebind_snapshots_locked()
        try:
            batches, part_ids = self._backfill_snapshot(spec)
            # writers that began before the snapshot may still be
            # between their memtable append (in the snapshot) and their
            # observe() call — wait them out so those observes land in
            # `pending`, where the (series, ts, version) dedup collapses
            # the overlap, instead of re-applying after building flips
            self._drain_ingest()
        except Exception:
            with self._lock:
                self._sigs.pop(spec, None)
                self._rebind_snapshots_locked()
            raise
        with self._lock:
            batches.extend(sig.pending)
            sig.pending = []
            sig.backfill_parts = frozenset(part_ids)
            # coverage opens BEFORE the apply: backfill rows land in
            # their (pre-horizon) windows instead of dropping as late
            sig.covered_from = _NEG_INF_TS
            self._apply_deduped_locked(sig, batches)
            sig.building = False
            self._evict_locked(sig)
            out = self._stats_one_locked(sig)
        self._persist()
        return out

    def _rebind_snapshots_locked(self) -> None:
        self._active = frozenset(
            (s.group, s.measure) for s in self._sigs
        )
        by: dict[tuple, list] = {}
        needs: dict[tuple, tuple] = {}
        for spec, sig in self._sigs.items():
            key = (spec.group, spec.measure)
            by.setdefault(key, []).append(sig)
            tags, flds = needs.get(key, ((), ()))
            needs[key] = (
                tuple(sorted(set(tags) | set(spec.key_tags))),
                tuple(sorted(set(flds) | set(spec.fields))),
            )
        self._by_measure = {k: tuple(v) for k, v in by.items()}
        self._needs = needs

    def _persist(self) -> None:
        with self._lock:
            doc = {
                "signatures": [
                    {
                        "group": s.group,
                        "measure": s.measure,
                        "key_tags": list(s.key_tags),
                        "fields": list(s.fields),
                        "window_millis": s.window_millis,
                        "origin": sig.origin,
                    }
                    for s, sig in self._sigs.items()
                ]
            }
        try:
            self._store.parent.mkdir(parents=True, exist_ok=True)
            fs.atomic_write_json(self._store, doc)
        except OSError:
            log.exception("streamagg registry persist failed (state kept)")

    def unregister(
        self,
        group: str,
        measure: str,
        key_tags,
        fields,
        window_millis: Optional[int] = None,
    ) -> bool:
        """Drop one materialized signature (the autoreg eviction path;
        also an operator surface via the ``streamagg`` topic).  All
        window state is released; queries it covered fall back to the
        scan path on their next plan_cover.  -> True when a signature
        was actually removed."""
        key_tags = tuple(sorted(dict.fromkeys(key_tags)))
        fields = tuple(sorted(dict.fromkeys(fields)))
        w = int(window_millis or 0)
        with self._lock:
            match = None
            for spec in self._sigs:
                if (
                    spec.group == group
                    and spec.measure == measure
                    and spec.key_tags == key_tags
                    and spec.fields == fields
                    and (w == 0 or spec.window_millis == w)
                ):
                    match = spec
                    break
            if match is None:
                return False
            self._sigs.pop(match)
            self._rebind_snapshots_locked()
        self._persist()
        log.info(
            "streamagg: unregistered %s/%s[%s]",
            group, measure, ",".join(key_tags),
        )
        return True

    def load_persisted(self) -> int:
        """Explicit persisted-registry reload for deferred-autoload
        processes (the worker-restart sequence: replay, THEN load).
        Idempotent — register() returns existing state for known
        signatures.  -> number of persisted records processed."""
        return self._load()

    def _load(self) -> int:
        """Reload persisted registrations (engine restart): each one
        re-registers with a fresh backfill, rebuilding windows
        deterministically from whatever parts survived on disk — the
        wqueue replay then installs (and window-feeds) anything that was
        in flight, and install-digest dedup keeps re-ships single."""
        try:
            if not self._store.exists():
                return 0
            doc = fs.read_json(self._store)
        except (OSError, ValueError):
            return 0
        recs = doc.get("signatures", [])
        for rec in recs:
            try:
                self.register(
                    rec["group"], rec["measure"],
                    key_tags=rec.get("key_tags", ()),
                    fields=rec.get("fields", ()),
                    window_millis=rec.get("window_millis"),
                    origin=rec.get("origin", "manual"),
                )
            except Exception:  # noqa: BLE001 — a stale entry (dropped
                # measure, renamed tag) must not take the engine down
                log.exception("streamagg: stale registration %r skipped", rec)
        return len(recs)

    # -- backfill ------------------------------------------------------------
    def _backfill_snapshot(self, spec: SigSpec) -> tuple[list, set]:
        """(batches, consumed part ids): one batch (ts, series, version,
        shards, keycols, fieldcols) per source the engine currently
        holds — parts and memtables, per shard (windows are shard-keyed
        so distributed folds can honor the scatter's shard subset) —
        plus the part-dir identities the snapshot consumed, so a raced
        install hook for one of THESE parts can be skipped instead of
        applied twice.  Takes NO registry lock: storage locks are
        acquired inside the engine, and the leaf-lock discipline
        forbids nesting them under ours."""
        shard_num = self.engine.registry.get_group(
            spec.group
        ).resource_opts.shard_num
        req = QueryRequest(
            groups=(spec.group,),
            name=spec.measure,
            time_range=TimeRange(0, _POS_INF_TS),
        )
        batches: list[tuple] = []
        part_ids: set = set()
        for shard in range(shard_num):
            sources = self.engine.gather_query_sources(
                req, shard_ids={shard}
            )
            for src in sources or ():
                n = int(src.ts.size)
                if n == 0:
                    continue
                ck = src.cache_key
                if ck and ck[0] == "part_read":
                    part_ids.add(ck[1])  # str(part dir)
                batches.append((
                    np.asarray(src.ts, dtype=np.int64),
                    np.asarray(src.series, dtype=np.int64),
                    np.asarray(src.version, dtype=np.int64),
                    np.full(n, shard, dtype=np.int64),
                    [coldata_tag_col(src, t, n) for t in spec.key_tags],
                    [coldata_field_col(src, f, n) for f in spec.fields],
                ))
        return batches, part_ids

    def _apply_deduped_locked(self, sig: _Sig, batches: list[tuple]) -> None:
        """Concatenate batches, dedup by (series, ts) keeping the max
        version — the rescan's own dedup contract — then apply.  Exact
        duplicates (a part in the snapshot AND its raced install hook)
        collapse to one row here."""
        if not batches:
            return
        ts = np.concatenate([b[0] for b in batches])
        series = np.concatenate([b[1] for b in batches])
        version = np.concatenate([b[2] for b in batches])
        shards = np.concatenate([b[3] for b in batches])
        nk = len(sig.spec.key_tags)
        nf = len(sig.spec.fields)
        keycols = [
            np.concatenate([b[4][i] for b in batches]) for i in range(nk)
        ]
        fcols = [
            np.concatenate([b[5][i] for b in batches]) for i in range(nf)
        ]
        keep = hostops.dedup_max_version(series, ts, version)
        self._apply_locked(
            sig,
            ts[keep],
            shards[keep],
            [c[keep] for c in keycols],
            [c[keep] for c in fcols],
        )

    # -- ingest --------------------------------------------------------------
    def observe(
        self,
        group: str,
        measure: str,
        *,
        ts,
        series,
        versions,
        shards,
        tag_col: Callable[[str], np.ndarray],
        field_col: Callable[[str], np.ndarray],
        part_id: Optional[str] = None,
    ) -> None:
        """Feed one ingest batch through every signature of (group,
        measure).  ``tag_col(tag)`` -> object array of canonical bytes
        per row; ``field_col(field)`` -> f64 array — callables so only
        registered columns pay materialization.  ``shards`` is an int
        array or a scalar shard id.  ``part_id`` (install hooks) names
        the part dir: a part the registration backfill already consumed
        is skipped here — its hook raced past ``building`` — while a
        batch arriving DURING backfill buffers into ``pending``, where
        the (series, ts, version) dedup collapses it."""
        if (group, measure) not in self._active:
            return
        ts = np.asarray(ts, dtype=np.int64)
        n = int(ts.size)
        if n == 0:
            return
        if np.isscalar(shards) or getattr(shards, "ndim", 1) == 0:
            shards = np.full(n, int(shards), dtype=np.int64)
        else:
            shards = np.asarray(shards, dtype=np.int64)
        tag_cache: dict[str, np.ndarray] = {}
        field_cache: dict[str, np.ndarray] = {}

        def _tag(t: str) -> np.ndarray:
            c = tag_cache.get(t)
            if c is None:
                c = tag_cache[t] = np.asarray(tag_col(t), dtype=object)
            return c

        def _field(f: str) -> np.ndarray:
            c = field_cache.get(f)
            if c is None:
                c = field_cache[f] = np.asarray(
                    field_col(f), dtype=np.float64
                )
            return c

        with self._lock:
            for sig in self._by_measure.get((group, measure), ()):
                if (
                    part_id is not None
                    and not sig.building
                    and part_id in sig.backfill_parts
                ):
                    continue  # backfill already folded this part's rows
                keycols = [_tag(t) for t in sig.spec.key_tags]
                fcols = [_field(f) for f in sig.spec.fields]
                if sig.building:
                    sig.pending.append((
                        ts,
                        np.asarray(series, dtype=np.int64),
                        np.asarray(versions, dtype=np.int64)
                        if versions is not None
                        else np.zeros(n, dtype=np.int64),
                        shards,
                        keycols,
                        fcols,
                    ))
                else:
                    self._apply_locked(sig, ts, shards, keycols, fcols)
                    self._evict_locked(sig)

    def _apply_locked(
        self,
        sig: _Sig,
        ts: np.ndarray,
        shards: np.ndarray,
        keycols: list[np.ndarray],
        fcols: list[np.ndarray],
    ) -> None:
        """Vectorized window accumulation: rows collapse to their
        distinct (window, shard, key-tuple) combos via chained
        np.unique factorization, then each combo folds with bincount /
        ufunc-at reductions — per-row Python never runs."""
        n = int(ts.size)
        if n == 0:
            return
        W = sig.spec.window_millis
        win = ts - (ts % W)
        # chained pairing: after each step the code domain re-compacts
        # to <= n, so the int64 pair key never overflows
        _, codes = np.unique(win, return_inverse=True)
        domain = int(codes.max()) + 1 if n else 1
        for col in (shards, *keycols):
            _, c = np.unique(col, return_inverse=True)
            d = int(c.max()) + 1
            pair = codes.astype(np.int64) * d + c
            _, codes = np.unique(pair, return_inverse=True)
            domain = int(codes.max()) + 1
        uniq, first_idx = np.unique(codes, return_index=True)
        k = int(uniq.size)
        counts = np.bincount(codes, minlength=k).astype(np.float64)
        tmin = np.full(k, _POS_INF_TS, dtype=np.int64)
        tmax = np.full(k, _NEG_INF_TS, dtype=np.int64)
        np.minimum.at(tmin, codes, ts)
        np.maximum.at(tmax, codes, ts)
        fsums, fmins, fmaxs = [], [], []
        for col in fcols:
            fsums.append(np.bincount(codes, weights=col, minlength=k))
            mn = np.full(k, np.inf, dtype=np.float64)
            mx = np.full(k, -np.inf, dtype=np.float64)
            np.minimum.at(mn, codes, col)
            np.maximum.at(mx, codes, col)
            fmins.append(mn)
            fmaxs.append(mx)
        self._seq += 1
        batch_seq = self._seq
        applied = 0
        key_index = sig.key_index
        # combos process in FIRST-ROW order (np.unique returns them in
        # sorted-key order): new accs then take their seq in batch
        # arrival order, which is the same tie-break the rescan's row
        # index applies for rows sharing a timestamp — and makes the
        # registration backfill (one batch in gather order) reproduce
        # the rescan's first-appearance order exactly.  Ties across
        # separately-ingested batches/shards remain implementation-
        # defined on BOTH paths (a flush re-sorts part rows by
        # (series, ts), so the rescan itself reorders such ties).
        for j in np.argsort(first_idx, kind="stable").tolist():
            i = int(first_idx[j])
            w = int(win[i])
            if w < sig.covered_from:
                # window already evicted: the fold never reads below
                # covered_from, so applying would only leak memory —
                # the uncovered range falls back to rescan regardless
                sig.late += int(counts[j])
                self._meter.counter_add(
                    "streamagg_late_dropped", float(counts[j])
                )
                continue
            shard = int(shards[i])
            key = tuple(c[i] for c in keycols)
            kid = key_index.get(key)
            if kid is None:
                kid = key_index[key] = len(sig.keys_rev)
                sig.keys_rev.append(key)
            states = sig.windows.setdefault(w, {}).setdefault(shard, {})
            # the frozen fold snapshot of this window-shard is stale now
            sig.snapshots.pop((w, shard), None)
            acc = states.get(kid)
            if acc is None:
                self._seq += 1
                acc = states[kid] = [
                    0.0, _POS_INF_TS, _NEG_INF_TS, self._seq, self._seq,
                ] + [0.0, np.inf, -np.inf] * len(fcols)
            acc[0] += float(counts[j])
            acc[1] = min(acc[1], int(tmin[j]))
            acc[2] = max(acc[2], int(tmax[j]))
            acc[4] = batch_seq
            for fi in range(len(fcols)):
                base = _ACC_FIXED + 3 * fi
                acc[base] += float(fsums[fi][j])
                acc[base + 1] = min(acc[base + 1], float(fmins[fi][j]))
                acc[base + 2] = max(acc[base + 2], float(fmaxs[fi][j]))
            applied += int(counts[j])
        sig.rows += applied
        hw = int(ts.max())
        if hw > sig.watermark:
            sig.watermark = hw
        if applied:
            self._meter.counter_add("streamagg_rows", float(applied))

    def invalidate(
        self,
        group: str,
        measure: str,
        reason: str = "",
        up_to: Optional[int] = None,
    ) -> None:
        """Poison coverage after a failed ingest-side update (e.g. an
        install hook that could not read its part): rows may be missing
        from the windows, so serving them would silently under-count.
        Every window at/below max(watermark, ``up_to``) drops and
        ``covered_from`` jumps past it — queries over the gap fall back
        to rescan, and coverage resumes from the next full window of
        NEW data.  ``up_to`` is the failed data's max event ts (the
        part meta's max_ts — it may lie ABOVE the watermark); None =
        unknown extent, which disables coverage entirely until the
        signature is re-registered."""
        with self._lock:
            for sig in self._by_measure.get((group, measure), ()):
                W = sig.spec.window_millis
                basis = max(
                    sig.watermark,
                    up_to if up_to is not None else _POS_INF_TS,
                )
                horizon = (
                    basis - (basis % W) + 2 * W
                    if _NEG_INF_TS < basis < _POS_INF_TS
                    else _POS_INF_TS
                )
                sig.covered_from = max(sig.covered_from, horizon)
                for w in [x for x in sig.windows if x < sig.covered_from]:
                    dropped = sig.windows.pop(w)
                    for shard in dropped:
                        sig.snapshots.pop((w, shard), None)
                self._meter.counter_add(
                    "streamagg_invalidated", 1.0
                )
        log.warning(
            "streamagg: coverage invalidated for %s/%s (%s); affected "
            "ranges rescan until fresh windows accumulate",
            group, measure, reason,
        )

    def _evict_locked(self, sig: _Sig) -> None:
        """Rolling horizon: past ``max_windows`` the OLDEST window is
        dropped and ``covered_from`` advances past it — queries into the
        evicted range fall back to (head) rescan, never read a gap."""
        while len(sig.windows) > sig.max_windows:
            oldest = min(sig.windows)
            dropped = sig.windows.pop(oldest)
            for shard in dropped:
                sig.snapshots.pop((oldest, shard), None)
            sig.evicted += sum(len(s) for s in dropped.values())
            sig.covered_from = max(
                sig.covered_from, oldest + sig.spec.window_millis
            )
            self._meter.counter_add("streamagg_windows_evicted", 1.0)
        if len(sig.keys_rev) > (1 << 20):
            # tag-churn guard (the measure_exec persistent-group cap
            # analog): an unbounded intern table means unbounded LUTs —
            # drop ALL window state and restart coverage at the next
            # window boundary; queries over the gap rescan
            sig.windows.clear()
            sig.snapshots.clear()
            sig.cond_luts.clear()
            sig.proj_luts.clear()
            sig.key_index.clear()
            sig.keys_rev.clear()
            W = sig.spec.window_millis
            sig.covered_from = (
                sig.watermark - (sig.watermark % W) + 2 * W
                if sig.watermark > _NEG_INF_TS
                else _POS_INF_TS
            )

    # -- query rewrite -------------------------------------------------------
    def plan_cover(self, m, req: QueryRequest) -> Optional[Cover]:
        """Coverage decision for one aggregate query: the Cover names
        the signature to fold, the folded window range, and the
        uncovered head/tail ranges the caller must rescan.  None =
        answer by full rescan (shape not materializable, no signature,
        flag off, or no usable full window in range)."""
        if not enabled():
            return None
        key = (m.group, m.name)
        if key not in self._active:
            return None
        cover = self._plan_cover_inner(m, req)
        self._meter.counter_add(
            "streamagg_reads", 1.0,
            {"kind": cover.kind if cover is not None else "fallback"},
        )
        return cover

    def _plan_cover_inner(self, m, req: QueryRequest) -> Optional[Cover]:
        from banyandb_tpu.query import measure_exec

        if req.group_by is not None and req.group_by.field_name:
            return None
        group_tags = (
            tuple(req.group_by.tag_names) if req.group_by else ()
        )
        agg = req.agg
        if agg is not None and agg.function not in (
            "count", "sum", "mean", "min", "max",
        ):
            return None  # percentile histograms are range-dependent
        try:
            conds, expr = measure_exec._lower_criteria(req.criteria)
        except ValueError:
            return None
        if expr:
            return None  # OR trees: disjuncts cannot filter state keys
        tag_names = {t.name for t in m.tags}
        for c in conds:
            if c.op not in _COVERED_OPS or c.name not in tag_names:
                return None
        # representative (projected-but-not-grouped) tags need the first
        # scanned ROW's values — windows keep no rows, so fall back
        from banyandb_tpu.api.schema import FieldType as _FT

        schema_fields = {f.name for f in m.fields}
        known = {
            f.name
            for f in m.fields
            if f.type not in (_FT.STRING, _FT.DATA_BINARY)
        }
        for t in req.tag_projection:
            if t in group_tags or t in schema_fields:
                continue
            return None
        fields = {f for f in req.field_projection if f in known}
        if agg:
            fields.add(agg.field_name)
        if req.top:
            fields.add(req.top.field_name)
        b = req.time_range.begin_millis
        e = req.time_range.end_millis
        want_rep = bool(group_tags)
        if want_rep and e - b >= 2**31:
            # the rescan drops scan-order tracking past an int32 ts span
            # and orders canonically instead — don't try to mirror that
            return None
        needed_tags = set(group_tags) | {c.name for c in conds}
        try:
            lits = [
                (
                    c.name,
                    c.op,
                    frozenset(
                        measure_exec._tag_value_bytes(v) for v in c.value
                    )
                    if c.op in ("in", "not_in")
                    else measure_exec._tag_value_bytes(c.value),
                )
                for c in conds
            ]
        except TypeError:
            return None
        with self._lock:
            best: Optional[_Sig] = None
            for sig in self._by_measure.get((m.group, m.name), ()):
                if sig.building:
                    continue
                if not needed_tags <= set(sig.spec.key_tags):
                    continue
                if not fields <= set(sig.spec.fields):
                    continue
                if best is None or len(sig.spec.key_tags) < len(
                    best.spec.key_tags
                ):
                    best = sig
            if best is None:
                return None
            W = best.spec.window_millis
            c0 = -(-b // W) * W
            c1 = (e // W) * W
            cov_lo = max(c0, best.covered_from)
            if cov_lo >= c1:
                return None  # no full covered window in range
            key_index = {t: i for i, t in enumerate(best.spec.key_tags)}
            return Cover(
                sig=best,
                group_tags=group_tags,
                fields=tuple(sorted(fields)),
                conds=[(key_index[nm], op, v) for nm, op, v in lits],
                want_minmax=(
                    not agg
                    or agg.function in ("min", "max")
                ),
                want_rep=want_rep,
                rep_desc=req.order_by_ts == "desc",
                cov_lo=cov_lo,
                cov_hi=c1,
                head=(b, cov_lo) if b < cov_lo else None,
                tail=(c1, e) if c1 < e else None,
            )

    def answer(
        self,
        cover: Cover,
        *,
        shard_ids=None,
        rescan: Callable[[int, int], object],
        span=None,
    ) -> Optional[list]:
        """Materialized partials for a covered query: fold the window
        states, rescan only the uncovered head/tail sub-ranges, return
        the partials list (head, fold, tail) for the ordinary
        combine/finalize tail.  ``rescan(begin, end)`` -> Partials over
        exactly that sub-range through the caller's normal scan path.

        The fold runs FIRST: if eviction (or the intern-cap reset)
        advanced the covered horizon past the planned range between
        plan_cover and here, the fold raises CoverageLost and this
        returns None — the caller falls back to the full rescan rather
        than answering with a window-shaped gap.  The partials keep the
        (head, fold, tail) order regardless of execution order."""
        import time as _time

        t0 = _time.perf_counter()
        head_ms = tail_ms = 0.0
        try:
            fold = self._fold(cover, shard_ids)
        except CoverageLost:
            self._meter.counter_add(
                "streamagg_reads", 1.0, {"kind": "lost"}
            )
            if span is not None:
                span.tag("coverage", "lost")
            return None
        parts = []
        if cover.head is not None:
            th = _time.perf_counter()
            parts.append(rescan(*cover.head))
            head_ms = (_time.perf_counter() - th) * 1000
        parts.append(fold)
        if cover.tail is not None:
            tt = _time.perf_counter()
            parts.append(rescan(*cover.tail))
            tail_ms = (_time.perf_counter() - tt) * 1000
        total_ms = (_time.perf_counter() - t0) * 1000
        _H_STREAMAGG.observe(total_ms)
        if span is not None:
            span.tag("signature", cover.sig.spec.label()).tag(
                "coverage", cover.kind
            ).tag(
                "windows",
                int((cover.cov_hi - cover.cov_lo)
                    // cover.sig.spec.window_millis),
            ).tag("groups", int(fold.count.size)).tag(
                "head_rescan_ms", round(head_ms, 3)
            ).tag("tail_rescan_ms", round(tail_ms, 3))
        return parts

    def _snapshot_locked(self, sig: _Sig, w: int, shard: int, states) -> tuple:
        """Frozen ([K] key ids, [K, C] acc matrix) for one window-shard,
        cached until the next apply touches it.  Covered windows are
        CLOSED windows, so in steady state every fold reuses these and
        the per-state Python cost is paid once per window, not per
        query.  The arrays are never mutated after construction (touch
        pops the cache entry; a rebuild makes new arrays), so readers
        may use them outside the lock."""
        snap = sig.snapshots.get((w, shard))
        if snap is None:
            k = len(states)
            ids = np.fromiter(states.keys(), np.int64, count=k)
            mat = np.asarray(
                list(states.values()), dtype=np.float64
            ).reshape(k, _ACC_FIXED + 3 * len(sig.spec.fields))
            snap = sig.snapshots[(w, shard)] = (ids, mat)
        return snap

    def _cond_mask_locked(self, sig: _Sig, conds: list):
        """AND-combined bool LUT over interned key ids for the covered
        predicate set; per-condition LUTs cache append-only (extension
        rebinds a NEW array, so captured references stay frozen).
        Bytes equality over the same canonical entity-bytes domain the
        rescan's global-code comparison resolves to."""
        if not conds:
            return None
        n = len(sig.keys_rev)
        rev = sig.keys_rev
        out = None
        for idx, op, val in conds:
            ck = (idx, op, val)
            lut = sig.cond_luts.get(ck)
            start = 0 if lut is None else len(lut)
            if start < n:
                tail = np.empty(n - start, dtype=bool)
                if op == "eq":
                    for i in range(start, n):
                        tail[i - start] = rev[i][idx] == val
                elif op == "ne":
                    for i in range(start, n):
                        tail[i - start] = rev[i][idx] != val
                elif op == "in":
                    for i in range(start, n):
                        tail[i - start] = rev[i][idx] in val
                else:  # not_in
                    for i in range(start, n):
                        tail[i - start] = rev[i][idx] not in val
                lut = tail if lut is None else np.concatenate([lut, tail])
                sig.cond_luts[ck] = lut
            out = lut if out is None else (out & lut)
        return out

    def _proj_lut_locked(self, sig: _Sig, group_tags: tuple) -> tuple:
        """key id -> group id LUT for one group-by projection, plus the
        group-tuple intern table (append-only, extended lazily as new
        key tuples appear)."""
        entry = sig.proj_luts.get(group_tags)
        if entry is None:
            entry = ({}, [], np.zeros(0, dtype=np.int64))
        proj_index, proj_rev, lut = entry
        n = len(sig.keys_rev)
        if len(lut) < n:
            proj = [sig.spec.key_tags.index(t) for t in group_tags]
            tail = np.empty(n - len(lut), dtype=np.int64)
            for i in range(len(lut), n):
                g = tuple(sig.keys_rev[i][j] for j in proj)
                gid = proj_index.get(g)
                if gid is None:
                    gid = proj_index[g] = len(proj_rev)
                    proj_rev.append(g)
                tail[i - len(lut)] = gid
            lut = np.concatenate([lut, tail]) if len(lut) else tail
            sig.proj_luts[group_tags] = (proj_index, proj_rev, lut)
        return proj_index, proj_rev, lut

    def _fold(self, cover: Cover, shard_ids=None):
        """Window states -> one Partials, mirroring the rescan's shape:
        per-group f64 count/sums (+ real min/max when the aggregate
        needs them, untouched ±inf otherwise, exactly like the device
        path), first-appearance rep keys (group min/max event ts; the
        acc seq is the row-order tie-break the rescan's local row index
        plays), field_stats for the percentile range round.

        Vectorized end-to-end: frozen window snapshots concatenate,
        predicates gather through cached id LUTs, and the cross-window
        group merge is np.unique + bincount / ufunc-at — the host-side
        shape of ops.group_reduce, never per-state Python in the query
        path."""
        from banyandb_tpu.query.measure_exec import Partials

        sig = cover.sig
        spec = sig.spec
        flds = cover.fields
        desc = cover.rep_desc
        with self._lock:
            if sig.building or sig.covered_from > cover.cov_lo:
                # the planned range was evicted/reset since plan_cover:
                # folding now would silently drop the missing windows
                raise CoverageLost(cover.sig.spec.label())
            # serve-hit bookkeeping: the autoreg budget evicts the
            # least-recently-HIT auto signature first
            sig.hits += 1
            sig.last_hit_ms = int(_now_ms())
            snaps = []
            for w in sig.windows:
                if not (cover.cov_lo <= w < cover.cov_hi):
                    continue
                for shard, states in sig.windows[w].items():
                    if shard_ids is not None and shard not in shard_ids:
                        continue
                    if states:
                        snaps.append(
                            self._snapshot_locked(sig, w, shard, states)
                        )
            cond_lut = self._cond_mask_locked(sig, cover.conds)
            proj_index, proj_rev, proj_lut = self._proj_lut_locked(
                sig, cover.group_tags
            )
        # below needs no lock: snapshots/LUTs are frozen-at-capture
        C = _ACC_FIXED + 3 * len(spec.fields)
        if snaps:
            ids = np.concatenate([s[0] for s in snaps])
            mat = np.concatenate([s[1] for s in snaps], axis=0)
        else:
            ids = np.zeros(0, dtype=np.int64)
            mat = np.zeros((0, C), dtype=np.float64)
        if cond_lut is not None and ids.size:
            keep = cond_lut[ids]
            ids = ids[keep]
            mat = mat[keep]
        gids = proj_lut[ids] if ids.size else ids
        uniq, inv = np.unique(gids, return_inverse=True)
        K = int(uniq.size)
        glist = [proj_rev[int(g)] for g in uniq]
        count = np.bincount(inv, weights=mat[:, 0], minlength=K)
        sums, mins, maxs = {}, {}, {}
        for f in flds:
            base = _ACC_FIXED + 3 * spec.fields.index(f)
            sums[f] = np.bincount(inv, weights=mat[:, base], minlength=K)
            if cover.want_minmax:
                mn = np.full(K, np.inf, dtype=np.float64)
                mx = np.full(K, -np.inf, dtype=np.float64)
                np.minimum.at(mn, inv, mat[:, base + 1])
                np.maximum.at(mx, inv, mat[:, base + 2])
                mins[f], maxs[f] = mn, mx
            else:
                # mirror the rescan: min/max untouched when the plan
                # does not compute them
                mins[f] = np.full(K, np.inf, dtype=np.float64)
                maxs[f] = np.full(K, -np.inf, dtype=np.float64)
        rep_key = None
        if cover.want_rep:
            # acc ts/seq live in the f64 matrix: exact to 2^53, far past
            # epoch-millis and the seq counter
            ts_col = mat[:, 2] if desc else mat[:, 1]
            seq_col = mat[:, 4] if desc else mat[:, 3]
            if desc:
                gts = np.full(K, -np.inf, dtype=np.float64)
                np.maximum.at(gts, inv, ts_col)
                tie = ts_col == gts[inv] if ids.size else np.zeros(0, bool)
                gseq = np.full(K, -np.inf, dtype=np.float64)
                np.maximum.at(gseq, inv[tie], seq_col[tie])
            else:
                gts = np.full(K, np.inf, dtype=np.float64)
                np.minimum.at(gts, inv, ts_col)
                tie = ts_col == gts[inv] if ids.size else np.zeros(0, bool)
                gseq = np.full(K, np.inf, dtype=np.float64)
                np.minimum.at(gseq, inv[tie], seq_col[tie])
            rep_key = np.stack([gts, gseq], axis=1).astype(np.int64)
        field_stats = {}
        if cover.want_minmax and K:
            nonempty = count > 0
            if nonempty.any():
                for f in flds:
                    field_stats[f] = (
                        float(mins[f][nonempty].min()),
                        float(maxs[f][nonempty].max()),
                    )
        if not cover.group_tags and K == 0:
            # the rescan always reports the single logical flat group,
            # matching _reduce_partials' nz=[0] shape
            glist = [()]
            count = np.zeros(1, dtype=np.float64)
            sums = {f: np.zeros(1, dtype=np.float64) for f in flds}
            mins = {f: np.full(1, np.inf, dtype=np.float64) for f in flds}
            maxs = {f: np.full(1, -np.inf, dtype=np.float64) for f in flds}
        return Partials(
            group_tags=cover.group_tags,
            groups=glist,
            count=count,
            sums=sums,
            mins=mins,
            maxs=maxs,
            hist=None,
            field_stats=field_stats,
            rep_key=rep_key,
            rep_desc=cover.rep_desc,
            rep_vals=None,
        )

    # -- introspection -------------------------------------------------------
    def _stats_one_locked(self, sig: _Sig) -> dict:
        return {
            "signature": sig.spec.label(),
            "group": sig.spec.group,
            "measure": sig.spec.measure,
            "key_tags": list(sig.spec.key_tags),
            "fields": list(sig.spec.fields),
            "window_millis": sig.spec.window_millis,
            "origin": sig.origin,
            "hits": sig.hits,
            "last_hit_ms": sig.last_hit_ms or None,
            "windows": len(sig.windows),
            "states": sum(
                len(s)
                for by in sig.windows.values()
                for s in by.values()
            ),
            "rows": sig.rows,
            "late_dropped": sig.late,
            "evicted_states": sig.evicted,
            "covered_from": (
                None if sig.covered_from == _NEG_INF_TS
                else sig.covered_from
            ),
            "watermark": (
                None if sig.watermark == _NEG_INF_TS else sig.watermark
            ),
            "building": sig.building,
        }

    def stats(self) -> dict:
        with self._lock:
            sigs = [self._stats_one_locked(s) for s in self._sigs.values()]
        return {
            "enabled": enabled(),
            "signatures": sigs,
            "windows": sum(s["windows"] for s in sigs),
            "states": sum(s["states"] for s in sigs),
            "rows": sum(s["rows"] for s in sigs),
            "late_dropped": sum(s["late_dropped"] for s in sigs),
        }

    def export_gauges(self) -> None:
        """Window/read/staleness gauges for the /metrics scrape."""
        st = self.stats()
        self._meter.gauge_set(
            "streamagg_signatures", float(len(st["signatures"]))
        )
        self._meter.gauge_set("streamagg_windows", float(st["windows"]))
        self._meter.gauge_set("streamagg_states", float(st["states"]))
        for s in st["signatures"]:
            if s["watermark"] is not None:
                self._meter.gauge_set(
                    "streamagg_watermark_ms",
                    float(s["watermark"]),
                    {"signature": s["signature"]},
                )
