"""Fused whole-plan executor: one XLA program per plan signature.

The staged executor (query/measure_exec) dispatches the per-chunk plan
kernel once per scan chunk with a batched device_get trailing each
dispatch — N accelerator round-trips per part-batch.  Tailwind (arXiv
2604.28079) argues the accelerator win comes from compiling the *whole*
query, not offloading operators; this module is that compiler for the
measure plan family: filter + group-by + aggregate + the rank inputs
(TopN metric vectors, percentile histograms) execute as ONE jitted
program per plan signature, so a part-batch crosses the accelerator
boundary exactly once — one dispatch in, one batched device_get out.

How parity is guaranteed (the A/B contract, ``BYDB_FUSED=0`` restores
the staged path):

- the fused program ``lax.scan``s the SAME per-chunk body the staged
  path jits (``measure_exec._kernel_body``) over a ``[C, nrows]``
  stacked chunk batch, and returns the per-chunk f32 partials stacked
  ``[C, ...]`` — the host then folds them into the f64 accumulators in
  scan order exactly like the staged loop.  Same per-chunk graph, same
  absorb order => byte-identical partials and results.
- group-by strategy (hash/scatter vs segment-sort, per arXiv
  2411.13245) resolves through ``ops.groupby.select_group_method`` from
  the signature's (nrows, num_groups) in BOTH paths, so an A/B flip can
  never pair different reduction orders.

Signature lifecycle: the chunk-count bucket rides the jit key
(``FusedSpec = PlanSpec + num_chunks``, power-of-two buckets keep the
compiled-shape set finite), every resolution is recorded in the
precompile registry under kind="fused" (cold starts warm the fused
kernels), and the bdjit kernel audit pins each builtin fused signature
to dispatches=1 / gets=1 in ``lint/kernel/kernel_budgets.py`` so
staging can never silently creep back.

The mesh half (``build_fused_dist_step``) shard_maps the same chunked
scan over a ('shard','seg') device mesh with the dist-path collectives
(psum count/sums/hist + pmin/pmax), so a distributed scan is one
collective program with a BOUNDED compile-shape set instead of one
unbounded-width kernel per row-count bucket.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from banyandb_tpu.query.measure_exec import PlanSpec, _kernel_body
from banyandb_tpu.utils.envflag import env_flag, env_int


def fused_enabled() -> bool:
    """The A/B flag: default on, ``BYDB_FUSED=0`` restores the staged
    per-chunk loop (read per query so operators can flip it live)."""
    return env_flag("BYDB_FUSED", default=True)


def max_fused_mb() -> int:
    """Device-footprint ceiling for one fused part-batch (stacked input
    columns + stacked per-chunk partials).  Plans whose one-shot
    footprint exceeds it (e.g. a huge-G percentile over many chunks,
    where the stacked [C, G, 512] histogram explodes) fall back to the
    staged loop instead of OOMing the device."""
    return env_int("BYDB_FUSED_MAX_MB", 1024)


@dataclass(frozen=True)
class FusedSpec:
    """Static jit key of one fused program: the plan signature plus the
    chunk-count bucket the part-batch is stacked into."""

    plan: PlanSpec
    num_chunks: int


def chunk_count_bucket(n_chunks: int) -> int:
    """Power-of-two chunk-count buckets: the compiled-shape set stays
    O(log max_chunks); chunks beyond the real count are fully invalid
    (valid=False everywhere) so absorbing them would be a numeric no-op
    — the host still only absorbs the real ones."""
    b = 1
    while b < n_chunks:
        b <<= 1
    return b


_KERNEL_CACHE: dict[FusedSpec, object] = {}


def _build_kernel(fspec: FusedSpec):
    """jit the whole-plan program: scan the shared per-chunk body over
    the stacked chunk axis, emitting stacked per-chunk partials.

    Compressed part-batches (``BYDB_DEVICE_DECODE``) decode FIRST,
    inside this same program: ops.decode.decode_chunk widens/remaps the
    whole stacked ``[C, nrows]`` batch (the remap LUTs are per-batch,
    not per-chunk, so decoding before the scan avoids broadcasting them
    down the scanned axis), then the scan body sees exactly the
    canonical chunks the staged kernel decodes per chunk — elementwise
    integer decode, so fused-vs-staged stays byte-identical in either
    ship form."""
    from banyandb_tpu.ops import decode as ops_decode

    body = _kernel_body(fspec.plan)

    def fused(chunks: dict, pred_vals: dict, hist_lo, hist_span):
        chunks = ops_decode.decode_chunk(chunks)

        def step(carry, chunk):
            return carry, body(chunk, pred_vals, hist_lo, hist_span)

        _, stacked = jax.lax.scan(step, None, chunks)
        return stacked

    return jax.jit(fused)


def _num_hist_buckets() -> int:
    from banyandb_tpu.query import measure_exec

    return measure_exec._NUM_HIST_BUCKETS


def estimate_bytes(spec: PlanSpec, num_chunks: int) -> int:
    """Device footprint of one fused part-batch: stacked input columns
    plus the stacked per-chunk partials pytree.

    Under ``BYDB_DEVICE_DECODE`` the compressed inputs (narrow tag/field
    buffers, the i16 src-ordinal column) are resident ALONGSIDE the
    decoded i32/f32 copies the in-program decode stage materializes
    before the scan, so the ceiling accounts both — else a batch sized
    at ``BYDB_FUSED_MAX_MB`` would OOM instead of taking the intended
    staged fallback.  (The [S, L] remap LUTs are a rounding error next
    to the per-row columns and ride the same conservative margin.)"""
    from banyandb_tpu.storage import encoded as enc_mod

    g = spec.num_groups
    nf = len(spec.fields)
    per_chunk_out = g * (1 + nf + (2 * nf if spec.want_minmax else 0))
    if spec.hist_field:
        per_chunk_out += g * _num_hist_buckets()
    if spec.want_rep:
        per_chunk_out += 2 * g
    cols = 4 + len(spec.tags_code) + nf  # ts/series/valid/row + tags + fields
    per_row = 4 * cols
    if enc_mod.device_decode_enabled():
        # narrow inputs (<=2 B/row per tag/field) + src_ord (2 B/row)
        per_row += 2 + 2 * (len(spec.tags_code) + nf)
    return num_chunks * (per_row * spec.nrows + 4 * per_chunk_out)


def _resolve_bucket(n_chunks: int, min_bucket: int | None) -> int:
    """The chunk-count bucket for a part-batch, honoring the planner's
    minimum-bucket hint.  The hint only ever rounds UP (padding chunks
    are fully invalid, the host absorbs only real ones — byte-identical)
    and is capped at one doubling of the actual bucket: the hint exists
    for part populations oscillating around a bucket boundary, not to
    pad a 1-chunk batch into a 64-chunk program."""
    bucket = chunk_count_bucket(n_chunks)
    if min_bucket is not None and bucket < min_bucket <= bucket * 2:
        return min_bucket
    return bucket


def eligible(
    spec: PlanSpec, n_chunks: int, min_bucket: int | None = None
) -> bool:
    """Fused path taken for this part-batch?  Flag + footprint budget."""
    if n_chunks < 1 or not fused_enabled():
        return False
    bucket = _resolve_bucket(n_chunks, min_bucket)
    return estimate_bytes(spec, bucket) <= max_fused_mb() * (1 << 20)


def _stacked_chunks(
    cols: dict,
    spans: list[tuple[int, int]],
    spec: PlanSpec,
    num_chunks: int,
    epoch: int,
    pad_ship_s: list | None = None,
    ship_stats: list | None = None,
) -> dict:
    """Pad the gathered columns into ``[C, nrows]`` device arrays.

    Chunk layout (per-row dtypes, padding, the epoch-relative int32 ts,
    the global row index) matches measure_exec._device_chunk exactly —
    the scan body sees per-chunk inputs identical to the staged
    kernel's, in EITHER ship form: compressed snapshots
    (``BYDB_DEVICE_DECODE``) stack the narrow local tag codes, the
    per-row source ordinals and exact-int fields, plus the per-batch
    [S, L] remap LUTs the in-program decode stage consumes.  Per-column
    pad work rides the chunk_stream prefetch worker (BYDB_PIPELINE
    honored) so padding column j+1 overlaps shipping column j.
    ``ship_stats`` collects one (shipped, dense) byte pair for the
    whole part-batch (decode-span attribution).
    """
    from banyandb_tpu.storage.chunk_stream import prefetched

    C, nb = num_chunks, spec.nrows
    compressed = "src_ord" in cols

    def pad2(get, dtype):
        out = np.zeros((C, nb), dtype=dtype)
        for k, (s, e) in enumerate(spans):
            out[k, : e - s] = get(s, e)
        return out

    def valid2():
        out = np.zeros((C, nb), dtype=bool)
        for k, (s, e) in enumerate(spans):
            out[k, : e - s] = True
        return out

    paths: list[tuple] = [("ts",), ("series",), ("valid",), ("row",)]
    thunks = [
        lambda: pad2(lambda s, e: cols["ts"][s:e] - epoch, np.int32),
        lambda: pad2(lambda s, e: cols["series"][s:e] % (2**31), np.int32),
        valid2,
        lambda: pad2(lambda s, e: np.arange(s, e, dtype=np.int32), np.int32),
    ]
    counted: set = set()
    if compressed:
        from banyandb_tpu.storage import encoded as enc_mod

        if spec.tags_code:
            for t in spec.tags_code:
                paths.append(("tags_enc", t))
                counted.add(("tags_enc", t))
                thunks.append(
                    lambda t=t: pad2(
                        lambda s, e: cols["tags_enc"][t][s:e],
                        cols["tags_enc"][t].dtype,
                    )
                )
                paths.append(("tags_lut", t))
                counted.add(("tags_lut", t))
                thunks.append(
                    lambda t=t: enc_mod.pack_luts(cols["tags_lut"][t])
                )
            paths.append(("src_ord",))
            counted.add(("src_ord",))
            thunks.append(
                lambda: pad2(
                    lambda s, e: cols["src_ord"][s:e], enc_mod.SRC_ORD_DTYPE
                )
            )
        for f in spec.fields:
            ndt = cols["fields_narrow"].get(f)
            if ndt is not None:
                paths.append(("fields_enc", f))
                counted.add(("fields_enc", f))
                thunks.append(
                    lambda f=f, ndt=ndt: pad2(
                        lambda s, e: cols["fields"][f][s:e], ndt
                    )
                )
            else:
                paths.append(("fields", f))
                counted.add(("fields", f))
                thunks.append(
                    lambda f=f: pad2(
                        lambda s, e: cols["fields"][f][s:e], np.float32
                    )
                )
    else:
        for t in spec.tags_code:
            paths.append(("tags_code", t))
            counted.add(("tags_code", t))
            thunks.append(
                lambda t=t: pad2(lambda s, e: cols["tags_code"][t][s:e], np.int32)
            )
        for f in spec.fields:
            paths.append(("fields", f))
            counted.add(("fields", f))
            thunks.append(
                lambda f=f: pad2(lambda s, e: cols["fields"][f][s:e], np.float32)
            )

    def timed(fn):
        def pad_thunk():  # host-side work on the prefetch worker
            t0 = time.perf_counter()
            try:
                return fn()
            finally:
                if pad_ship_s is not None:
                    pad_ship_s.append(time.perf_counter() - t0)

        return pad_thunk

    out: dict = {
        "tags_code": {},
        "tags_enc": {},
        "tags_lut": {},
        "fields": {},
        "fields_enc": {},
    }
    shipped = 0
    for path, arr in zip(
        paths,
        prefetched([timed(fn) for fn in thunks], name="bydb-fused-pad"),
    ):
        t0 = time.perf_counter()
        dev = jnp.asarray(arr)
        if pad_ship_s is not None:
            pad_ship_s.append(time.perf_counter() - t0)
        if path in counted:
            shipped += dev.nbytes
        if len(path) == 1:
            out[path[0]] = dev
        else:
            out[path[0]][path[1]] = dev
    # canonical keys (tags_code/fields) stay present even when empty —
    # the pre-decode chunk structure the staged path and the precompile
    # warm args share; the compressed-only keys appear only when used
    for key in ("tags_enc", "tags_lut", "fields_enc"):
        if not out[key]:
            del out[key]
    if ship_stats is not None:
        dense = (len(spec.tags_code) + len(spec.fields)) * C * nb * 4
        ship_stats.append((shipped, dense))
    return out


def run_fused(
    chunks_np: dict,
    chunk_spans: list[tuple[int, int]],
    spec: PlanSpec,
    pred_vals: dict,
    hist_lo,
    hist_span,
    epoch: int,
    *,
    gather_key=None,
    dev_cache=None,
    pad_ship_s: list | None = None,
    ship_stats: list | None = None,
    min_bucket: int | None = None,
) -> tuple[list[dict], float, str]:
    """Execute one part-batch through the fused program.

    -> (per-chunk host partials in scan order for the staged f64 absorb
    loop, seconds spent at the two accelerator boundaries, input-cache
    outcome tag).  Exactly one kernel dispatch and one batched
    device_get regardless of chunk count.  ``min_bucket`` (planner
    hint) rounds the chunk-count bucket up — see ``_resolve_bucket``.
    """
    num_chunks = _resolve_bucket(len(chunk_spans), min_bucket)
    fspec = FusedSpec(plan=spec, num_chunks=num_chunks)
    kernel = _KERNEL_CACHE.get(fspec)
    if kernel is None:
        kernel = _KERNEL_CACHE[fspec] = _build_kernel(fspec)
    # function-local import: precompile imports this module's builders
    from banyandb_tpu.query.precompile import default_registry

    default_registry().record("fused", fspec)

    built: list = []

    def _build():
        built.append(1)
        return _stacked_chunks(
            chunks_np, chunk_spans, spec, num_chunks, epoch, pad_ship_s,
            ship_stats=ship_stats,
        )

    if dev_cache is not None:
        # stacked inputs depend only on (gathered data, bucket, columns):
        # keep them device-resident so repeat queries skip pad+ship too
        # (the fused twin of the staged per-chunk device cache)
        ck = (
            "fused_chunks",
            gather_key,
            num_chunks,
            spec.nrows,
            spec.tags_code,
            spec.fields,
        )
        dev_chunks = dev_cache.get_or_load(ck, _build)
    else:
        dev_chunks = _build()

    device_s = 0.0
    t0 = time.perf_counter()
    out = kernel(dev_chunks, pred_vals, hist_lo, hist_span)
    device_s += time.perf_counter() - t0
    t0 = time.perf_counter()
    # bdlint: disable=host-sync -- THE result boundary of the fused
    # plan: the whole part-batch's stacked partials move in one batched
    # transfer (1 get per part-batch, ratcheted by kernel_budgets)
    moved = jax.device_get(out)
    device_s += time.perf_counter() - t0
    chunks_out = [
        jax.tree_util.tree_map(lambda a, k=k: a[k], moved)
        for k in range(len(chunk_spans))
    ]
    return chunks_out, device_s, ("built" if built else "hit")


# ---------------------------------------------------------------------------
# Mesh-parallel fused step: the whole distributed scan as ONE collective
# program (shard_map over ('shard','seg'), dist_exec's psum/pmin/pmax set)
# with a bounded compile-shape set (fixed-nrows chunks scanned per device).
# ---------------------------------------------------------------------------


def _fused_dist_step(
    plan, num_chunks: int, chunks: dict, pred_codes: dict, hist_lo, hist_span
):
    """One device's [1, C*nrows] slice -> chunked scan -> collectives.

    Per-chunk f32 partials combine across chunks with Kahan-compensated
    f32 (count/sums/hist) and exact min/max — the precision contract's
    bounded-span rule, on device.  With num_chunks=1 the math reduces to
    parallel/dist_exec._step exactly (Kahan from zero is the identity).
    """
    from banyandb_tpu import ops
    from banyandb_tpu.ops.groupby import _kahan_add
    from banyandb_tpu.parallel import dist_exec

    nhb = dist_exec._NUM_HIST_BUCKETS
    chunks = jax.tree.map(
        lambda a: a.reshape((num_chunks, -1)), chunks
    )
    G = plan.num_groups
    zero = jnp.zeros(G, jnp.float32)

    def step(carry, chunk):
        # the SAME map half the legacy mesh step runs (dist_exec.map_chunk)
        part, key, mask = dist_exec.map_chunk(plan, chunk, pred_codes)
        count, sums, mins, maxs, hist = carry
        count = _kahan_add(count[0], count[1], part.count)
        sums = {
            f: _kahan_add(sums[f][0], sums[f][1], part.sums[f])
            for f in plan.fields
        }
        mins = {
            f: jnp.minimum(mins[f], part.mins[f]) for f in plan.fields
        }
        maxs = {
            f: jnp.maximum(maxs[f], part.maxs[f]) for f in plan.fields
        }
        if plan.want_hist:
            h = ops.group_histogram(
                key,
                mask,
                chunk["fields"][plan.want_hist],
                G,
                hist_lo,
                hist_span,
                nhb,
            )
            hist = _kahan_add(hist[0], hist[1], h)
        return (count, sums, mins, maxs, hist), None

    init = (
        (zero, zero),
        {f: (zero, zero) for f in plan.fields},
        {f: jnp.full(G, jnp.inf, jnp.float32) for f in plan.fields},
        {f: jnp.full(G, -jnp.inf, jnp.float32) for f in plan.fields},
        (
            (jnp.zeros((G, nhb), jnp.float32),) * 2
            if plan.want_hist
            else (zero, zero)
        ),
    )
    (count, sums, mins, maxs, hist), _ = jax.lax.scan(step, init, chunks)

    # ---- the collective reduce: ICI replaces the proto partial hop ----
    axes = ("shard", "seg")
    out = {
        "count": jax.lax.psum(count[0] - count[1], axes),
        "sums": {
            f: jax.lax.psum(sums[f][0] - sums[f][1], axes)
            for f in plan.fields
        },
        "mins": {f: jax.lax.pmin(mins[f], axes) for f in plan.fields},
        "maxs": {f: jax.lax.pmax(maxs[f], axes) for f in plan.fields},
    }
    if plan.want_hist:
        out["hist"] = jax.lax.psum(hist[0] - hist[1], axes)
    if plan.topn:
        mean = out["sums"][plan.fields[0]] / jnp.maximum(out["count"], 1.0)
        vals, idx = ops.topk_groups(mean, out["count"] > 0, plan.topn)
        out["top_vals"], out["top_idx"] = vals, idx
    return out


_DIST_STEP_CACHE: dict[tuple, object] = {}


def build_fused_dist_step(mesh, plan, num_chunks: int):
    """-> jitted f(chunks, pred_codes, hist_lo, hist_span): the whole
    distributed scan as one collective program.  ``chunks`` arrays carry
    [D, num_chunks*nrows] sharded over ('shard','seg'); outputs are
    replicated.  Memoized per (mesh devices, plan, chunk bucket)."""
    from banyandb_tpu.parallel import dist_exec

    cache_key = (
        tuple(d.id for d in mesh.devices.flat),
        mesh.axis_names,
        plan,
        num_chunks,
    )
    cached = _DIST_STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached

    from jax.sharding import PartitionSpec as P

    if hasattr(jax, "shard_map"):
        _shard_map = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as _shard_map
    data_spec = P(("shard", "seg"))
    step = _shard_map(
        partial(_fused_dist_step, plan, num_chunks),
        mesh=mesh,
        in_specs=(
            {
                "valid": data_spec,
                "tags": {t: data_spec for t in plan.tags_code},
                "fields": {f: data_spec for f in plan.fields},
            },
            {t: P() for t in plan.eq_preds},
            P(),
            P(),
        ),
        out_specs=dist_exec._out_specs(plan),
    )
    jitted = jax.jit(step)
    _DIST_STEP_CACHE[cache_key] = jitted
    return jitted


def fused_distributed_aggregate(
    mesh,
    plan,
    num_chunks: int,
    chunks: dict,
    pred_codes=None,
    hist_lo: float = 0.0,
    hist_span: float = 1.0,
):
    """Convenience wrapper mirroring dist_exec.distributed_aggregate."""
    step = build_fused_dist_step(mesh, plan, num_chunks)
    codes = {
        t: jnp.int32((pred_codes or {}).get(t, -1)) for t in plan.eq_preds
    }
    return step(chunks, codes, jnp.float32(hist_lo), jnp.float32(hist_span))
