"""Device scan path for retrieval-shaped (stream/raw) tag filtering.

The aggregate path fuses filtering into the reduce kernel
(measure_exec); retrieval queries only need the boolean row mask — but
at stream scale (millions of log elements) evaluating many tag
predicates per row is still vector work the device does better than
row-at-a-time host code.  This module jits one mask kernel per predicate
signature (op kinds + padded row bucket), ships dictionary-code columns,
and returns a host bool mask; the host keeps the cheap parts (time
range, gather of the few selected rows).

Semantics match query/filter.row_mask exactly (-1 = literal not in
dictionary, -2 = column absent); tests/test_stream_index.py fuzzes the
two against each other.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from banyandb_tpu.api.model import Condition
from banyandb_tpu.query.filter import tag_value_bytes
from banyandb_tpu.storage.part import ColumnData

# below this, kernel-launch overhead beats the vector win — host numpy
DEVICE_MIN_ROWS = 32_768


def _pad_bucket(n: int) -> int:
    """Next power-of-two row bucket (mask sources can be far larger than
    the 8192-row aggregate chunk; ~log2 distinct kernel shapes total)."""
    return 1 << max(n - 1, 1).bit_length()

_SUPPORTED = {"eq", "ne", "in", "not_in"}


@dataclass(frozen=True)
class _MaskSpec:
    preds: tuple[tuple[str, int], ...]  # (op, padded set size)
    nrows: int


_KERNEL_CACHE: dict[_MaskSpec, object] = {}


def _build_kernel(spec: _MaskSpec):
    import jax
    import jax.numpy as jnp

    from banyandb_tpu import ops

    def kernel(cols, pred_vals):
        mask = jnp.ones(spec.nrows, dtype=bool)
        for i, (op, _nv) in enumerate(spec.preds):
            # device-side decode stage (ROADMAP item 3): columns arrive
            # at their stored narrow width when the source was read with
            # narrow_codes and widen here, on device; i32 columns pass
            # through (astype is the identity) — predicate codes are
            # always i32, so the mask math is identical either way
            col = ops.widen_codes(cols[i])
            v = pred_vals[i]
            if op in ("in", "not_in"):
                m = ops.in_set_mask(col, v)
                mask &= ~m if op == "not_in" else m
            else:
                mask &= ops.cmp_mask(col, op, v)
        return mask

    return jax.jit(kernel)


def device_tag_mask(src: ColumnData, conds: list[Condition]):
    """bool[n] tag-predicate mask on device, or None when the predicate
    set is unsupported (caller falls back to the host path)."""
    import jax.numpy as jnp

    n = src.ts.size
    if not conds or any(c.op not in _SUPPORTED for c in conds):
        return None
    nrows = _pad_bucket(n)
    cols = []
    pred_vals = []
    preds = []
    for c in conds:
        col = src.tags.get(c.name)
        if col is None:
            col = np.full(n, -2, dtype=np.int32)
        d = src.dicts.get(c.name, [])
        lut = {v: i for i, v in enumerate(d)}
        if c.op in ("in", "not_in"):
            codes = sorted({lut.get(tag_value_bytes(v), -1) for v in c.value})
            # pad the set to the next power of two with the -1 sentinel
            # (matches no real code, codes are dict indices >= 0) so the
            # jit cache is keyed by O(log) set sizes, not every distinct
            # IN-list cardinality seen
            padded_len = 1 << max(0, (len(codes) - 1)).bit_length() if codes else 1
            arr = np.full(padded_len, -1, dtype=np.int32)
            arr[: len(codes)] = codes
            preds.append((c.op, padded_len))
            pred_vals.append(jnp.asarray(arr))
        else:
            code = lut.get(tag_value_bytes(c.value), -1)
            preds.append((c.op, 1))
            pred_vals.append(jnp.int32(code))
        # pad with a sentinel that matches nothing real; padded rows are
        # discarded by the caller's slice anyway.  The column keeps its
        # incoming width (narrow i8/i16 under the device-decode read
        # path — every signed width holds the -1/-2/-3 sentinels), so a
        # compressed column crosses PCIe compressed.
        padded = np.full(nrows, -3, dtype=col.dtype)
        padded[:n] = col
        cols.append(jnp.asarray(padded))

    spec = _MaskSpec(preds=tuple(preds), nrows=nrows)
    kernel = _KERNEL_CACHE.get(spec)
    if kernel is None:
        kernel = _KERNEL_CACHE[spec] = _build_kernel(spec)
    from banyandb_tpu.query.precompile import default_registry

    default_registry().record("stream_mask", spec)
    import jax

    # bdlint: disable=host-sync -- the retrieval result boundary: the
    # whole bool mask moves in one transfer; the host gather needs it
    mask = jax.device_get(kernel(tuple(cols), tuple(pred_vals)))
    return mask[:n]


def row_mask(
    src: ColumnData,
    conds: list[Condition],
    begin_millis: int,
    end_millis: int,
) -> np.ndarray:
    """Time+tag mask: device for big sources, host otherwise."""
    from banyandb_tpu.query import filter as qfilter

    if src.ts.size >= DEVICE_MIN_ROWS:
        tag_mask = device_tag_mask(src, conds)
        if tag_mask is not None:
            return (
                (src.ts >= begin_millis) & (src.ts < end_millis) & tag_mask
            )
    return qfilter.row_mask(src, conds, begin_millis, end_millis)
