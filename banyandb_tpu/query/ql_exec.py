"""Shared BydbQL executors for the trace and property catalogs.

One implementation serves both entry surfaces — the standalone bus server
(server.py TOPIC_QL) and the gRPC BydbQLService (api/grpc_server.py) —
the way the reference routes every catalog through one handler
(banyand/liaison/grpc/bydbql.go:143-173).  Measure and stream QL lower
onto their engines' query() directly; trace and property need the
catalog-specific planning below (trace-id lookup vs sidx-ordered scan,
id/tag filter splitting).
"""

from __future__ import annotations

from banyandb_tpu.api.model import QueryRequest, QueryResult, TimeRange


def and_leaves(req: QueryRequest):
    """Criteria leaves for catalogs whose executors take flat AND
    filters — OR trees are rejected rather than silently flattened
    (flattening an OR into AND returns wrong results)."""
    from banyandb_tpu.query.measure_exec import _lower_criteria

    leaves, expr = _lower_criteria(req.criteria)
    if expr:
        raise ValueError("OR criteria not supported for this catalog")
    return leaves


def _span_matches(span: dict, conds) -> bool:
    for c in conds:
        v = span.get("tags", {}).get(c.name)
        if c.op == "eq":
            if v != c.value:
                return False
        elif c.op == "ne":
            if v == c.value:
                return False
        elif c.op == "in":
            if v not in c.value:
                return False
        elif c.op == "not_in":
            if v in c.value:
                return False
        elif c.op in ("gt", "ge", "lt", "le"):
            if v is None:
                return False
            try:
                fv, fc = float(v), float(c.value)
            except (TypeError, ValueError):
                return False
            if c.op == "gt" and not fv > fc:
                return False
            if c.op == "ge" and not fv >= fc:
                return False
            if c.op == "lt" and not fv < fc:
                return False
            if c.op == "le" and not fv <= fc:
                return False
        else:  # never silently match an op we can't evaluate
            raise ValueError(f"trace QL op {c.op!r} not supported")
    return True


def execute_trace_ql(trace_engine, req: QueryRequest) -> QueryResult:
    """Trace QL execution: trace-id equality (the schema's trace_id_tag,
    not a hardcoded name) fetches spans; otherwise an ORDER BY <numeric
    tag> query rides the ordered (sidx) index with range bounds from
    conditions on that tag.  Residual tag conditions post-filter spans
    (never silently ignored); a SELECT projection narrows span tags."""
    res = QueryResult()
    leaves = and_leaves(req)
    group = req.groups[0]
    tid_tag = trace_engine.get_trace(group, req.name).trace_id_tag or "trace_id"
    proj = set(req.tag_projection or ())

    def shape(span: dict, tid: str) -> dict:
        tags = span.get("tags", {})
        if proj:
            tags = {k: v for k, v in tags.items() if k in proj}
        out = {"trace_id": tid, "tags": tags}
        if "span" in span:
            out["span"] = span["span"]
        return out

    tid_conds = [c for c in leaves if c.name == tid_tag and c.op == "eq"]
    if tid_conds:
        tid = str(tid_conds[0].value)
        residual = [c for c in leaves if c is not tid_conds[0]]
        spans = trace_engine.query_by_trace_id(group, req.name, tid)
        res.data_points = [
            shape(s, tid) for s in spans if _span_matches(s, residual)
        ][: req.limit or 100]
        return res
    if req.order_by_tag:
        lo = hi = None
        residual = []
        for c in leaves:
            if c.name == req.order_by_tag and c.op in ("gt", "ge", "lt", "le"):
                # duplicate bounds INTERSECT (AND semantics)
                if c.op in ("gt", "ge"):
                    b = int(c.value) + (1 if c.op == "gt" else 0)
                    lo = b if lo is None else max(lo, b)
                else:
                    b = int(c.value) - (1 if c.op == "lt" else 0)
                    hi = b if hi is None else min(hi, b)
            else:
                residual.append(c)
        tr = TimeRange(req.time_range.begin_millis, req.time_range.end_millis)
        ids = trace_engine.query_ordered(
            group,
            req.name,
            req.order_by_tag,
            tr,
            lo=lo,
            hi=hi,
            asc=(req.order_by_dir == "asc"),
            # over-fetch when residual filters will drop candidates
            limit=(req.limit or 20) * (4 if residual else 1),
        )
        if residual:
            kept = []
            for tid in ids:
                spans = trace_engine.query_by_trace_id(group, req.name, tid)
                if any(_span_matches(s, residual) for s in spans):
                    kept.append(tid)
                if len(kept) >= (req.limit or 20):
                    break
            ids = kept
        res.data_points = [{"trace_id": t} for t in ids[: req.limit or 20]]
        return res
    raise ValueError(
        f"trace QL needs WHERE {tid_tag} = '...' or ORDER BY <numeric tag>"
    )


def execute_property_ql(property_engine, req: QueryRequest) -> QueryResult:
    """Property QL: id equality / IN and tag-equality filters."""
    res = QueryResult()
    leaves = and_leaves(req)
    ids = None
    tag_filters = {}
    for c in leaves:
        if c.name == "id":
            if c.op == "eq":
                ids = [str(c.value)]
            elif c.op == "in":
                ids = [str(v) for v in c.value]
            else:
                raise ValueError("property id supports = / IN only")
        elif c.op == "eq":
            tag_filters[c.name] = c.value
        else:
            raise ValueError(f"property QL supports = on tags, got {c.op}")
    props = property_engine.query(
        req.groups[0],
        req.name,
        tag_filters=tag_filters or None,
        ids=ids,
        limit=req.limit or 100,
    )
    proj = set(req.tag_projection or ())
    res.data_points = [
        {
            "id": p.id,
            "tags": (
                {k: v for k, v in p.tags.items() if k in proj}
                if proj
                else p.tags
            ),
            "mod_revision": p.mod_revision,
        }
        for p in props
    ]
    return res
