"""Shared BydbQL executors for the trace and property catalogs.

One implementation serves both entry surfaces — the standalone bus server
(server.py TOPIC_QL) and the gRPC BydbQLService (api/grpc_server.py) —
the way the reference routes every catalog through one handler
(banyand/liaison/grpc/bydbql.go:143-173).  Measure and stream QL lower
onto their engines' query() directly; trace and property need the
catalog-specific planning below (trace-id lookup vs sidx-ordered scan,
id/tag filter splitting).
"""

from __future__ import annotations

from banyandb_tpu.api.model import QueryRequest, QueryResult


def and_leaves(req: QueryRequest):
    """Criteria leaves for catalogs whose executors take flat AND
    filters — OR trees are rejected rather than silently flattened
    (flattening an OR into AND returns wrong results)."""
    from banyandb_tpu.query.measure_exec import _lower_criteria

    leaves, expr = _lower_criteria(req.criteria)
    if expr:
        raise ValueError("OR criteria not supported for this catalog")
    return leaves


def span_matches(span: dict, conds) -> bool:
    for c in conds:
        v = span.get("tags", {}).get(c.name)
        if c.op == "eq":
            if v != c.value:
                return False
        elif c.op == "ne":
            if v == c.value:
                return False
        elif c.op == "in":
            if v not in c.value:
                return False
        elif c.op == "not_in":
            if v in c.value:
                return False
        elif c.op in ("gt", "ge", "lt", "le"):
            if v is None:
                return False
            try:
                fv, fc = float(v), float(c.value)
            except (TypeError, ValueError):
                return False
            if c.op == "gt" and not fv > fc:
                return False
            if c.op == "ge" and not fv >= fc:
                return False
            if c.op == "lt" and not fv < fc:
                return False
            if c.op == "le" and not fv <= fc:
                return False
        else:  # never silently match an op we can't evaluate
            raise ValueError(f"trace QL op {c.op!r} not supported")
    return True


def execute_trace_ql(trace_engine, req: QueryRequest, tracer=None) -> QueryResult:
    """Trace QL execution over the unified engine surface
    (TraceEngine.query and its cluster facades): general AND criteria
    (eq/ne/in/not_in, numeric ranges), SELECT projection, ORDER BY
    <numeric tag> asc/desc with LIMIT+OFFSET pushed into the sidx walk.
    OR trees and unknown ops are rejected up front so every engine —
    standalone, worker pool, liaison — refuses them identically instead
    of half-scattering."""
    for c in and_leaves(req):
        if c.op not in ("eq", "ne", "in", "not_in", "gt", "ge", "lt", "le"):
            raise ValueError(f"trace QL op {c.op!r} not supported")
    return trace_engine.query(req, tracer=tracer)


def execute_property_ql(property_engine, req: QueryRequest) -> QueryResult:
    """Property QL: id equality / IN and tag-equality filters."""
    res = QueryResult()
    leaves = and_leaves(req)
    ids = None
    tag_filters = {}
    for c in leaves:
        if c.name == "id":
            if c.op == "eq":
                ids = [str(c.value)]
            elif c.op == "in":
                ids = [str(v) for v in c.value]
            else:
                raise ValueError("property id supports = / IN only")
        elif c.op == "eq":
            tag_filters[c.name] = c.value
        else:
            raise ValueError(f"property QL supports = on tags, got {c.op}")
    props = property_engine.query(
        req.groups[0],
        req.name,
        tag_filters=tag_filters or None,
        ids=ids,
        limit=req.limit or 100,
    )
    proj = set(req.tag_projection or ())
    res.data_points = [
        {
            "id": p.id,
            "tags": (
                {k: v for k, v in p.tags.items() if k in proj}
                if proj
                else p.tags
            ),
            "mod_revision": p.mod_revision,
        }
        for p in props
    ]
    return res
