"""Logical plan trees over the fused-kernel executors.

Analog of the reference's logical planner (pkg/query/logical:
Plan/UnresolvedPlan interfaces, per-model analyzers building
IndexScan -> GroupBy/Agg -> Top -> Merge/Limit trees,
measure_analyzer.go:70 local / :170 distributed).  The TPU build keeps
execution fused — one jitted kernel per PlanSpec (measure_exec) is the
whole point — so the plan tree is the *decision and explanation* layer
above it:

- analyzers own the routing decisions that used to live inline in the
  engines (index-mode short-circuit, aggregate vs raw scan, order-by-
  index fork, TopN re-rank, distributed merge shape);
- every node renders into the in-band query trace (the reference
  returns plan strings in QueryResponse the same way);
- the leaves name the exact executor entry they lower onto, so the
  explain output is an honest description of what will run.

The tree deliberately does NOT re-implement row-operator execution: a
plan node's execute() calls the fused executor seam it describes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from banyandb_tpu.api.model import QueryRequest


@dataclasses.dataclass
class PlanNode:
    """One node: kind + human-readable props + children (logical/interface.go
    Plan analog; Children()/Schema() collapsed into this dataclass)."""

    kind: str
    props: dict = dataclasses.field(default_factory=dict)
    children: list["PlanNode"] = dataclasses.field(default_factory=list)
    # the executor closure this subtree lowers onto (leaf-bound; inner
    # nodes usually delegate to their child's executor)
    _execute: Optional[Callable] = None

    def explain(self, indent: int = 0) -> str:
        """Render the subtree (the reference's plan String() — these
        strings ride the in-band query trace)."""
        pad = "  " * indent
        props = ", ".join(
            f"{k}={v}" for k, v in self.props.items() if v not in ("", None, ())
        )
        line = f"{pad}{self.kind}" + (f" [{props}]" if props else "")
        return "\n".join(
            [line] + [c.explain(indent + 1) for c in self.children]
        )

    def execute(self, *a, **kw):
        node = self
        while node._execute is None:
            if not node.children:
                raise RuntimeError(f"plan node {self.kind} has no executor")
            node = node.children[0]
        return node._execute(*a, **kw)

    def find(self, kind: str) -> Optional["PlanNode"]:
        if self.kind == kind:
            return self
        for c in self.children:
            hit = c.find(kind)
            if hit is not None:
                return hit
        return None

    def leaf(self) -> "PlanNode":
        node = self
        while node.children:
            node = node.children[0]
        return node


def _time_props(req: QueryRequest) -> dict:
    tr = req.time_range
    return {"range": f"[{tr.begin_millis},{tr.end_millis})"}


def _criteria_summary(criteria) -> str:
    """Compact criteria rendering for explain output."""
    if criteria is None:
        return ""
    if hasattr(criteria, "op") and hasattr(criteria, "left"):  # LogicalExpression
        return (
            f"({_criteria_summary(criteria.left)} {criteria.op.upper()} "
            f"{_criteria_summary(criteria.right)})"
        )
    val = criteria.value
    if isinstance(val, (list, tuple)) and len(val) > 3:
        val = f"[{len(val)} values]"
    return f"{criteria.name} {criteria.op} {val!r}"


# -- measure ----------------------------------------------------------------


class _Conflict:
    """Sentinel: a criteria subtree whose entity-literal combinations
    are unsatisfiable (parseEntities nil in the reference)."""


_CONFLICT = _Conflict()


def check_entity_combinations(measure, criteria) -> None:
    """Reject criteria whose ENTITY-tag literal algebra is
    unsatisfiable (pkg/query/logical/parser.go parseEntities analog:
    the reference returns nil for conflicting AND-of-OR entity
    literals and fails the query; evaluating such a tree as plain mask
    algebra would instead scan and return rows).

    The algebra per subtree is a map {entity tag -> possible value
    set} (absent = unconstrained):

    - a leaf ``eq``/``in`` on an entity tag constrains that tag to its
      literal set; every other leaf is unconstrained;
    - AND intersects per-tag sets — an EMPTY intersection makes the
      subtree a conflict;
    - OR unions per-tag sets when both branches constrain a tag and
      drops the constraint otherwise; a conflicting branch poisons the
      OR (the reference's nil propagates up).

    Raises ValueError (→ INVALID_ARGUMENT on the wire) on conflict.
    """
    from banyandb_tpu.api.model import Condition, LogicalExpression

    entity = set(
        getattr(getattr(measure, "entity", None), "tag_names", ()) or ()
    )
    if criteria is None or not entity:
        return

    def lit_bytes(v):
        from banyandb_tpu.query.measure_exec import _tag_value_bytes

        try:
            return _tag_value_bytes(v)
        except TypeError:
            return None

    def walk(node):
        if node is None:
            return {}
        if isinstance(node, Condition):
            if node.name in entity and node.op == "eq":
                b = lit_bytes(node.value)
                return {} if b is None else {node.name: {b}}
            if node.name in entity and node.op == "in":
                vals = {lit_bytes(v) for v in node.value}
                vals.discard(None)
                return {node.name: vals} if vals else {}
            return {}
        assert isinstance(node, LogicalExpression), node
        left, right = walk(node.left), walk(node.right)
        if node.op == "and":
            if left is _CONFLICT or right is _CONFLICT:
                return _CONFLICT
            out = dict(left)
            for tag, vals in right.items():
                if tag in out:
                    inter = out[tag] & vals
                    if not inter:
                        return _CONFLICT
                    out[tag] = inter
                else:
                    out[tag] = vals
            return out
        # or
        if left is _CONFLICT or right is _CONFLICT:
            return _CONFLICT
        out = {}
        for tag in set(left) & set(right):
            out[tag] = left[tag] | right[tag]
        return out

    if walk(criteria) is _CONFLICT:
        raise ValueError(
            "unsatisfiable entity criteria: conflicting entity-tag "
            "literals under AND (no entity combination can match)"
        )


def analyze_measure(measure, req: QueryRequest, *, execute=None) -> PlanNode:
    """Local measure plan (measure_analyzer.go:70 Analyze analog).

    Owns the routing decisions: index-mode short-circuit (query.go:506),
    aggregate pipeline vs raw projection scan, TopN re-rank — and the
    reference's entity-combination rejection (conflicting entity
    literals raise before anything executes).
    execute: closure the leaf lowers onto (engine-provided).
    """
    check_entity_combinations(measure, req.criteria)
    if getattr(measure, "index_mode", False):
        scan = PlanNode(
            "IndexModeScan",
            {
                "measure": f"{measure.group}.{measure.name}",
                **_time_props(req),
                "criteria": _criteria_summary(req.criteria),
                "via": "series_index.SearchWithoutSeries",
            },
            _execute=execute,
        )
    else:
        scan = PlanNode(
            "IndexScan",
            {
                "measure": f"{measure.group}.{measure.name}",
                **_time_props(req),
                "criteria": _criteria_summary(req.criteria),
                "projection": ",".join(
                    (*req.tag_projection, *req.field_projection)
                ),
                "via": "parts+memtables -> device chunk",
            },
            _execute=execute,
        )
    root = scan
    # top WITHOUT group-by/agg ranks raw data points by field value
    # (measure_top.go row-level top) — a raw scan concern, not the
    # grouped kernel's
    if req.agg or req.group_by:
        root = PlanNode(
            "GroupByAggregate",
            {
                "group_by": ",".join(req.group_by.tag_names)
                if req.group_by
                else "",
                "agg": f"{req.agg.function}({req.agg.field_name})"
                if req.agg
                else "",
                "kernel": "fused jit PlanSpec (mixed-radix keys, "
                "group_reduce auto)",
            },
            children=[root],
        )
        if req.top:
            root = PlanNode(
                "Top",
                {
                    "n": req.top.number,
                    "field": req.top.field_name,
                    "sort": req.top.field_value_sort,
                    "kernel": "device top-k",
                },
                children=[root],
            )
    else:
        order = (
            f"index:{req.order_by_tag} {req.order_by_dir}"
            if req.order_by_tag
            else (f"ts {req.order_by_ts}" if req.order_by_ts else "")
        )
        if order:
            root = PlanNode("Sort", {"order": order}, children=[root])
    if req.offset or req.limit:
        root = PlanNode(
            "OffsetLimit",
            {"offset": req.offset, "limit": req.limit},
            children=[root],
        )
    return root


def analyze_measure_distributed(
    measure, req: QueryRequest, nodes: list[str], *, execute=None
) -> PlanNode:
    """Distributed plan (measure_analyzer.go:170 DistributedAnalyze +
    measure_plan_distributed.go:296 Broadcast): scatter the local plan to
    every data node, combine partials at the liaison."""
    local = analyze_measure(measure, req)
    return PlanNode(
        "DistributedMerge",
        {
            "nodes": len(nodes),
            "fan_out": ",".join(sorted(nodes)[:8]),
            "combine": "host combine_partials (f64 Kahan)",
            "replica_dedup": "version-max per (series, ts)",
        },
        children=[local],
        _execute=execute,
    )


# -- stream -----------------------------------------------------------------


def analyze_stream(stream, req: QueryRequest, *, execute=None) -> PlanNode:
    """Stream plan (stream_analyzer.go:50,103): the analyzer picks the
    element-index path vs the order-by-index fork."""
    scan = PlanNode(
        "ElementScan",
        {
            "stream": f"{stream.group}.{stream.name}",
            **_time_props(req),
            "criteria": _criteria_summary(req.criteria),
            "via": "element index (TYPE_INVERTED) + skipping blooms "
            "(TYPE_SKIPPING) -> device mask",
        },
        _execute=execute,
    )
    if req.order_by_tag:
        root = PlanNode(
            "SortByIndex",
            {"tag": req.order_by_tag, "dir": req.order_by_dir},
            children=[scan],
        )
    else:
        root = PlanNode(
            "Sort", {"order": f"ts {req.order_by_ts or 'desc'}"}, children=[scan]
        )
    return PlanNode(
        "OffsetLimit",
        {"offset": req.offset, "limit": req.limit},
        children=[root],
    )


# -- trace ------------------------------------------------------------------


def analyze_trace(
    trace_schema,
    *,
    trace_id: str = "",
    order_by_key: bool = False,
    limit: int = 0,
    execute=None,
) -> PlanNode:
    """Trace plan (trace_analyzer.go:35,104): trace-id point lookup rides
    the part-level bloom; ordered retrieval rides the sidx key ranges."""
    if trace_id:
        scan = PlanNode(
            "TraceIDScan",
            {
                "trace": f"{trace_schema.group}.{trace_schema.name}",
                "trace_id": trace_id,
                "via": "traceID.filter bloom -> span store",
            },
            _execute=execute,
        )
    else:
        scan = PlanNode(
            "SidxScan",
            {
                "trace": f"{trace_schema.group}.{trace_schema.name}",
                "order": "sidx key " + ("asc" if order_by_key else "desc"),
                "via": "sidx parts k-way merge (key-bound pruning)",
            },
            _execute=execute,
        )
    if limit:
        return PlanNode("Limit", {"n": limit}, children=[scan])
    return scan
