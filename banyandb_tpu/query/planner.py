"""Cost-based adaptive query planner + self-driving materialization.

Closes the loop between the observability plane and the execution plane
(ROADMAP item 5, the Enthuse adaptability thesis — PAPERS.md
arXiv 2405.18168): the engine has four ways to answer an aggregate
(streamagg window fold, serving-cache replay, zone-skipped fused scan,
full scan) and this module makes the CHOICE evidence-driven instead of
hardwired flag-priority.

Two cooperating halves:

1. **Cost-based scan planning** (``plan_scan`` / ``PlanDecision``,
   ``BYDB_PLANNER`` A/B flag, default on): before the gather, estimate
   per-part selectivity and surviving rows from metadata that is
   ALREADY in memory — per-block zone maps (tag local-code ranges +
   row counts, written at flush/merge since PR 9), per-part dictionary
   radices and per-part row counts — then

   - choose the group-by strategy through
     ``ops.groupby.select_group_method`` from the *estimated distinct
     group count* instead of the static radix product (the
     hash-vs-sort crossover of arXiv 2411.13245 keys on REAL group
     cardinality; a sparse cross product of two large dictionaries
     must hash, not sort),
   - pick the fused chunk schedule: the chunk-count bucket is rounded
     UP to the estimate's bucket (signature stability — a dashboard
     whose part population oscillates around a bucket boundary keeps
     ONE compiled program), and a part-batch whose *estimated*
     stacked footprint exceeds ``BYDB_FUSED_MAX_MB`` is routed
     straight to the staged loop,
   - skip the zone-map pre-pass entirely when estimated selectivity
     is ~1 (``ZONE_SKIP_MIN_SELECTIVITY``): lowering predicates onto
     every part dictionary and interval-checking every block is pure
     planner-path overhead when nothing will be skipped.

   Every decision is **result-preserving by construction**: group
   methods are bit-identical within the span bound (ops/groupby
   contract), a larger chunk bucket only adds fully-invalid padding
   chunks the host never absorbs, and the zone pre-pass only ever
   *removes reads of non-matching blocks* — so ``BYDB_PLANNER=0/1``
   result JSON is byte-identical (pinned across every builtin
   signature by tests/test_planner.py).  The decision + estimates ride
   the span tree (``planner`` span: ``path``, ``est_rows`` vs
   ``actual_rows``, ``est_groups``, ``group_method``,
   ``zone_prepass``) and ``planner_decisions_total{path}``.

2. **Auto-registration** (``AutoRegistrar``, the ``bydb-autoreg``
   loop, ``BYDB_AUTOREG`` flag): mines the query-signature evidence
   the obs plane already collects — the slowlog recorder's signature
   stats (every measure query, obs/recorder.SignatureStats) and the
   plan precompile registry's recorded (spec, measure-context, hits)
   population — for hot streamagg-ELIGIBLE signatures (pure-AND
   eq/ne/in/not_in predicates, group-by ⊆ key tags, covered
   aggregates) and registers materialized rolling windows for them
   through the same ``streamagg`` control surface operators use.
   Budgeted: at most ``BYDB_AUTOREG_MAX_SIGNATURES`` auto
   registrations and ``BYDB_AUTOREG_MAX_STATE_MB`` of estimated
   window-state memory; past either bound the least-recently-HIT auto
   signature is evicted first, and manual registrations are never
   auto-evicted.  Per-signature hit/age stats persist to
   ``<root>/autoreg.json`` so a restart resumes with yesterday's
   evidence instead of re-learning the dashboard population from
   scratch.  ``autoreg_signatures{source}`` gauges the split.

Everything here is host-side metadata work — the planner dispatches
ZERO device kernels by design (the streamagg-ingest host-only budget
exemption applies identically; pinned by
tests/test_planner.py::test_planner_path_is_host_only).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from banyandb_tpu.utils.envflag import env_flag, env_float, env_int

log = logging.getLogger("banyandb.planner")

# estimated-selectivity floor above which the zone-map pre-pass is
# skipped: when ~every block would survive anyway, the per-part dict
# lowering + per-block interval checks are pure overhead
ZONE_SKIP_MIN_SELECTIVITY = 0.9


def enabled() -> bool:
    """The cost-based-planning A/B flag (read per query so operators can
    flip it live; ``BYDB_PLANNER=0`` restores the pre-planner fixed
    thresholds — results byte-identical either way)."""
    return env_flag("BYDB_PLANNER", default=True)


def autoreg_enabled() -> bool:
    return env_flag("BYDB_AUTOREG", default=True)


# ---------------------------------------------------------------------------
# Conjunctive-predicate lowering (shared with the zone-skip gather path)
# ---------------------------------------------------------------------------
# Moved here from models/measure so the planner (query layer) never
# imports upward into the engines layer; models/measure re-exports them.


def conjunctive_eq_conditions(req):
    """[(tag, [byte values])] from eq/in conditions that are REQUIRED
    (pure-AND criteria tree).  Any OR anywhere disables zone pruning —
    a disjunct must never skip blocks its sibling could match."""
    from banyandb_tpu.query import measure_exec

    try:
        conds = measure_exec._collect_conditions(req.criteria)
    except NotImplementedError:
        return []
    out = []
    for c in conds:
        try:
            if c.op == "eq":
                out.append((c.name, [measure_exec._tag_value_bytes(c.value)]))
            elif c.op == "in":
                out.append(
                    (c.name, [measure_exec._tag_value_bytes(v) for v in c.value])
                )
        except TypeError:
            continue  # unsupported literal type: no pruning on this cond
    return out


def part_zone_preds(part, zone_conds) -> list:
    """Lower conjunctive eq/in tag conditions onto ONE part's local
    dictionary -> zone_preds for select_blocks.

    The zone maps store per-block LOCAL code ranges, so each predicate
    value resolves to this part's local code first.  A part whose
    dictionary holds NONE of a required predicate's values cannot match
    at all — expressed as an EMPTY allowed set, which marks every block
    (select_blocks still applies the dedup-safety overlap check before
    any block actually skips).  A tag column absent from the part
    entirely means every row carries the implicit empty value, so only
    an explicit empty-value predicate can match.
    """
    import numpy as np

    if not zone_conds:
        return []
    none_match = [("*", np.zeros(0, dtype=np.int64))]
    preds: list = []
    part_tags = set(part.meta.get("tags", ()))
    for name, values in zone_conds:
        if name not in part_tags:
            # schema evolution: rows carry the empty value for this tag
            if b"" not in values:
                return none_match
            continue
        lut = part.dict_index(name)  # cached reverse map
        codes = sorted({lut[v] for v in values if v in lut})
        if not codes:
            return none_match
        preds.append((f"tag_{name}", np.asarray(codes, dtype=np.int64)))
    return preds


# ---------------------------------------------------------------------------
# Cost model: scan estimation from on-disk metadata already in memory
# ---------------------------------------------------------------------------


@dataclass
class ScanEstimate:
    """Pre-gather estimate for one aggregate scan."""

    rows: int = 0  # candidate rows in range (post series/time pruning)
    scan_rows: int = 0  # est rows the gather will materialize (zone pass)
    surviving_rows: int = 0  # est rows surviving predicates
    groups: int = 1  # est distinct group count
    static_groups: int = 1  # the radix product the executor would use
    bytes: int = 0  # est surviving column bytes shipped
    selectivity: float = 1.0  # surviving_rows / rows
    parts: int = 0
    blocks: int = 0
    zone_markable_rows: int = 0  # rows in blocks the zone maps can prove away


@dataclass
class PlanDecision:
    """The planner's execution hints for one query.  Every field is a
    RESULT-PRESERVING refinement (see module docstring); ``None`` /
    default means "keep the executor's own choice"."""

    est: ScanEstimate = field(default_factory=ScanEstimate)
    path: str = "scan"  # materialized | fused | staged | raw
    group_method: Optional[str] = None  # select_group_method override
    zone_prepass: bool = True  # lower zone preds + run the block pre-pass
    chunk_bucket: Optional[int] = None  # min fused chunk-count bucket
    prefer_staged: bool = False  # est footprint exceeds the fused budget
    actual_rows: Optional[int] = None  # written back by compute_partials

    def span_tags(self, span) -> None:
        if span is None:
            return
        e = self.est
        # est_rows predicts what the gather materializes (time + zone
        # pruning) — directly comparable with the actual_rows written
        # back by compute_partials; the predicate-surviving estimate
        # rides separately as est_surviving
        span.tag("path", self.path).tag("est_rows", int(e.scan_rows)).tag(
            "est_surviving", int(e.surviving_rows)
        ).tag(
            "est_groups", int(e.groups)
        ).tag("selectivity", round(e.selectivity, 4)).tag(
            "zone_prepass", bool(self.zone_prepass)
        ).tag("group_method", self.group_method or "auto").tag(
            "parts", e.parts
        )


def _part_pred_selectivity(part, zone_conds) -> float:
    """Within-part match fraction for conjunctive eq/in predicates,
    from dictionary coverage: |predicate values present in the part
    dict| / dict size per predicate, multiplied (independence).  A
    value missing from every dict makes the part unmatchable (0.0)."""
    sel = 1.0
    part_tags = set(part.meta.get("tags", ()))
    for name, values in zone_conds:
        if name not in part_tags:
            # schema evolution: all rows carry the empty value
            sel *= 1.0 if b"" in values else 0.0
            continue
        idx = part.dict_index(name)
        if not idx:
            sel *= 0.0
            continue
        hit = sum(1 for v in values if v in idx)
        sel *= min(hit / max(len(idx), 1), 1.0)
    return max(min(sel, 1.0), 0.0)


def _part_zone_rows(part, begin_ms: int, end_ms: int, zone_conds) -> tuple:
    """(candidate_rows, zone_surviving_rows, blocks) for one part: rows
    in blocks overlapping the time range, and rows in the subset of
    those blocks whose zone maps admit every predicate (the dedup-
    safety gate can only KEEP more — this is the optimistic skip
    estimate, which is exactly what a cost model wants)."""
    cand = surv = blocks = 0
    preds = part_zone_preds(part, zone_conds) if zone_conds else []
    for b in part.blocks:
        if not (b["min_ts"] < end_ms and begin_ms <= b["max_ts"]):
            continue
        cnt = int(b["count"])
        cand += cnt
        blocks += 1
        zones = b.get("zones")
        keep = True
        if preds and zones:
            import numpy as np

            for col, allowed in preds:
                if not len(allowed):
                    keep = False
                    break
                z = zones.get(col)
                if z is None:
                    continue
                lo, hi = z
                j = int(np.searchsorted(allowed, lo))
                if j >= len(allowed) or allowed[j] > hi:
                    keep = False
                    break
        elif preds and not zones:
            keep = True  # pre-upgrade part: never skippable
        if keep:
            surv += cnt
    return cand, surv, blocks


def estimate_scan(engine, db, m, req) -> ScanEstimate:
    """Walk segment/shard/part METADATA (no column reads, no locks
    beyond the part-list snapshot) and estimate the scan.

    Inputs are all already resident: the per-part block index
    (``Part.blocks`` incl. zone maps), per-part dictionaries
    (``dict_index``, cached), memtable row counts."""
    est = ScanEstimate()
    zone_conds = conjunctive_eq_conditions(req)
    begin = req.time_range.begin_millis
    end = req.time_range.end_millis
    group_tags = tuple(req.group_by.tag_names) if req.group_by else ()
    # per group tag: union cardinality is unknown pre-gather; the SUM of
    # per-part dict sizes is an upper bound that stays tight for the
    # dashboard shape (parts of one measure share value populations, so
    # we also track the per-part MAX as the optimistic bound and take
    # the geometric middle)
    tag_sum = {t: 0 for t in group_tags}
    tag_max = {t: 1 for t in group_tags}
    scan_rows_total = 0  # rows surviving the zone pass (gather size)
    zone_surv_total = 0  # ... further scaled by predicate selectivity
    for seg in db.select_segments(begin, end):
        for shard in seg.shards:
            for mem_cols in shard.hot_columns(m.name):
                n = int(mem_cols.ts.size)
                est.rows += n
                scan_rows_total += n  # memtable rows never zone-skip
                zone_surv_total += n
                for t in group_tags:
                    col = mem_cols.tags.get(t)
                    d = mem_cols.dicts.get(t) if col is not None else None
                    sz = len(d) if d is not None else 1
                    tag_sum[t] += sz
                    tag_max[t] = max(tag_max[t], sz)
            for part in shard.parts:
                if part.meta.get("measure") != m.name:
                    continue
                cand, zone_surv, blocks = _part_zone_rows(
                    part, begin, end, zone_conds
                )
                if cand == 0:
                    continue
                est.parts += 1
                est.blocks += blocks
                est.rows += cand
                sel = (
                    _part_pred_selectivity(part, zone_conds)
                    if zone_conds
                    else 1.0
                )
                scan_rows_total += zone_surv if zone_conds else cand
                zone_surv_total += int(zone_surv * sel) if zone_conds else cand
                for t in group_tags:
                    sz = len(part.dict_for(t)) or 1
                    tag_sum[t] += sz
                    tag_max[t] = max(tag_max[t], sz)
    est.scan_rows = min(scan_rows_total, est.rows)
    est.surviving_rows = min(zone_surv_total, est.rows)
    est.zone_markable_rows = est.rows - est.scan_rows
    est.selectivity = (
        est.surviving_rows / est.rows if est.rows else 1.0
    )
    static = 1
    groups = 1
    for t in group_tags:
        # geometric middle of [per-part max, cross-part sum]: the union
        # is at least the largest single dictionary and at most the sum
        hi = max(tag_sum[t], 1)
        lo = tag_max[t]
        static *= hi
        groups *= int(max((lo * hi) ** 0.5, 1))
    est.static_groups = static
    # distinct groups can never exceed surviving rows
    est.groups = max(min(groups, max(est.surviving_rows, 1)), 1)
    # ship bytes: 4 B/row per column (i32 codes / f32 fields) over the
    # predicate+group tag set and the aggregate field, sized by what
    # the gather will actually materialize (predicates mask on device,
    # they don't shrink the ship) — the planner only needs the ORDER
    # of magnitude for the fused-footprint call
    ncols = 4 + len(
        {c for c, _ in zone_conds} | set(group_tags)
    ) + 1
    est.bytes = est.scan_rows * 4 * ncols
    return est


def plan_scan(engine, db, m, req, span=None) -> Optional[PlanDecision]:
    """The cost-based pre-gather decision for one aggregate query; None
    when the planner flag is off (executors keep their fixed-threshold
    behavior).  Tags the ``planner`` span and counts the decision."""
    if not enabled():
        return None
    from banyandb_tpu import ops
    from banyandb_tpu.query import measure_exec

    est = estimate_scan(engine, db, m, req)
    d = PlanDecision(est=est)

    # zone pre-pass: skip when the maps cannot prove enough away — the
    # relevant fraction is what the BLOCK pass could remove (scan_rows),
    # not within-block predicate selectivity (which only the kernel's
    # mask applies)
    zone_frac = est.scan_rows / est.rows if est.rows else 1.0
    d.zone_prepass = zone_frac < ZONE_SKIP_MIN_SELECTIVITY

    # group-by strategy from ESTIMATED distinct groups: only override
    # when the estimate lands on the other side of the crossover from
    # the static radix product (otherwise keep "auto" so the plan
    # signature — and with it the jit/precompile/budget population —
    # stays exactly the pre-planner one)
    nrows_guess = min(
        max(est.scan_rows, 1), measure_exec.SCAN_CHUNK
    )
    static_method = ops.groupby.select_group_method(
        nrows_guess, max(est.static_groups, 1)
    )
    est_method = ops.groupby.select_group_method(
        nrows_guess, est.groups
    )
    if est_method != static_method:
        d.group_method = est_method

    # fused chunk schedule from estimated surviving bytes
    from banyandb_tpu.query import fused_exec

    est_chunks = max(
        -(-max(est.scan_rows, 1) // measure_exec.SCAN_CHUNK), 1
    )
    d.chunk_bucket = fused_exec.chunk_count_bucket(est_chunks)
    d.prefer_staged = (
        est.bytes > fused_exec.max_fused_mb() * (1 << 20)
    )
    d.path = "staged" if d.prefer_staged else "fused"
    d.span_tags(span)
    return d


def record_decision(path: str) -> None:
    """``planner_decisions_total{path}``: one increment per planned
    query, path ∈ materialized|fused|staged|raw|off."""
    from banyandb_tpu.obs import metrics as obs_metrics

    obs_metrics.global_meter().counter_add(
        "planner_decisions", 1.0, {"path": path}
    )


# ---------------------------------------------------------------------------
# Streamagg eligibility: one shape test shared by mining surfaces
# ---------------------------------------------------------------------------

_COVERED_OPS = ("eq", "ne", "in", "not_in")
_COVERED_AGGS = ("count", "sum", "mean", "min", "max")


def signature_of(req) -> Optional[tuple]:
    """(group, measure, key_tags, fields) when `req` is a streamagg-
    ELIGIBLE aggregate (pure-AND eq/ne/in/not_in predicates, group-by
    tags only, covered aggregate, no percentile/OR/order-by-tag), else
    None.  The registration itself re-validates against the schema —
    this is the cheap mining-side shape test."""
    from banyandb_tpu.query import measure_exec

    if not req.groups or not req.name:
        return None
    if req.group_by is not None and req.group_by.field_name:
        return None
    agg = req.agg
    if agg is not None and agg.function not in _COVERED_AGGS:
        return None
    if agg is None and not req.top:
        return None  # raw-row queries have no fold
    try:
        conds, expr = measure_exec._lower_criteria(req.criteria)
    except (ValueError, NotImplementedError):
        return None
    if expr:
        return None
    for c in conds:
        if c.op not in _COVERED_OPS:
            return None
    group_tags = tuple(req.group_by.tag_names) if req.group_by else ()
    key_tags = tuple(
        sorted(set(group_tags) | {c.name for c in conds})
    )
    fields: set = set()
    if agg:
        fields.add(agg.field_name)
    if req.top:
        fields.add(req.top.field_name)
    if not key_tags or not fields:
        return None
    return (req.groups[0], req.name, key_tags, tuple(sorted(fields)))


def signature_from_spec(spec, context) -> Optional[tuple]:
    """The plan-registry twin of :func:`signature_of`: derive an
    eligible (group, measure, key_tags, fields) from a recorded measure
    ``PlanSpec`` plus its (group, measure) context."""
    if context is None:
        return None
    group, measure = context
    if spec.hist_field or spec.expr:
        return None
    for p in spec.preds:
        if p.kind != "code" or p.op not in _COVERED_OPS:
            return None
    key_tags = tuple(
        sorted(set(spec.group_tags) | {p.name for p in spec.preds})
    )
    if not key_tags or not spec.fields:
        return None
    return (group, measure, key_tags, tuple(spec.fields))


# ---------------------------------------------------------------------------
# Auto-registration: the bydb-autoreg loop
# ---------------------------------------------------------------------------


def autoreg_max_signatures() -> int:
    return env_int("BYDB_AUTOREG_MAX_SIGNATURES", 8)


def autoreg_max_state_mb() -> int:
    return env_int("BYDB_AUTOREG_MAX_STATE_MB", 64)


def autoreg_interval_s() -> float:
    return env_float("BYDB_AUTOREG_INTERVAL_S", 2.0)


def autoreg_min_hits() -> int:
    """Evidence threshold: a signature registers once it has been asked
    this many times (a dashboard refreshing every few seconds crosses
    it within one autoreg interval)."""
    return env_int("BYDB_AUTOREG_MIN_HITS", 3)


def autoreg_backoff_s() -> float:
    """Base re-registration backoff after a budget eviction (doubles
    per repeated eviction of the same signature, capped at one hour):
    a signature whose window state blows the MB budget must not
    register-evict-register every tick while its queries keep
    generating evidence."""
    return env_float("BYDB_AUTOREG_BACKOFF_S", 60.0)


# estimated bytes per materialized window STATE (acc list + key tuple +
# interning overhead), used for the MB budget — deliberately
# conservative (CPython list-of-floats + tuple + dict slots)
_STATE_BYTES = 640


class AutoRegistrar:
    """The ``bydb-autoreg`` background loop.

    Dependency-injected so every serving topology reuses it: the server
    passes ``register_fn``/``unregister_fn`` that route through its own
    ``streamagg`` control surface (engine-direct standalone, broadcast
    in worker-pool mode) and ``stats_fn`` returning the live
    ``StreamAggRegistry.stats()['signatures']`` rows (which carry
    hits / last-hit / state counts / origin).

    Evidence sources (mined each tick):
    - ``sig_stats`` — obs/recorder.SignatureStats, fed by the server's
      query epilogue (the slowlog plane: every measure query, not just
      slow ones, with slow queries double-weighted);
    - the plan precompile registry's recorded signatures + measure
      contexts (``evidence()``), covering embedded/engine-level
      callers that never cross a server epilogue.

    State (``<root>/autoreg.json``): per-signature cumulative hits,
    first/last-seen wall ms, and which signatures THIS loop registered
    (the auto set) — so a restart neither re-learns from zero nor
    mistakes a manual registration for its own.
    """

    def __init__(
        self,
        store_path,
        *,
        sig_stats=None,
        register_fn: Callable[[str, str, tuple, tuple], dict],
        unregister_fn: Callable[[str, str, tuple, tuple], bool],
        stats_fn: Callable[[], list],
        plan_registry=None,
        interval_s: Optional[float] = None,
    ):
        self.store = Path(store_path)
        self.sig_stats = sig_stats
        self.register_fn = register_fn
        self.unregister_fn = unregister_fn
        self.stats_fn = stats_fn
        self.plan_registry = plan_registry
        self.interval_s = (
            interval_s if interval_s is not None else autoreg_interval_s()
        )
        self._lock = threading.Lock()
        # sig key (group, measure, key_tags, fields) -> evidence record
        self._hits: dict[tuple, dict] = {}
        self._auto: set[tuple] = set()  # signatures THIS loop registered
        self._last_counts: dict[tuple, int] = {}  # mining deltas
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.registered_total = 0
        self.evicted_total = 0
        self.errors = 0
        self._load()

    # -- persistence ---------------------------------------------------------
    @staticmethod
    def _key_to_json(key: tuple) -> dict:
        g, m, tags, fields = key
        return {
            "group": g,
            "measure": m,
            "key_tags": list(tags),
            "fields": list(fields),
        }

    @staticmethod
    def _key_from_json(d: dict) -> tuple:
        return (
            d["group"],
            d["measure"],
            tuple(d["key_tags"]),
            tuple(d["fields"]),
        )

    def _load(self) -> None:
        try:
            if not self.store.exists():
                return
            doc = json.loads(self.store.read_text())
        except (OSError, ValueError):
            return
        with self._lock:
            for rec in doc.get("signatures", []):
                try:
                    key = self._key_from_json(rec)
                except KeyError:
                    continue
                self._hits[key] = {
                    "hits": int(rec.get("hits", 0)),
                    "first_ms": int(rec.get("first_ms", 0)),
                    "last_ms": int(rec.get("last_ms", 0)),
                }
                for extra in ("evictions", "backoff_until_ms"):
                    if rec.get(extra):
                        self._hits[key][extra] = int(rec[extra])
                if rec.get("auto"):
                    self._auto.add(key)

    def _save_locked(self) -> None:
        doc = {
            "signatures": [
                {
                    **self._key_to_json(key),
                    **rec,
                    "auto": key in self._auto,
                }
                for key, rec in self._hits.items()
            ]
        }
        try:
            self.store.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.store.with_suffix(".tmp")
            tmp.write_text(json.dumps(doc, indent=1))
            import os

            os.replace(tmp, self.store)
        except OSError:
            pass  # evidence persistence is an optimization

    # -- mining --------------------------------------------------------------
    def _note(self, key: tuple, hits: int, now_ms: int) -> None:
        rec = self._hits.get(key)
        if rec is None:
            rec = self._hits[key] = {
                "hits": 0, "first_ms": now_ms, "last_ms": now_ms,
            }
        rec["hits"] += hits
        rec["last_ms"] = now_ms

    def _note_evicted(self, key: tuple) -> None:
        """Stamp an eviction: the signature re-registers only after an
        exponential backoff (doubling per eviction, 1 h cap) — without
        it, a budget-blowing signature whose queries keep generating
        evidence would register-evict-register every tick."""
        with self._lock:
            rec = self._hits.get(key)
            if rec is None:
                return
            n = int(rec.get("evictions", 0)) + 1
            rec["evictions"] = n
            backoff_ms = min(
                autoreg_backoff_s() * (2 ** (n - 1)), 3600.0
            ) * 1000.0
            rec["backoff_until_ms"] = int(
                time.time() * 1000 + backoff_ms
            )

    def mine(self) -> None:
        """Fold fresh evidence from both obs surfaces into the hit
        table (delta-based: each source's cumulative counters are
        diffed against the last tick)."""
        now_ms = int(time.time() * 1000)
        with self._lock:
            if self.sig_stats is not None:
                for key, count in self.sig_stats.snapshot().items():
                    prev = self._last_counts.get(("sig", key), 0)
                    if count > prev:
                        self._note(key, count - prev, now_ms)
                        self._last_counts[("sig", key)] = count
            if self.plan_registry is not None:
                for kind, spec, count, ctx in self.plan_registry.evidence():
                    if kind != "measure":
                        continue
                    key = signature_from_spec(spec, ctx)
                    if key is None:
                        continue
                    prev = self._last_counts.get(("plan", key), 0)
                    if count > prev:
                        self._note(key, count - prev, now_ms)
                        self._last_counts[("plan", key)] = count

    # -- budget --------------------------------------------------------------
    def _live_by_key(self) -> dict:
        """Current registry rows keyed by signature tuple."""
        out = {}
        for row in self.stats_fn() or ():
            key = (
                row.get("group"),
                row.get("measure"),
                tuple(row.get("key_tags", ())),
                tuple(row.get("fields", ())),
            )
            out[key] = row
        return out

    def _enforce_budget(self, live: dict) -> None:
        """Evict least-recently-hit AUTO signatures past either bound.
        Manual registrations (rows whose key this loop never
        registered) are never touched.

        Budgets are PER TENANT (docs/robustness.md "Multi-tenant QoS"):
        each tenant gets the full signature-count and state-MB
        allowance over its own groups, so one tenant's churn can never
        evict another tenant's materialized windows.  A single-tenant
        deployment — every group in the default tenant — degenerates to
        exactly the old global budget."""
        from banyandb_tpu.qos.tenancy import tenant_of_group

        by_tenant: dict[str, list] = {}
        for key, row in live.items():
            if key in self._auto:
                by_tenant.setdefault(tenant_of_group(key[0]), []).append(
                    (key, row)
                )
        max_n = autoreg_max_signatures()
        max_bytes = autoreg_max_state_mb() * (1 << 20)

        def lru_order(kr):
            row = kr[1]
            return (row.get("last_hit_ms") or 0, row.get("hits") or 0)

        for auto_rows in by_tenant.values():
            # only AUTO signatures' window states count against the
            # autoreg budget: a large MANUAL registration is the
            # operator's own memory decision and must not starve auto
            # materialization (only auto signatures are ever evicted)
            total_states = sum(
                int(r.get("states", 0)) for _k, r in auto_rows
            )
            auto_rows.sort(key=lru_order)
            while auto_rows and (
                len(auto_rows) > max_n
                or total_states * _STATE_BYTES > max_bytes
            ):
                key, row = auto_rows.pop(0)
                try:
                    if self.unregister_fn(*key):
                        self.evicted_total += 1
                        total_states -= int(row.get("states", 0))
                        with self._lock:
                            self._auto.discard(key)
                        self._note_evicted(key)
                        log.info(
                            "autoreg: evicted %s/%s%s (budget)",
                            key[0], key[1], list(key[2]),
                        )
                except Exception:  # noqa: BLE001 — must not kill the loop
                    self.errors += 1
                    return

    # -- the tick ------------------------------------------------------------
    def _make_room(
        self, live: dict, cand_last_ms: int, tenant: str = ""
    ) -> bool:
        """Displace the least-recently-HIT auto signature for a new
        candidate — only when that victim is actually COLDER than the
        candidate's evidence (a dashboard whose windows serve every
        refresh keeps a fresh last-hit and is never displaced by a
        one-off).  Manual registrations are never touched; victims come
        from the CANDIDATE'S OWN tenant only (per-tenant budget
        partitions — one tenant's hot pattern never displaces
        another's)."""
        from banyandb_tpu.qos.tenancy import tenant_of_group

        rows = sorted(
            (
                (k, live[k])
                for k in live
                if k in self._auto
                and (not tenant or tenant_of_group(k[0]) == tenant)
            ),
            key=lambda kr: (
                kr[1].get("last_hit_ms") or 0,
                kr[1].get("hits") or 0,
            ),
        )
        if not rows:
            return False
        victim, vrow = rows[0]
        if (vrow.get("last_hit_ms") or 0) >= cand_last_ms:
            return False  # everything live is hotter than the candidate
        try:
            if not self.unregister_fn(*victim):
                return False
        except Exception:  # noqa: BLE001
            self.errors += 1
            return False
        self.evicted_total += 1
        live.pop(victim, None)
        with self._lock:
            self._auto.discard(victim)
        self._note_evicted(victim)
        log.info(
            "autoreg: evicted %s/%s%s (lru, making room)",
            victim[0], victim[1], list(victim[2]),
        )
        return True

    def tick(self) -> int:
        """One mine → register → budget round; -> registrations made."""
        self.mine()
        live = self._live_by_key()
        min_hits = autoreg_min_hits()
        max_n = autoreg_max_signatures()
        made = 0
        now_ms = int(time.time() * 1000)
        with self._lock:
            candidates = sorted(
                (
                    (key, rec)
                    for key, rec in self._hits.items()
                    if key not in live
                    and rec["hits"] >= min_hits
                    and now_ms >= rec.get("backoff_until_ms", 0)
                ),
                key=lambda kr: -kr[1]["hits"],
            )
        from banyandb_tpu.qos.tenancy import tenant_of_group

        for key, rec in candidates:
            # per-tenant count: the cap applies within the candidate's
            # tenant, not across the whole node
            tenant = tenant_of_group(key[0])
            n_auto = sum(
                1
                for k in live
                if k in self._auto and tenant_of_group(k[0]) == tenant
            )
            if n_auto >= max_n and not self._make_room(
                live, rec["last_ms"], tenant
            ):
                continue
            try:
                info = self.register_fn(*key)
            except Exception as e:  # noqa: BLE001 — a stale/invalid
                # signature (dropped measure, renamed tag, index-mode)
                # must not wedge the loop; forget it so it cannot retry
                # forever
                self.errors += 1
                with self._lock:
                    self._hits.pop(key, None)
                log.info("autoreg: %s/%s rejected: %s", key[0], key[1], e)
                continue
            made += 1
            self.registered_total += 1
            with self._lock:
                self._auto.add(key)
            live[key] = info if isinstance(info, dict) else {}
            log.info(
                "autoreg: registered %s/%s keys=%s fields=%s "
                "(hits=%d)",
                key[0], key[1], list(key[2]), list(key[3]), rec["hits"],
            )
        if made:
            live = self._live_by_key()
        self._enforce_budget(live)
        self._export_gauges(live)
        with self._lock:
            self._save_locked()
        return made

    def _export_gauges(self, live: dict) -> None:
        from banyandb_tpu.obs import metrics as obs_metrics

        meter = obs_metrics.global_meter()
        n_auto = sum(1 for k in live if k in self._auto)
        meter.gauge_set(
            "autoreg_signatures", float(n_auto), {"source": "auto"}
        )
        meter.gauge_set(
            "autoreg_signatures",
            float(len(live) - n_auto),
            {"source": "manual"},
        )

    # -- lifecycle -----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                self.errors += 1
                log.exception("autoreg tick failed")
            self._wake.wait(self.interval_s)
            self._wake.clear()

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        t = threading.Thread(
            target=self._loop, name="bydb-autoreg", daemon=True
        )
        self._thread = t
        t.start()

    def poke(self) -> None:
        """Wake the loop now (tests / smoke scripts)."""
        self._wake.set()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None
        with self._lock:
            self._save_locked()

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": autoreg_enabled(),
                "known_signatures": len(self._hits),
                "auto_registered": len(self._auto),
                "registered_total": self.registered_total,
                "evicted_total": self.evicted_total,
                "errors": self.errors,
                "max_signatures": autoreg_max_signatures(),
                "max_state_mb": autoreg_max_state_mb(),
            }
